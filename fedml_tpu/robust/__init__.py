"""Byzantine-robust + private aggregation for the cross-device hot path.

``defense`` holds the server-side half: ``DefenseConfig`` (the knob
set), ``RobustAggregator`` (per-upload screening + per-connection
contribution caps + buffered robust close), and the connection-cap
water-filling math.  The defense FORMULAS themselves live in
``fedml_tpu.core.robust`` — one copy, polymorphic over np/jnp, shared
with the simulation layer's ``make_robust_transform`` hook.
"""

from fedml_tpu.robust.defense import (
    DEFENSES,
    DefenseConfig,
    RobustAggregator,
    cap_connection_weights,
)

__all__ = [
    "DEFENSES",
    "DefenseConfig",
    "RobustAggregator",
    "cap_connection_weights",
]
