"""Server-side robust aggregation: the cross-device defense module.

PR 10's virtual-client muxing made one physical connection speak for
thousands of virtual identities — a compromised muxer IS a Sybil
attack.  This module gives ``FedAvgServerManager`` the defenses the
simulation layer has had since the seed (``core/robust``), in two modes
sharing the ONE defense-math implementation:

- **streaming** (composes with the O(1) num/den fold): per-upload
  norm-difference clipping against the broadcast base, an
  outlier-score reject on arrival (``score = ||delta|| / norm_bound``;
  ``score > outlier_mult`` ⇒ rejected, counted
  ``faults.observed{kind=outlier_upload}`` — the non-finite firewall's
  norm-space twin), and per-CONNECTION contribution caps so no single
  physical conn (muxer) can exceed ``conn_cap`` of a round's total
  weight — the anti-Sybil lever muxing demands.  Screening is pure
  numpy on the host (xp=np through ``core.robust``): order-independent
  per-upload math, so defended same-seed runs stay digest-identical
  whatever the arrival interleaving.
- **buffered** (``median`` / ``trimmed_mean``): decoded uploads are
  buffered to the round close (still through the PR-8 decode-worker
  pool) and the params collection is replaced by the coordinate-wise
  robust center — ``core.robust.robust_center``, the same estimator
  ``make_robust_transform`` runs inside the compiled engine.

Client-level DP rides either mode: per-client delta clip (``dp_clip``)
+ Gaussian noise (``dp_noise``) drawn from the deterministic
``fold_in`` stream ``core.robust.agg_noise_key(seed, round, slot)`` —
the exact per-slot keys the engine's weak-DP hook uses, so DP runs are
bit-reproducible across processes and topologies.

Threat model honesty (also in README): the conn cap bounds what one
CONNECTION can contribute; an attacker who can open many connections
(conn-level Sybil) is outside this lever's reach — that requires
admission control above the hub.  And norm-clipping cannot detect a
sign-flipped update of honest magnitude; it only bounds its influence
— the buffered estimators are the defense for that shape of attack.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from fedml_tpu.core import robust as robustlib
from fedml_tpu.obs.telemetry import get_telemetry

PyTree = Any

DEFENSES = ("none", "streaming", "median", "trimmed_mean")


@dataclasses.dataclass(frozen=True)
class DefenseConfig:
    """Cross-device defense knobs (``distributed_fedavg --defense ...``).

    ``defense`` picks the aggregation mode; ``norm_bound`` /
    ``outlier_mult`` / ``conn_cap`` are streaming-mode levers;
    ``dp_clip`` / ``dp_noise`` (client-level DP) compose with any mode;
    ``trim_frac`` parametrizes ``trimmed_mean``.  Zero = off for every
    numeric knob.
    """

    defense: str = "none"
    norm_bound: float = 0.0   # streaming: clip ||delta|| to this bound
    outlier_mult: float = 0.0  # reject ||delta|| > mult * norm_bound
    conn_cap: float = 0.0     # max fraction of round weight per conn
    dp_clip: float = 0.0      # client-level DP: per-client delta clip
    dp_noise: float = 0.0     # client-level DP: gaussian sigma
    trim_frac: float = 0.2    # trimmed_mean: fraction trimmed per side

    def __post_init__(self):
        if self.defense not in DEFENSES:
            raise ValueError(
                f"unknown defense {self.defense!r} (one of {DEFENSES})"
            )
        if self.defense != "streaming" and (self.norm_bound > 0
                                            or self.outlier_mult > 0):
            # a bound without the mode would be silently inert — the
            # operator believes clipping is on and it is not
            raise ValueError(
                "norm_bound/outlier_mult are streaming-mode knobs; "
                f"set defense='streaming' (got {self.defense!r})"
            )
        if self.outlier_mult > 0 and self.norm_bound <= 0:
            raise ValueError(
                "outlier_mult needs norm_bound: the outlier score is "
                "||delta|| / norm_bound"
            )
        if self.conn_cap and not 0.0 < self.conn_cap < 1.0:
            raise ValueError(
                f"conn_cap must be a fraction in (0, 1): {self.conn_cap!r}"
            )
        if self.conn_cap > 0 and self.defense != "streaming":
            raise ValueError(
                "conn_cap is a streaming-mode lever (the buffered "
                "estimators are weight-free); set defense='streaming'"
            )
        if not 0.0 <= self.trim_frac < 0.5:
            raise ValueError(
                f"trim_frac must be in [0, 0.5): {self.trim_frac!r}"
            )
        if self.dp_noise > 0 and self.dp_clip <= 0:
            raise ValueError(
                "dp_noise without dp_clip is noise without a sensitivity "
                "bound — set dp_clip (the clip IS the DP guarantee)"
            )

    @property
    def enabled(self) -> bool:
        return (self.defense != "none" or self.dp_clip > 0
                or self.dp_noise > 0)

    @property
    def buffered(self) -> bool:
        return self.defense in ("median", "trimmed_mean")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def cap_connection_weights(
    weights: Dict[Any, float], cap: float
) -> Tuple[Dict[Any, float], bool]:
    """Per-connection weight scales enforcing ``w'_c ≤ cap · Σ w'``.

    Water-filling fixed point: sort connections by weight (descending,
    ties broken by key string for determinism), grow the capped set
    until every capped conn lands at EXACTLY ``cap`` of the rescaled
    total ``T = W_uncapped / (1 − k·cap)`` and no uncapped conn exceeds
    it.  Returns ``(scales, infeasible)``; infeasible (every connection
    would cap — cap < 1/C with near-equal weights, or a single-conn
    federation) leaves all scales at 1.0 and is the caller's to count:
    a cap that cannot be satisfied must be visible, never silently
    half-applied.
    """
    scales = {k: 1.0 for k in weights}
    live = {k: w for k, w in weights.items() if w > 0}
    if not 0.0 < cap < 1.0 or not live:
        return scales, False
    if len(live) == 1:
        # the most hostile shape: ONE connection carried the whole
        # round (everyone else missed the deadline) — its fraction is
        # 1 > cap by definition and no rescaling can change that.
        # Infeasible, loudly: robust.cap_infeasible is the signal.
        return scales, True
    items = sorted(live.items(), key=lambda kv: (-kv[1], str(kv[0])))
    n = len(items)
    # m = size of the capped prefix; at least one connection must stay
    # uncapped or the rescaled total collapses to zero (cap < 1/C with
    # near-equal weights — no assignment can satisfy the cap)
    for m in range(n):
        if 1.0 - m * cap <= 0.0:
            return scales, True
        w_uncapped = sum(w for _, w in items[m:])
        total = w_uncapped / (1.0 - m * cap)
        # stable iff the largest uncapped conn fits under the cap and
        # the smallest capped one genuinely needed capping
        if items[m][1] > cap * total:
            continue
        if m > 0 and items[m - 1][1] <= cap * total:
            continue
        for k, w in items[:m]:
            scales[k] = (cap * total) / w
        return scales, False
    return scales, True  # every conn would cap: infeasible


def _params_of(tree: PyTree) -> PyTree:
    """The clippable/noisable collection: ``tree['params']`` when the
    variables dict has one (flax convention — BN running stats stay
    outside, the reference's ``vectorize_weight`` exclusion), else the
    whole tree."""
    if isinstance(tree, dict) and "params" in tree:
        return tree["params"]
    return tree


def _with_params(tree: PyTree, new_params: PyTree) -> PyTree:
    if isinstance(tree, dict) and "params" in tree:
        return {**tree, "params": new_params}
    return new_params


def _stack1(params: PyTree) -> PyTree:
    """One client's params as a stacked [1, ...] fp32 numpy tree — the
    host-side screening runs the SAME stacked formulas the compiled
    transform runs, at K=1."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32)[None], params
    )


def _unstack1(stacked: PyTree, like: PyTree) -> PyTree:
    return jax.tree_util.tree_map(
        lambda s, l: np.asarray(s[0], np.asarray(l).dtype), stacked, like
    )


class RobustAggregator:
    """Per-federation defense state for ``FedAvgServerManager``.

    ``screen`` runs OUTSIDE the round lock on the decode path (pure
    numpy, O(model) — the same altitude as decode itself); the
    per-round counters it keeps are read + reset under ``note_round``
    at each close.  Connection attribution comes from the hub's
    ``conn_map`` introspection (``TcpBackend.request_conn_map``); nodes
    the map does not cover fall back to a per-node singleton connection
    — a v1 dialer IS its own physical conn.
    """

    _GUARDED_BY = {
        "_round_counts": "_lock",
        "_base_np_cache": "_lock",
    }

    def __init__(self, cfg: DefenseConfig, *, seed: int = 0):
        self.cfg = cfg
        self.seed = int(seed)
        self._seed_key = None  # built lazily: jax PRNGKey only if DP is on
        self._lock = threading.Lock()  # leaf lock: counters + caches
        self._round_counts = {"clipped": 0, "outliers": 0, "dp_noised": 0}
        self._conn_map: Dict[int, str] = {}
        self._conn_map_src = None  # identity of the last ingested raw map
        # (base object, fp32 numpy params) — see _base_np
        self._base_np_cache: Optional[tuple] = None

    # -- connection attribution ---------------------------------------------
    def set_conn_map(self, conns: Optional[dict]) -> None:
        """Ingest a hub ``conn_map`` reply: ``{cid: [node ids]}`` →
        node → ``conn<cid>``.  Identity-cached (the backend hands back
        the same parsed object until a fresh reply lands)."""
        if conns is None or conns is self._conn_map_src:
            return
        inv: Dict[int, str] = {}
        for cid, nodes in conns.items():
            for n in nodes:
                inv[int(n)] = f"conn{cid}"
        self._conn_map = inv
        self._conn_map_src = conns

    def conn_key(self, sender: int) -> str:
        return self._conn_map.get(int(sender), f"node{sender}")

    # -- per-upload screening (outside the round lock) ----------------------
    def screen(self, variables: PyTree, base: PyTree, *, round_idx: int,
               slot: int) -> Tuple[Optional[PyTree], dict]:
        """Clip / outlier-score / DP-transform ONE decoded upload
        against the broadcast base.  Returns ``(variables, flags)`` —
        the (possibly transformed) tree, or ``None`` for an outlier
        reject (the caller counts it through the standard reject path,
        never silently).  ``flags`` says what WOULD be counted
        (clipped/dp_noised); the caller feeds it to ``note_upload``
        AFTER its duplicate check, so a chaos-redelivered copy's screen
        work never double-counts the defense telemetry."""
        cfg = self.cfg
        tel = get_telemetry()
        flags = {"clipped": False, "dp_noised": False}
        params = _params_of(variables)
        base_params = _params_of(base)
        stacked = _stack1(params)
        base_np = self._base_np(base, base_params)
        norm = float(robustlib.param_delta_norms(
            base_np, stacked, xp=np
        )[0])
        tel.observe("robust.upload_norm", norm)
        if (cfg.outlier_mult > 0 and cfg.norm_bound > 0
                and norm > cfg.outlier_mult * cfg.norm_bound):
            with self._lock:
                self._round_counts["outliers"] += 1
            return None, flags
        changed = False
        # one clip at the TIGHTEST applicable bound (norm-difference
        # clipping composes: clip(clip(d, a), b) == clip(d, min(a, b))
        # up to a second fp multiply — one clip is the cleaner form).
        # Uploads already inside every bound pass through UNTOUCHED —
        # not even an fp32 rewrite — so an honest run under the
        # streaming defense stays byte-identical to the undefended one.
        bounds = []
        if cfg.defense == "streaming" and cfg.norm_bound > 0:
            bounds.append(cfg.norm_bound)
        if cfg.dp_clip > 0:
            # client-level DP sensitivity bound: after this clip no one
            # client's delta exceeds dp_clip in L2, so dp_noise has a
            # defined per-client sensitivity to hide
            bounds.append(cfg.dp_clip)
        if bounds and norm > min(bounds):
            stacked = robustlib.clip_stacked_params(
                base_np, stacked, min(bounds), xp=np
            )
            changed = True
            # counted whenever the clip actually FIRED — whether the
            # binding bound was the streaming norm_bound or dp_clip
            # (a mutation with zero telemetry would violate the
            # counted-never-silent discipline)
            flags["clipped"] = True
        if cfg.dp_noise > 0:
            if self._seed_key is None:
                self._seed_key = jax.random.PRNGKey(self.seed)
            key = robustlib.agg_noise_key(self._seed_key, round_idx, slot)
            noised = robustlib.noise_params(
                key, _unstack1(stacked, params), cfg.dp_noise
            )
            stacked = _stack1(noised)
            changed = True
            flags["dp_noised"] = True
        if not changed:
            return variables, flags
        return _with_params(variables, _unstack1(stacked, params)), flags

    def note_upload(self, flags: dict) -> None:
        """Count one ACCEPTED (non-duplicate) upload's defense activity
        — called by the server under its round lock after the duplicate
        check, so redelivered copies never inflate the telemetry."""
        tel = get_telemetry()
        with self._lock:
            if flags.get("clipped"):
                self._round_counts["clipped"] += 1
            if flags.get("dp_noised"):
                self._round_counts["dp_noised"] += 1
        if flags.get("clipped"):
            tel.inc("robust.clipped_uploads")
        if flags.get("dp_noised"):
            tel.inc("robust.dp_noised_uploads")

    def _base_np(self, base: PyTree, base_params: PyTree) -> PyTree:
        """The fp32 numpy view of the broadcast base, identity-cached
        per round: every upload of a round screens against the SAME
        base object, so K uploads share one conversion instead of
        paying K full-model copies on the decode workers."""
        with self._lock:
            if self._base_np_cache is not None \
                    and self._base_np_cache[0] is base:
                return self._base_np_cache[1]
        converted = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), base_params
        )
        with self._lock:
            self._base_np_cache = (base, converted)
        return converted

    # -- round bookkeeping ---------------------------------------------------
    def note_round(self, *, capped: int = 0,
                   cap_infeasible: bool = False) -> dict:
        """Snapshot + reset this round's defense counts (called under
        the server's round lock at close); the returned dict rides the
        ``round_close`` record/event so per-round defense activity is
        visible next to participants and spans."""
        tel = get_telemetry()
        if capped:
            tel.inc("robust.capped_conns", capped)
        if cap_infeasible:
            tel.inc("robust.cap_infeasible")
        with self._lock:
            counts = dict(self._round_counts)
            for k in self._round_counts:
                self._round_counts[k] = 0
        counts["capped_conns"] = int(capped)
        if cap_infeasible:
            counts["cap_infeasible"] = True
        return counts

    # -- buffered close ------------------------------------------------------
    def buffered_center(self, entries: List[PyTree]) -> PyTree:
        """The robust params center over the buffered cohort — numpy
        host-side, same ``robust_center`` formula as the compiled
        transform (leaf-exactness vs a numpy oracle is pinned in
        ``tests/test_robust_agg.py``)."""
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x, np.float32) for x in xs]),
            *[_params_of(v) for v in entries],
        )
        return robustlib.robust_center(
            self.cfg.defense, stacked, trim_frac=self.cfg.trim_frac, xp=np
        )

    def buffered_close(self, entries: List[PyTree],
                       norm_weights: List[float]) -> PyTree:
        """The buffered-mode aggregate: sample-weighted mean for every
        non-params collection (BN stats etc. — exactly the undefended
        close), params replaced by the robust center.  Weight-free by
        design: a Byzantine upload's fake sample count buys it nothing
        against a coordinate-wise estimator.  The weighted mean runs
        over the NON-params collections only — params would be thrown
        away in favor of the center, and for a params-only model the
        mean is the whole O(K·model) close stall."""
        from fedml_tpu.core import tree as treelib

        center = self.buffered_center(entries)
        first = entries[0]
        cast = jax.tree_util.tree_map(
            lambda c, l: np.asarray(c, np.asarray(l).dtype),
            center, _params_of(first),
        )
        if not (isinstance(first, dict) and "params" in first):
            return cast
        rest_keys = [k for k in first if k != "params"]
        if not rest_keys:
            return {"params": cast}
        mean_rest = treelib.tree_weighted_sum(
            [{k: e[k] for k in rest_keys} for e in entries], norm_weights
        )
        return {**mean_rest, "params": cast}
