"""Entry shim — reference parity with ``fedml_experiments/*/main_fedavg_robust.py``."""

import sys

if __package__ in (None, ""):  # run as a script: _bootstrap fixes sys.path
    import _bootstrap  # noqa: F401

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(["--algorithm", "fedavg_robust", *sys.argv[1:]])
