"""Entry shim — reference parity with ``fedml_experiments/*/main_fedavg_robust.py``."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(["--algorithm", "fedavg_robust", *sys.argv[1:]])
