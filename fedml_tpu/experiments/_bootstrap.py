"""Script-mode path shim for the ``main_*.py`` entry points.

Imported (as a plain top-level module — the script's own directory is
on ``sys.path`` in script mode) only when a shim runs as
``python fedml_tpu/experiments/main_fedavg.py``; puts the repo root on
``sys.path`` so ``import fedml_tpu`` resolves.  Running via
``python -m fedml_tpu.experiments.main_fedavg`` never imports this.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
