"""Entry shim — federated transformer fine-tuning (beyond-reference
long-context family; ``--tp_degree N`` runs DP x TP on a (clients,
model) device mesh)."""

import sys

if __package__ in (None, ""):  # run as a script: _bootstrap fixes sys.path
    import _bootstrap  # noqa: F401

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main([
        "--algorithm", "fedllm", "--dataset", "fed_shakespeare",
        *sys.argv[1:],
    ])
