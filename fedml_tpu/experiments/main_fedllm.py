"""Entry shim — federated transformer fine-tuning (beyond-reference
long-context family; ``--tp_degree N`` runs DP x TP on a (clients,
model) device mesh)."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main([
        "--algorithm", "fedllm", "--dataset", "fed_shakespeare",
        *sys.argv[1:],
    ])
