"""Entry shim — reference parity with ``fedml_experiments/*/main_fedgkt.py``."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(["--algorithm", "fedgkt", *sys.argv[1:]])
