"""Entry shim — reference parity with ``fedml_experiments/*/main_fedavg.py``."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(["--algorithm", "fedavg", *sys.argv[1:]])
