"""Model + dataset registries for experiment entry points.

Mirrors the reference's switch blocks: ``create_model``
(``fedml_experiments/distributed/fedavg/main_fedavg.py:217-252``) and
``load_data`` (``main_fedavg.py:108-214``), with the same model/dataset
name vocabulary, mapped onto the TPU-native zoo and loaders.
"""

from __future__ import annotations

from typing import Optional

from fedml_tpu.core.types import FedDataset
from fedml_tpu.models.base import ModelBundle


def load_data(
    dataset: str,
    data_dir: str = "",
    num_clients: int = 10,
    partition_method: str = "hetero",
    partition_alpha: float = 0.5,
    seed: int = 0,
) -> FedDataset:
    d = dict(num_clients=num_clients, seed=seed)
    if dataset == "mnist":
        from fedml_tpu.data.mnist import load_mnist

        return load_mnist(data_dir or "./data/mnist", num_clients,
                          partition="power_law", seed=seed)
    if dataset in ("cifar10", "cifar100", "cinic10"):
        from fedml_tpu.data import cifar

        fn = {"cifar10": cifar.load_cifar10, "cifar100": cifar.load_cifar100,
              "cinic10": cifar.load_cinic10}[dataset]
        return fn(data_dir or f"./data/{dataset}", num_clients,
                  partition=partition_method, partition_alpha=partition_alpha,
                  seed=seed)
    if dataset == "femnist":
        from fedml_tpu.data.emnist import load_femnist

        return load_femnist(data_dir or "./data/FederatedEMNIST/datasets",
                            num_clients, seed=seed)
    if dataset == "fed_cifar100":
        from fedml_tpu.data.emnist import load_fed_cifar100

        return load_fed_cifar100(data_dir or "./data/fed_cifar100/datasets",
                                 seed=seed)
    if dataset == "shakespeare":
        from fedml_tpu.data.shakespeare import load_shakespeare

        return load_shakespeare(data_dir or "./data/shakespeare",
                                num_clients, seed=seed)
    if dataset == "fed_shakespeare":
        from fedml_tpu.data.shakespeare import load_fed_shakespeare

        return load_fed_shakespeare(data_dir or "./data/fed_shakespeare/datasets",
                                    num_clients, seed=seed)
    if dataset == "stackoverflow_lr":
        from fedml_tpu.data.stackoverflow import load_stackoverflow_lr

        return load_stackoverflow_lr(data_dir or "./data/stackoverflow_lr",
                                     num_clients, seed=seed)
    if dataset == "stackoverflow_nwp":
        from fedml_tpu.data.stackoverflow import load_stackoverflow_nwp

        return load_stackoverflow_nwp(data_dir or "./data/stackoverflow",
                                      num_clients, seed=seed)
    if dataset in ("ILSVRC2012", "imagenet"):
        from fedml_tpu.data.imagenet import load_imagenet

        return load_imagenet(data_dir or "./data/ImageNet", num_clients,
                             seed=seed)
    if dataset in ("gld23k", "gld160k"):
        from fedml_tpu.data.imagenet import load_landmarks

        return load_landmarks(data_dir or "./data/gld", variant=dataset,
                              seed=seed)
    if dataset == "synthetic":
        from fedml_tpu.data.synthetic import synthetic_classification

        return synthetic_classification(
            num_clients=num_clients, partition=partition_method,
            partition_alpha=partition_alpha, seed=seed,
        )
    raise ValueError(f"unknown dataset: {dataset}")


def create_model(
    model: str, dataset: str, num_classes: int,
    image_size: Optional[int] = None,
    input_shape: Optional[tuple] = None,
) -> ModelBundle:
    """The reference's (model, dataset) switch, TPU-native bundles."""
    img = image_size or (
        input_shape[0] if input_shape and len(input_shape) >= 2
        else (28 if dataset in ("mnist", "femnist") else 32)
    )
    if model == "lr" and dataset == "mnist":
        from fedml_tpu.models.linear import logistic_regression

        return logistic_regression(28 * 28, num_classes)
    if model == "lr" and dataset == "stackoverflow_lr":
        from fedml_tpu.models.linear import logistic_regression

        return logistic_regression(10000, num_classes)
    if model == "rnn" and dataset in ("shakespeare", "fed_shakespeare"):
        from fedml_tpu.models.rnn import rnn_shakespeare

        return rnn_shakespeare(seq_output=(dataset == "fed_shakespeare"))
    if model == "rnn" and dataset == "stackoverflow_nwp":
        from fedml_tpu.models.rnn import rnn_stackoverflow

        return rnn_stackoverflow()
    if model == "cnn":
        from fedml_tpu.models.cnn import cnn_dropout

        return cnn_dropout(only_digits=False, side=img)
    if model == "resnet18_gn":
        from fedml_tpu.models.resnet_gn import resnet18_gn

        return resnet18_gn(num_classes=num_classes, image_size=img)
    if model in ("resnet56", "resnet110", "resnet20", "resnet32", "resnet44"):
        from fedml_tpu.models import resnet

        return getattr(resnet, model)(num_classes=num_classes, image_size=img)
    if model == "mobilenet":
        from fedml_tpu.models.mobilenet import mobilenet

        return mobilenet(num_classes=num_classes, image_size=img)
    if model == "mobilenet_v3":
        from fedml_tpu.models.mobilenet_v3 import mobilenet_v3

        return mobilenet_v3(num_classes=num_classes, model_mode="LARGE",
                            image_size=img)
    if model == "efficientnet":
        from fedml_tpu.models.efficientnet import efficientnet

        return efficientnet("efficientnet-b0", num_classes=num_classes,
                            image_size=img)
    if model.startswith("vgg"):
        from fedml_tpu.models import vgg

        return getattr(vgg, model)(num_classes=num_classes, image_size=img)
    if model == "lr":
        # generic fallback: LR flattens any input shape
        import numpy as np

        from fedml_tpu.models.linear import logistic_regression

        dim = int(np.prod(input_shape)) if input_shape else 784
        return logistic_regression(dim, num_classes)
    raise ValueError(f"unknown model: {model} (dataset {dataset})")
