"""Model + dataset registries for experiment entry points.

Mirrors the reference's switch blocks: ``create_model``
(``fedml_experiments/distributed/fedavg/main_fedavg.py:217-252``) and
``load_data`` (``main_fedavg.py:108-214``), with the same model/dataset
name vocabulary, mapped onto the TPU-native zoo and loaders.
"""

from __future__ import annotations

from typing import Optional

from fedml_tpu.core.types import FedDataset
from fedml_tpu.models.base import ModelBundle


def load_data(
    dataset: str,
    data_dir: str = "",
    num_clients: int = 10,
    partition_method: str = "hetero",
    partition_alpha: float = 0.5,
    seed: int = 0,
) -> FedDataset:
    d = dict(num_clients=num_clients, seed=seed)
    if dataset == "mnist":
        from fedml_tpu.data.mnist import load_mnist

        return load_mnist(data_dir or "./data/mnist", num_clients,
                          partition="power_law", seed=seed)
    if dataset in ("cifar10", "cifar100", "cinic10"):
        from fedml_tpu.data import cifar

        fn = {"cifar10": cifar.load_cifar10, "cifar100": cifar.load_cifar100,
              "cinic10": cifar.load_cinic10}[dataset]
        return fn(data_dir or f"./data/{dataset}", num_clients,
                  partition=partition_method, partition_alpha=partition_alpha,
                  seed=seed)
    if dataset == "femnist":
        from fedml_tpu.data.emnist import load_femnist

        return load_femnist(data_dir or "./data/FederatedEMNIST/datasets",
                            num_clients, seed=seed)
    if dataset == "fed_cifar100":
        from fedml_tpu.data.emnist import load_fed_cifar100

        return load_fed_cifar100(data_dir or "./data/fed_cifar100/datasets",
                                 seed=seed)
    if dataset == "shakespeare":
        from fedml_tpu.data.shakespeare import load_shakespeare

        return load_shakespeare(data_dir or "./data/shakespeare",
                                num_clients, seed=seed)
    if dataset == "fed_shakespeare":
        from fedml_tpu.data.shakespeare import load_fed_shakespeare

        return load_fed_shakespeare(data_dir or "./data/fed_shakespeare/datasets",
                                    num_clients, seed=seed)
    if dataset == "stackoverflow_lr":
        from fedml_tpu.data.stackoverflow import load_stackoverflow_lr

        return load_stackoverflow_lr(data_dir or "./data/stackoverflow_lr",
                                     num_clients, seed=seed)
    if dataset == "stackoverflow_nwp":
        from fedml_tpu.data.stackoverflow import load_stackoverflow_nwp

        return load_stackoverflow_nwp(data_dir or "./data/stackoverflow",
                                      num_clients, seed=seed)
    if dataset in ("ILSVRC2012", "imagenet"):
        from fedml_tpu.data.imagenet import load_imagenet

        return load_imagenet(data_dir or "./data/ImageNet", num_clients,
                             seed=seed)
    if dataset in ("gld23k", "gld160k"):
        from fedml_tpu.data.imagenet import load_landmarks

        return load_landmarks(data_dir or "./data/gld", variant=dataset,
                              seed=seed)
    if dataset == "synthetic":
        from fedml_tpu.data.synthetic import synthetic_classification

        return synthetic_classification(
            num_clients=num_clients, partition=partition_method,
            partition_alpha=partition_alpha, seed=seed,
        )
    raise ValueError(f"unknown dataset: {dataset}")


def task_loss_for_dataset(dataset: str):
    """Per-dataset task loss — the reference selects a trainer class by
    dataset (``standalone/fedavg/fedavg_api.py`` passes
    ``my_model_trainer_tag_prediction`` for stackoverflow_lr, the
    NWP/classification trainers otherwise).  Here the same switch picks
    the masked loss every driver threads through its round kernel."""
    from fedml_tpu.core import losses

    if dataset == "stackoverflow_lr":  # multi-label tag prediction
        return losses.masked_multilabel_bce
    return losses.masked_softmax_ce


def shrink_dataset(
    ds: FedDataset,
    max_samples_per_client: int = 0,
    max_test_samples: int = 0,
) -> FedDataset:
    """Deterministically cap per-client shard sizes and the test set.

    The smoke tier shrinks the REAL task instead of substituting a
    synthetic one (the reference's ``--ci 1`` swaps the dataset out,
    ``FedAVGAggregator.py:115-120`` — which is how broken task wiring
    survives CI; here the model/dataset/loss pair under test is always
    the real one, just smaller).
    """
    import dataclasses as _dc

    if not (max_samples_per_client or max_test_samples):
        return ds
    train_idx = ds.train_client_idx
    if max_samples_per_client:
        train_idx = {
            c: idx[:max_samples_per_client] for c, idx in train_idx.items()
        }
    test_x, test_y = ds.test_x, ds.test_y
    test_idx = ds.test_client_idx
    if max_test_samples and test_y is not None and \
            len(test_y) > max_test_samples:
        # deterministic STRIDED selection, not a prefix: folder-tree
        # loaders (imagefolder/CINIC) emit test arrays grouped by class,
        # so a [:N] prefix collapses the smoke test set to one or two
        # classes (advisor r3 — the pitfall cifar.py:142 documents)
        import numpy as _np

        keep = _np.linspace(0, len(test_y) - 1, max_test_samples,
                            dtype=_np.int64)
        test_x = test_x[keep]
        test_y = test_y[keep]
        if test_idx is not None:
            # remap kept global positions to their new compacted index
            pos = {int(g): i for i, g in enumerate(keep)}
            test_idx = {
                c: _np.asarray([pos[int(g)] for g in idx if int(g) in pos],
                               dtype=_np.int64)
                for c, idx in test_idx.items()
            }
    return _dc.replace(
        ds, train_client_idx=train_idx, test_x=test_x, test_y=test_y,
        test_client_idx=test_idx,
    )


def create_model(
    model: str, dataset: str, num_classes: int,
    image_size: Optional[int] = None,
    input_shape: Optional[tuple] = None,
) -> ModelBundle:
    """The reference's (model, dataset) switch, TPU-native bundles."""
    img = image_size or (
        input_shape[0] if input_shape and len(input_shape) >= 2
        else (28 if dataset in ("mnist", "femnist") else 32)
    )
    if model == "lr" and dataset == "mnist":
        from fedml_tpu.models.linear import logistic_regression

        return logistic_regression(28 * 28, num_classes)
    if model == "lr" and dataset == "stackoverflow_lr":
        from fedml_tpu.models.linear import logistic_regression

        return logistic_regression(10000, num_classes)
    if model == "rnn" and dataset in ("shakespeare", "fed_shakespeare"):
        from fedml_tpu.models.rnn import rnn_shakespeare

        return rnn_shakespeare(seq_output=(dataset == "fed_shakespeare"))
    if model == "rnn" and dataset == "stackoverflow_nwp":
        from fedml_tpu.models.rnn import rnn_stackoverflow

        return rnn_stackoverflow()
    if model == "cnn":
        from fedml_tpu.models.cnn import cnn_dropout

        return cnn_dropout(only_digits=False, side=img)
    if model == "resnet18_gn":
        from fedml_tpu.models.resnet_gn import resnet18_gn

        return resnet18_gn(num_classes=num_classes, image_size=img)
    if model in ("resnet56", "resnet110", "resnet20", "resnet32", "resnet44"):
        from fedml_tpu.models import resnet

        return getattr(resnet, model)(num_classes=num_classes, image_size=img)
    if model == "mobilenet":
        from fedml_tpu.models.mobilenet import mobilenet

        return mobilenet(num_classes=num_classes, image_size=img)
    if model == "mobilenet_v3":
        from fedml_tpu.models.mobilenet_v3 import mobilenet_v3

        return mobilenet_v3(num_classes=num_classes, model_mode="LARGE",
                            image_size=img)
    if model == "efficientnet":
        from fedml_tpu.models.efficientnet import efficientnet

        return efficientnet("efficientnet-b0", num_classes=num_classes,
                            image_size=img)
    if model.startswith("vgg"):
        from fedml_tpu.models import vgg

        return getattr(vgg, model)(num_classes=num_classes, image_size=img)
    if model == "lr":
        # generic fallback: LR flattens any input shape
        import numpy as np

        from fedml_tpu.models.linear import logistic_regression

        dim = int(np.prod(input_shape)) if input_shape else 784
        return logistic_regression(dim, num_classes)
    raise ValueError(f"unknown model: {model} (dataset {dataset})")
