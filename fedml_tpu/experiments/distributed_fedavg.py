"""Real multi-process federated training over the TCP hub.

The reference's flagship mode launches N+1 OS processes under mpirun
(``fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:19-37``:
``PROCESS_NUM = WORKER_NUM + 1``, rank 0 = server).  Here the same
shape runs over the zero-dependency TCP hub (``comm/tcp.py``): one hub
process routes JSON-line frames, one server process coordinates, N
client processes train with the SAME jit local-update operator the
simulation uses — so the distributed result is asserted equal to the
in-process simulation (``tests/test_distributed_process.py``).

Roles (one process each):

    python -m fedml_tpu.experiments.distributed_fedavg --role hub --port 0
    python -m fedml_tpu.experiments.distributed_fedavg --role server \
        --port P --num-clients 3 --rounds 2 --out /tmp/final.npz
    python -m fedml_tpu.experiments.distributed_fedavg --role client \
        --port P --node-id 1

Every process builds the same synthetic dataset deterministically from
``--seed`` (the reference likewise has every rank load all partitions,
``main_fedavg.py:108-214``).  ``launch()`` spawns the whole federation
as subprocesses for tests/smoke runs.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _force_cpu_if_requested():
    # Subprocesses inherit FEDML_TPU_FORCE_CPU=1 from the test launcher:
    # the config update must land before the first device query
    # (tests/conftest.py — env vars alone are too late when
    # sitecustomize imports jax at interpreter start)
    if os.environ.get("FEDML_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")


def _build_problem(seed: int, num_clients: int):
    import jax

    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models.linear import logistic_regression

    ds = synthetic_classification(
        num_train=60 * num_clients, num_test=30, input_shape=(8,),
        num_classes=2, num_clients=num_clients, partition="homo", seed=seed,
    )
    bundle = logistic_regression(8, 2)
    init = bundle.init(jax.random.PRNGKey(seed))
    lu = make_local_update(bundle, make_client_optimizer("sgd", 0.1), 1)
    return ds, bundle, init, lu


def _connect_backend(node_id: int, host: str, port: int, retries: int = 50,
                     auto_reconnect: int = 0):
    """The hub may still be binding when a worker starts: retry."""
    from fedml_tpu.comm.tcp import TcpBackend

    for attempt in range(retries):
        try:
            return TcpBackend(node_id, host, port,
                              auto_reconnect=auto_reconnect)
        except (ConnectionError, OSError):
            if attempt == retries - 1:
                raise
            time.sleep(0.1)


def run_hub(host: str, port: int) -> None:
    from fedml_tpu.comm.tcp import TcpHub

    hub = TcpHub(host, port)
    # announce the bound port on stdout for the launcher
    print(json.dumps({"hub_port": hub.port}), flush=True)
    stop = {"flag": False}

    def _stop(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    try:
        while not stop["flag"]:
            time.sleep(0.1)
    finally:
        hub.stop()


def run_server(args) -> None:
    _force_cpu_if_requested()
    import numpy as np

    import jax

    from fedml_tpu.algorithms.fedavg_cross_device import FedAvgServerManager

    ds, bundle, init, lu = _build_problem(args.seed, args.num_clients)
    backend = _connect_backend(0, args.host, args.port)
    # cohort-wide pack geometry (fedavg_cross_device.py:62-66): each
    # client's single-client pack must match its slice of the
    # simulation's cohort pack even with heterogeneous client sizes
    from fedml_tpu.core.types import cohort_steps_per_epoch

    steps = cohort_steps_per_epoch(ds, args.batch_size)
    if not args.round_timeout:
        # clients dial with auto_reconnect (run_client below): frames
        # routed while a client is disconnected are LOST, so a SYNC that
        # lands in a reconnect window leaves the server waiting forever
        # for an upload the client never saw.  Reconnect tolerance
        # relies on the round deadline to move on without it (advisor
        # r3) — run without one only if clients never drop.
        print("WARNING: no --round-timeout with auto-reconnecting "
              "clients: a SYNC lost during a client's reconnect window "
              "deadlocks the round; set --round-timeout to tolerate "
              "connection drops", file=sys.stderr, flush=True)
    server = FedAvgServerManager(
        backend, init, num_clients=args.num_clients,
        clients_per_round=args.clients_per_round or args.num_clients,
        comm_rounds=args.rounds, seed=args.seed,
        steps_per_epoch=steps,
        round_timeout=args.round_timeout or None,
    )
    # startup barrier: the hub drops frames to unregistered receivers,
    # so broadcasting before every client registered would hang
    # registration barrier budget scales with the federation size: on
    # a 1-core host N client processes SERIALIZE their jax imports
    # (~8 s each), so a fixed 60 s cap spuriously fails at N >= ~8
    backend.await_peers(range(1, args.num_clients + 1),
                        timeout=60 + 15 * args.num_clients)
    server.start()
    backend.run()  # returns when finish() closes the socket
    if args.out:
        leaves = jax.tree_util.tree_leaves(server.variables)
        np.savez(
            args.out,
            **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
            rounds=server.round_idx,
            round_log=json.dumps(server.round_log),
        )
    print(json.dumps({
        "rounds": server.round_idx,
        "zero_participant_rounds": server.zero_participant_rounds,
    }), flush=True)
    if server.zero_participant_rounds >= server.comm_rounds:
        # every round aggregated nobody (deadline shorter than client
        # train time): the "final" model is the init model — fail loudly
        # instead of handing back rc=0
        print("ERROR: all rounds closed with zero participants; the "
              "model was never updated (round_timeout too short?)",
              file=sys.stderr, flush=True)
        sys.exit(2)


def run_client(args) -> None:
    _force_cpu_if_requested()
    from fedml_tpu.algorithms.fedavg_cross_device import FedAvgClientManager

    ds, bundle, init, lu = _build_problem(args.seed, args.num_clients)
    # clients ride out transient hub-connection drops: re-dial +
    # re-register, rejoining as a straggler for the missed round (the
    # server's round deadline covers the gap)
    backend = _connect_backend(args.node_id, args.host, args.port,
                               auto_reconnect=3)
    FedAvgClientManager(
        backend, lu, ds, batch_size=args.batch_size,
        template_variables=init, seed=args.seed,
        train_delay=args.train_delay,
    )
    backend.run()  # returns on FINISH


def launch(
    num_clients: int = 3,
    rounds: int = 2,
    *,
    seed: int = 0,
    batch_size: int = 16,
    out_path: str,
    extra_idle_clients: int = 0,
    kill_idle_after: float = 0.0,
    round_timeout: float = 0.0,
    slow_client_delay: float = 0.0,
    kill_slow_client_after: float = 0.0,
    env=None,
    server_env=None,
    timeout: float = 180.0,
):
    """Spawn hub + server + clients as OS processes and wait for the
    federation to finish; returns the server's exit code (0 = the
    configured rounds completed and ``out_path`` was written).

    ``extra_idle_clients`` registers clients beyond ``num_clients`` that
    the server never samples — one is SIGKILLed once the launcher has
    CONFIRMED its hub registration (``await_peers``), exercising the
    hub's dead-peer handling mid-run without wedging the round.

    ``slow_client_delay`` makes the LAST sampled client (node id
    ``num_clients``) sleep that long before each local update;
    ``kill_slow_client_after`` SIGKILLs it mid-sleep — i.e. a SAMPLED
    client dies mid-round.  With ``round_timeout`` set the server's
    deadline aggregates without it and logs the dropout."""
    env = dict(env or os.environ)
    me = [sys.executable, "-m", "fedml_tpu.experiments.distributed_fedavg"]
    hub = None
    procs = []
    killed_registered_peer = False
    try:
        hub = subprocess.Popen(
            me + ["--role", "hub", "--port", "0"],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        port_line = hub.stdout.readline()
        if not port_line:
            raise RuntimeError("hub died before announcing its port")
        port = json.loads(port_line)["hub_port"]
        common = ["--host", "127.0.0.1", "--port", str(port),
                  "--num-clients", str(num_clients), "--rounds", str(rounds),
                  "--seed", str(seed), "--batch-size", str(batch_size)]
        if round_timeout:
            common += ["--round-timeout", str(round_timeout)]
        clients = [
            subprocess.Popen(
                me + ["--role", "client", "--node-id", str(i + 1)] + common
                + (["--train-delay", str(slow_client_delay)]
                   if slow_client_delay and i == num_clients - 1 else []),
                env=env,
            )
            for i in range(num_clients)
        ]
        procs += clients
        idle = [
            subprocess.Popen(
                me + ["--role", "client",
                      "--node-id", str(num_clients + 1 + j)] + common,
                env=env,
            )
            for j in range(extra_idle_clients)
        ]
        procs += idle
        # server_env lets the SERVER run on a different backend than the
        # clients — e.g. aggregation on the one real TPU chip while 16
        # client processes train on CPU (only one process may hold the
        # tunnel lease)
        server = subprocess.Popen(
            me + ["--role", "server", "--out", out_path] + common,
            env=dict(server_env) if server_env is not None else env,
        )
        procs.append(server)
        if kill_slow_client_after and slow_client_delay:
            # wait until EVERYONE (clients + server) is registered — the
            # server's await_peers barrier has then passed, so killing
            # the slow client can no longer wedge startup; by now it is
            # asleep in its first local update (train_delay) — a SAMPLED
            # client dying mid-round
            from fedml_tpu.comm.tcp import TcpBackend

            mon = TcpBackend(9998, "127.0.0.1", port)
            mon.await_peers([0] + list(range(1, num_clients + 1)),
                            timeout=60 + 15 * num_clients)
            mon.stop()
            time.sleep(kill_slow_client_after)
            clients[-1].kill()
        if idle:
            # monitor connection: wait until the doomed peer is actually
            # registered, so the kill exercises hub dead-peer cleanup
            # rather than landing on a process that never connected
            from fedml_tpu.comm.tcp import TcpBackend

            monitor = TcpBackend(9999, "127.0.0.1", port)
            monitor.await_peers([num_clients + 1],
                                timeout=60 + 15 * num_clients)
            if kill_idle_after:
                time.sleep(kill_idle_after)
            idle[0].kill()
            killed_registered_peer = True
            monitor.stop()
        rc = server.wait(timeout=timeout)
        for c in clients:
            c.wait(timeout=30)
        if extra_idle_clients:
            assert killed_registered_peer
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if hub is not None:
            hub.terminate()
            hub.wait(timeout=10)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--role", choices=["hub", "server", "client"], required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--node-id", type=int, default=0)
    p.add_argument("--num-clients", type=int, default=3)
    p.add_argument("--clients-per-round", type=int, default=0)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--out", default="")
    # straggler knobs: server-side round deadline (s; 0 = wait forever,
    # the reference's behavior) and client-side artificial train delay
    p.add_argument("--round-timeout", type=float, default=0.0)
    p.add_argument("--train-delay", type=float, default=0.0)
    args = p.parse_args(argv)
    if args.role == "hub":
        run_hub(args.host, args.port)
    elif args.role == "server":
        run_server(args)
    else:
        run_client(args)


if __name__ == "__main__":
    main()
