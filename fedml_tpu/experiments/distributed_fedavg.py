"""Real multi-process federated training over the TCP hub.

The reference's flagship mode launches N+1 OS processes under mpirun
(``fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:19-37``:
``PROCESS_NUM = WORKER_NUM + 1``, rank 0 = server).  Here the same
shape runs over the zero-dependency TCP hub (``comm/tcp.py``): one hub
process routes JSON-line frames, one server process coordinates, N
client processes train with the SAME jit local-update operator the
simulation uses — so the distributed result is asserted equal to the
in-process simulation (``tests/test_distributed_process.py``).

Roles (one process each):

    python -m fedml_tpu.experiments.distributed_fedavg --role hub --port 0
    python -m fedml_tpu.experiments.distributed_fedavg --role server \
        --port P --num-clients 3 --rounds 2 --out /tmp/final.npz
    python -m fedml_tpu.experiments.distributed_fedavg --role client \
        --port P --node-id 1

Every process builds the same synthetic dataset deterministically from
``--seed`` (the reference likewise has every rank load all partitions,
``main_fedavg.py:108-214``).  ``launch()`` spawns the whole federation
as subprocesses for tests/smoke runs.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _force_cpu_if_requested():
    # Subprocesses inherit FEDML_TPU_FORCE_CPU=1 from the test launcher:
    # the config update must land before the first device query
    # (tests/conftest.py — env vars alone are too late when
    # sitecustomize imports jax at interpreter start)
    if os.environ.get("FEDML_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")


def _build_problem(seed: int, num_clients: int, input_dim: int = 8,
                   train_samples: int = 60):
    """``input_dim`` scales the model (logistic_regression(input_dim, 2))
    so byte-accounting runs can measure compression on a payload large
    enough that the frame envelope is noise (the default 18-param model
    is all envelope); ``train_samples`` (per client) scales local
    compute, so latency runs can pick a comm-dominant regime."""
    import jax

    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.data.synthetic import synthetic_classification
    from fedml_tpu.models.linear import logistic_regression

    ds = synthetic_classification(
        num_train=train_samples * num_clients, num_test=30,
        input_shape=(input_dim,),
        num_classes=2, num_clients=num_clients, partition="homo", seed=seed,
    )
    bundle = logistic_regression(input_dim, 2)
    init = bundle.init(jax.random.PRNGKey(seed))
    lu = make_local_update(bundle, make_client_optimizer("sgd", 0.1), 1)
    return ds, bundle, init, lu


def _dial_with_retry(factory, retries: int = 50):
    """The hub may still be binding when a worker starts: retry the
    backend constructor — ONE retry policy for every dialing role."""
    for attempt in range(retries):
        try:
            return factory()
        except (ConnectionError, OSError):
            if attempt == retries - 1:
                raise
            time.sleep(0.1)


def _lane_kwargs(args) -> dict:
    """Transport-lane knobs shared by every dialing role: ``--lane shm``
    creates a per-connection shared-memory slab (payload bytes ride its
    rings, headers stay on TCP; automatic per-frame TCP fallback)."""
    return {
        "lane": args.lane,
        "shm_data_bytes": args.shm_mib << 20,
        "shm_min_bytes": args.shm_min_bytes,
    }


def _connect_backend(node_id: int, host: str, port: int, retries: int = 50,
                     auto_reconnect: int = 0, wire: int = 2, **lane_kw):
    from fedml_tpu.comm.tcp import TcpBackend

    return _dial_with_retry(
        lambda: TcpBackend(node_id, host, port,
                           auto_reconnect=auto_reconnect, wire=wire,
                           **lane_kw),
        retries)


def _connect_mux_backend(node_ids, host: str, port: int, retries: int = 50,
                         auto_reconnect: int = 0, wire: int = 2, **lane_kw):
    """Muxed twin of ``_connect_backend``: one hello-v2 dial registers
    the whole virtual-client range."""
    from fedml_tpu.comm.mux import TcpMuxBackend

    return _dial_with_retry(
        lambda: TcpMuxBackend(node_ids, host, port,
                              auto_reconnect=auto_reconnect, wire=wire,
                              **lane_kw),
        retries)


def _chaos_plan():
    from fedml_tpu.faults import FaultPlan

    return FaultPlan.from_env()


def _traffic_model(role: str):
    """Open-loop traffic model for this worker, or None: ``launch()``
    ships ``TrafficModel`` JSON through ``FEDML_TPU_TRAFFIC`` (same
    shape as the chaos plan's env ride), and the model's ``roles``
    field gates which worker kinds draw from it."""
    from fedml_tpu.faults.traffic import TrafficModel

    tm = TrafficModel.from_env()
    if tm is None or role not in tm.roles or not tm.any_traffic():
        return None
    return tm


def _maybe_chaos(backend, role: str, plan=None):
    """Wrap the transport in a ``ChaosBackend`` when a fault plan rides
    the ``FEDML_TPU_CHAOS`` env var and names this role — how
    ``tools/chaos_run.py`` injects message faults into worker
    subprocesses without new plumbing on every entry point."""
    from fedml_tpu.faults import ChaosBackend

    plan = plan if plan is not None else _chaos_plan()
    if plan is None or role not in plan.roles:
        return backend
    return ChaosBackend(backend, plan)


def _collect_json_lines(stream, info: dict) -> None:
    """Fold every parseable JSON line of a finished process's stdout
    into ``info`` (server fault counters, hub stats, per-client upload
    digests).  ``stream`` may also be already-read text (the muxer
    path drains via ``communicate`` — see ``launch``)."""
    if stream is None:
        return
    text = stream if isinstance(stream, str) else stream.read()
    for line in text.splitlines():
        try:
            info.update(json.loads(line))
        except json.JSONDecodeError:
            continue


def _resolve_crash_round(flag_value: int, plan, node_id: int):
    """Crash schedule precedence: an explicit ``--crash-at-round`` flag
    wins; otherwise the env-shipped plan's ``crash_at_round`` map is
    consulted for this node (the FaultPlan knob is live, not just
    serialized)."""
    if flag_value >= 0:
        return flag_value
    if plan is not None:
        return plan.crash_at_round.get(node_id)
    return None


def _node_metrics_logger(run_dir: str, tag):
    """Per-process metrics sink: each federation participant appends to
    its OWN ``metrics-<tag>.jsonl`` inside the shared run_dir, so
    concurrent processes never interleave into one file and
    ``tools/fed_timeline.py`` can merge the set.  Returns None when no
    run_dir was requested (the legacy stdout-only mode)."""
    if not run_dir:
        return None
    from fedml_tpu.core.metrics import MetricsLogger

    return MetricsLogger(run_dir=run_dir, filename=f"metrics-{tag}.jsonl")


def _install_flight(run_dir: str, tag) -> None:
    """Arm this process's flight recorder (``obs/flight.py``): dump
    destination ``flight-<tag>.json`` beside the metrics files, SIGUSR2
    snapshot handler, unhandled-exception hooks, and the faulthandler
    crash log.  Recording itself is always on; without a run_dir the
    triggers only mark history."""
    from fedml_tpu.obs import flight

    flight.install(run_dir or None, str(tag))


def _start_event_flusher(mlog, interval: float = 1.0):
    """Periodically drain the telemetry event ring into this process's
    metrics file while the main thread is blocked in ``backend.run()``.
    The ring holds 4096 events and a traced run emits ~participants
    ``trace_hop`` events per round, so exit-time-only draining evicts
    the earliest chains (the one ``clock_sync`` event first) on long
    runs.  Returns a stop callable; call it BEFORE the final
    ``log_telemetry`` so only one thread ever writes at a time."""
    if mlog is None:
        return lambda: None
    import threading

    stop = threading.Event()

    def _loop():
        while not stop.wait(interval):
            mlog.flush_events()

    t = threading.Thread(target=_loop, daemon=True)
    t.start()

    def _stop():
        stop.set()
        t.join(timeout=5)

    return _stop


def _start_stats_reporter(args, backend, mgr, nodes):
    """Attach + start a DigestReporter when the stats plane is on: one
    delta-digest frame per report interval, through the SAME (possibly
    chaos-wrapped) backend the manager sends on — so a telemetry_loss
    fault plan drops digest frames exactly where it would on a
    dedicated process.  The manager stops it at FINISH with a final
    flush; the returned handle is the entry point's belt-and-braces
    stop for runs that end without one (killed hub, crash)."""
    if args.stats_plane != "on":
        return None
    from fedml_tpu.obs.digest import DigestReporter

    reporter = DigestReporter(backend, interval=args.report_interval,
                              nodes=nodes)
    mgr.stats_reporter = reporter
    return reporter.start()


def run_hub(host: str, port: int, run_dir: str = "",
            stats_interval: float = 1.0, fanout: str = "striped",
            stripe_kib: int = 256, stripe_pace: int = 8,
            shm_min_bytes: int = 1024) -> None:
    from fedml_tpu.comm.tcp import TcpHub

    # striped fan-out is the DEFAULT hub mode: multicast payloads split
    # into fixed-size crc'd stripes, every receiver's stripe 0
    # head-started before any tail, so the last of K receivers no
    # longer waits behind K-1 whole-frame sends to START receiving
    # (the PR-6-measured bcast_queue wall).  --fanout whole restores
    # the PR-5 whole-frame behavior — the measurement baseline arm.
    hub = TcpHub(host, port,
                 stripe_bytes=(stripe_kib << 10) if fanout == "striped"
                 else 0,
                 max_inflight_stripes=stripe_pace,
                 shm_min_bytes=shm_min_bytes)
    # announce the bound port on stdout for the launcher
    print(json.dumps({"hub_port": hub.port}), flush=True)
    from fedml_tpu.obs.telemetry import get_telemetry

    get_telemetry().gauge_set("hub.tier", 0)
    stop = {"flag": False}

    def _stop(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    mlog = _node_metrics_logger(run_dir, "hub")
    _install_flight(run_dir, "hub")
    last_sample = time.monotonic()
    try:
        while not stop["flag"]:
            time.sleep(0.1)
            if mlog is not None and (
                time.monotonic() - last_sample >= stats_interval
            ):
                # periodic snapshot INTO THE FILE, not just the exit
                # print: a crashed/SIGKILLed hub still leaves its
                # queue-depth / backpressure time series behind
                last_sample = time.monotonic()
                hub.sample_telemetry()
                mlog.log_telemetry()
    finally:
        hub.stop()
        if mlog is not None:
            hub.sample_telemetry()
            mlog.log_telemetry()
            mlog.close()
        # hub-side fault accounting for the launcher (dropped frames by
        # message type — chaos runs reconcile these against injections)
        print(json.dumps({"hub_stats": hub.stats()}), flush=True)


def _defense_from_args(args):
    """Build the DefenseConfig (or None = the exact undefended path)
    from the CLI knobs — shared by the root server and the edge-hub
    tier, which must screen uploads with the IDENTICAL configuration
    for the tree-vs-flat byte-identity pin to hold."""
    if args.trim_frac != 0.2 and args.defense != "trimmed_mean":
        # DefenseConfig cannot tell an explicit 0.2 from the default,
        # so the only layer that knows the flag was TYPED is this one —
        # a trim fraction without its mode must not be silently inert
        raise SystemExit(
            "--trim-frac only applies with --defense trimmed_mean "
            f"(got --defense {args.defense})"
        )
    if (args.defense != "none" or args.dp_clip > 0 or args.dp_noise > 0
            or args.norm_bound > 0 or args.outlier_mult > 0
            or args.conn_cap > 0):
        # ANY defense knob constructs the config, so a knob that needs
        # a mode it wasn't given fails DefenseConfig validation loudly
        # instead of running a silently-undefended federation
        from fedml_tpu.robust import DefenseConfig

        return DefenseConfig(
            defense=args.defense, norm_bound=args.norm_bound,
            outlier_mult=args.outlier_mult, conn_cap=args.conn_cap,
            dp_clip=args.dp_clip, dp_noise=args.dp_noise,
            trim_frac=args.trim_frac,
        )
    return None


def run_server(args) -> None:
    _force_cpu_if_requested()
    import numpy as np

    import jax

    from fedml_tpu.algorithms.fedavg_cross_device import FedAvgServerManager

    ds, bundle, init, lu = _build_problem(args.seed, args.num_clients,
                                          args.input_dim, args.train_samples)
    backend = _maybe_chaos(
        _connect_backend(0, args.host, args.port,
                         auto_reconnect=max(args.auto_reconnect, 0),
                         wire=args.wire, **_lane_kwargs(args)),
        "server",
    )
    # cohort-wide pack geometry (fedavg_cross_device.py:62-66): each
    # client's single-client pack must match its slice of the
    # simulation's cohort pack even with heterogeneous client sizes
    from fedml_tpu.core.types import cohort_steps_per_epoch

    steps = cohort_steps_per_epoch(ds, args.batch_size)
    if not args.round_timeout:
        # clients dial with auto_reconnect (run_client below): frames
        # routed while a client is disconnected are LOST, so a SYNC that
        # lands in a reconnect window leaves the server waiting forever
        # for an upload the client never saw.  Reconnect tolerance
        # relies on the round deadline to move on without it (advisor
        # r3) — run without one only if clients never drop.
        print("WARNING: no --round-timeout with auto-reconnecting "
              "clients: a SYNC lost during a client's reconnect window "
              "deadlocks the round; set --round-timeout to tolerate "
              "connection drops", file=sys.stderr, flush=True)
    # stats plane: declared SLO objectives (--slo: inline JSON or a
    # file path); an empty spec still produces the full health report
    slo_spec = None
    if args.slo:
        from fedml_tpu.obs.slo import SloSpec

        slo_spec = SloSpec.from_arg(args.slo)
    # robust aggregation (fedml_tpu/robust): --defense picks the mode,
    # the numeric knobs parametrize it; all-defaults = None = the exact
    # undefended code path
    defense = _defense_from_args(args)
    server = FedAvgServerManager(
        backend, init, num_clients=args.num_clients,
        clients_per_round=args.clients_per_round or args.num_clients,
        comm_rounds=args.rounds, seed=args.seed,
        steps_per_epoch=steps,
        round_timeout=args.round_timeout or None,
        spares=args.spares,
        codec=args.codec,
        multicast=args.hotpath == "fast",
        streaming_agg=args.hotpath == "fast",
        # decode/fold pipeline + double-buffered broadcast encode: fast
        # hotpath only (the legacy arm is the fully serial baseline)
        decode_workers=(args.decode_workers
                        if args.hotpath == "fast" else 0),
        # in-band stats plane (obs/digest + obs/slo): rollup of the
        # cohort's digest frames + per-round SLO evaluation; with a
        # run_dir the live status.json and final slo_report.json land
        # there (--stats-plane off = the A/B measurement baseline arm)
        stats_plane=args.stats_plane == "on",
        slo_spec=slo_spec,
        status_dir=args.run_dir or None,
        stats_interval=args.report_interval,
        defense=defense,
        # delta/dedup broadcast (--bcast delta): sync ships the int8
        # chain update against each node's last-acked round; --bcast-
        # codec "" resolves to qsgd8 in delta mode / none (legacy) in
        # full mode, and an explicit codec on a full run turns on the
        # same quantized chain for the delta-vs-full digest pin
        bcast=args.bcast,
        bcast_codec=args.bcast_codec,
        delta_base_window=args.delta_base_window,
        # async buffered rounds (--round-mode async): fold-on-arrival,
        # cut every --cut-size arrivals (or the round deadline), stale
        # uploads in the --max-staleness window folded at the
        # --stale-policy/--stale-alpha discount instead of rejected
        round_mode=args.round_mode,
        cut_size=args.cut_size,
        max_staleness=args.max_staleness,
        stale_policy=args.stale_policy,
        stale_alpha=args.stale_alpha,
    )
    # startup barrier: the hub drops frames to unregistered receivers,
    # so broadcasting before every client registered would hang
    # registration barrier budget scales with the federation size: on
    # a 1-core host N client processes SERIALIZE their jax imports
    # (~8 s each), so a fixed 60 s cap spuriously fails at N >= ~8
    backend.await_peers(range(1, args.num_clients + 1),
                        timeout=60 + 15 * args.num_clients)
    # the metrics sink opens BEFORE the round loop and a flusher thread
    # drains the bounded event ring on a timer: a long traced run would
    # otherwise evict clock_sync + early trace_hop chains before the
    # exit-time drain (deque maxlen=4096)
    mlog = _node_metrics_logger(args.run_dir, "node0")
    _install_flight(args.run_dir, "node0")
    stop_flusher = _start_event_flusher(mlog)
    server.start()
    backend.run()  # returns when finish() closes the socket
    if args.out:
        leaves = jax.tree_util.tree_leaves(server.variables)
        np.savez(
            args.out,
            **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
            rounds=server.round_idx,
            round_log=json.dumps(server.round_log),
        )
    # final drain: stop the flusher first so only one thread writes,
    # then the full registry (remaining events + counter/histogram
    # snapshot) lands in the server's own metrics file — the
    # per-process record fed_timeline merges
    stop_flusher()
    if mlog is not None:
        mlog.log_telemetry()
        mlog.close()
    # fault accounting alongside the round count: the process-local
    # telemetry registry dies with this process, so surface the chaos
    # counters on stdout where the launcher/chaos driver collects them
    from fedml_tpu.obs.telemetry import get_telemetry

    snap = get_telemetry().snapshot()["counters"]
    print(json.dumps({
        "rounds": server.round_idx,
        "zero_participant_rounds": server.zero_participant_rounds,
        "rejected_uploads": server.rejected_uploads,
        "rounds_degraded": snap.get("rounds.degraded", 0),
        # stats-plane outcome: digest streams ingested (== CONNECTIONS
        # under muxing, not clients), frames/rejects, SLO verdict — the
        # health campaign asserts on this line
        "stats_plane": server.stats_summary(),
        "faults": {k: v for k, v in snap.items()
                   if k.startswith(("faults.", "robust.", "comm.unhandled",
                                    "comm.send_retries", "comm.send_failed",
                                    "comm.reconnects", "comm.shm_",
                                    "comm.delta_"))},
        # exact server-side wire accounting (TcpBackend counts header +
        # binary payload): the compression measurement reads C2S bytes
        # off this line across baseline/compressed federations
        "comm_bytes": {k: v for k, v in snap.items()
                       if k.startswith(("comm.recv_bytes",
                                        "comm.sent_bytes",
                                        "comm.recv_msgs",
                                        "comm.sent_msgs"))},
    }), flush=True)
    if server.zero_participant_rounds >= server.comm_rounds:
        # every round aggregated nobody (deadline shorter than client
        # train time): the "final" model is the init model — fail loudly
        # instead of handing back rc=0
        print("ERROR: all rounds closed with zero participants; the "
              "model was never updated (round_timeout too short?)",
              file=sys.stderr, flush=True)
        sys.exit(2)


def run_client(args) -> None:
    _force_cpu_if_requested()
    from fedml_tpu.algorithms.fedavg_cross_device import FedAvgClientManager

    ds, bundle, init, lu = _build_problem(args.seed, args.num_clients,
                                          args.input_dim, args.train_samples)
    # clients ride out transient hub-connection drops: re-dial +
    # re-register, rejoining as a straggler for the missed round (the
    # server's round deadline covers the gap)
    plan = _chaos_plan()
    # -1 = role default (3): `or 3` would silently promote an EXPLICIT
    # --auto-reconnect 0 (fail-fast) back to reconnecting
    reconnect = args.auto_reconnect if args.auto_reconnect >= 0 else 3
    backend = _maybe_chaos(
        _connect_backend(args.node_id, args.host, args.port,
                         auto_reconnect=reconnect, wire=args.wire,
                         **_lane_kwargs(args)),
        "client", plan,
    )
    mgr = FedAvgClientManager(
        backend, lu, ds, batch_size=args.batch_size,
        template_variables=init, seed=args.seed,
        train_delay=args.train_delay,
        crash_at_round=_resolve_crash_round(
            args.crash_at_round, plan, args.node_id
        ),
        traffic=_traffic_model("client"),
    )
    # the client's registry used to die here with nothing but a stdout
    # counter dump — now the whole thing (trace_hop chains, clock_sync,
    # comm counters, handle-latency histograms) lands in this process's
    # own metrics-node<id>.jsonl for the timeline merger; the flusher
    # thread keeps the bounded event ring from evicting early chains
    # on long runs
    mlog = _node_metrics_logger(args.run_dir, f"node{args.node_id}")
    _install_flight(args.run_dir, f"node{args.node_id}")
    stop_flusher = _start_event_flusher(mlog)
    reporter = _start_stats_reporter(args, backend, mgr,
                                     nodes=[args.node_id])
    backend.run()  # returns on FINISH
    if reporter is not None:
        reporter.stop(final_flush=False)  # idempotent; FINISH flushed
    stop_flusher()
    if mlog is not None:
        mlog.log_telemetry()
        mlog.close()
    # reproducibility probe: the accumulated sha256 of every encoded
    # upload — two runs at the same seed must print identical digests
    # (the launcher collects these when asked)
    print(json.dumps({
        f"client_{args.node_id}_upload_digest": mgr.upload_digest,
        f"client_{args.node_id}_rounds_trained": mgr.rounds_trained,
    }), flush=True)


def run_muxer(args) -> None:
    """ONE process driving ``--virtual-clients`` virtual clients over
    ONE hub connection (node ids ``--node-id .. --node-id + N - 1``):
    hello-v2 registration, local demux of per-connection broadcast
    copies, and one vmapped jit step per round's co-located cohort —
    the process-per-client decoupling ROADMAP item 2 asks for."""
    _force_cpu_if_requested()
    from fedml_tpu.algorithms.fedavg_mux import FedAvgMuxClientManager

    ds, bundle, init, lu = _build_problem(args.seed, args.num_clients,
                                          args.input_dim, args.train_samples)
    node_ids = list(range(args.node_id,
                          args.node_id + max(1, args.virtual_clients)))
    plan = _chaos_plan()
    reconnect = args.auto_reconnect if args.auto_reconnect >= 0 else 3
    mux = _connect_mux_backend(node_ids, args.host, args.port,
                               auto_reconnect=reconnect, wire=args.wire,
                               **_lane_kwargs(args))
    # chaos parity: the plan wraps each VIRTUAL node's backend, so
    # fault decisions are keyed by virtual node id — the exact per-node
    # streams the one-process-per-client topology would draw
    wrap = None
    if plan is not None and "client" in plan.roles:
        from fedml_tpu.faults import ChaosBackend

        wrap = lambda vb: ChaosBackend(vb, plan)  # noqa: E731
    # crash schedule: the flag wins; otherwise ANY virtual id with a
    # plan-scheduled crash takes the whole muxer down at the EARLIEST
    # such round — a process crash is process-granular, so one virtual
    # client's schedule costs its co-located peers too (the honest
    # muxer blast radius; chaos_run's muxer_crash scenario)
    crash_rounds = [
        r for r in (_resolve_crash_round(args.crash_at_round, plan, n)
                    for n in node_ids)
        if r is not None
    ]
    mesh = None
    if getattr(args, "mesh", ""):
        from fedml_tpu.parallel.mesh import mesh_from_spec

        mesh = mesh_from_spec(args.mesh)
    mgr = FedAvgMuxClientManager(
        mux, lu, ds, batch_size=args.batch_size,
        template_variables=init, seed=args.seed,
        train_delay=args.train_delay,
        crash_at_round=min(crash_rounds) if crash_rounds else None,
        wrap_backend=wrap,
        rejoin_every_round=args.rejoin_every_round,
        traffic=_traffic_model("muxer"),
        mesh=mesh,
        partition_rules=getattr(args, "partition_rules", "") or None,
    )
    mlog = _node_metrics_logger(args.run_dir, f"mux{args.node_id}")
    _install_flight(args.run_dir, f"mux{args.node_id}")
    if mlog is not None:
        # timeline grouping evidence: fed_timeline parks every virtual
        # client's track under this muxer's process
        from fedml_tpu.obs.telemetry import get_telemetry

        get_telemetry().event("mux_members", muxer=args.node_id,
                              nodes=node_ids)
    stop_flusher = _start_event_flusher(mlog)
    # the muxer's ONE reporter pre-merges the whole virtual cohort:
    # its digest covers every co-located node id, so the hub/server
    # ingests one stream per connection (not per client) — the O(conns)
    # stats-plane cost model.  It sends through the primary virtual
    # node's chaos-wrapped endpoint (fault-plan parity).
    reporter = _start_stats_reporter(args, mgr.reporter_backend(), mgr,
                                     nodes=node_ids)
    mgr.run()  # returns on FINISH
    if reporter is not None:
        reporter.stop(final_flush=False)  # idempotent; FINISH flushed
    stop_flusher()
    if mlog is not None:
        mlog.log_telemetry()
        mlog.close()
    # the same per-client reproducibility probes the single-process
    # role prints — one line per virtual client, so digest comparisons
    # are topology-blind
    digests = mgr.upload_digests
    for n in node_ids:
        print(json.dumps({
            f"client_{n}_upload_digest": digests[n],
            f"client_{n}_rounds_trained": mgr.rounds_trained[n],
        }), flush=True)


def run_edge_hub(args) -> None:
    """ONE edge tier of the hierarchical aggregation tree: a LOCAL hub
    terminating the downstream cohort (node ids ``--node-id ..
    --node-id + --virtual-clients - 1``), the streaming partial fold,
    and one uplink connection to the root — the root's connection and
    fold load both shrink from O(clients) to O(edges)."""
    _force_cpu_if_requested()
    from fedml_tpu.algorithms.edge_hub import EdgeHubManager
    from fedml_tpu.comm.edge import EdgeUplinkBackend
    from fedml_tpu.comm.tcp import TcpHub
    from fedml_tpu.obs.telemetry import get_telemetry

    ds, bundle, init, lu = _build_problem(args.seed, args.num_clients,
                                          args.input_dim, args.train_samples)
    node_ids = list(range(args.node_id,
                          args.node_id + max(1, args.virtual_clients)))
    # the local tier is a full hub: stripes/shm lanes are PER-TIER
    # decisions, so each broadcast crosses each leg's wire exactly once
    # with that leg's own fan-out machinery
    hub = TcpHub("127.0.0.1", 0,
                 stripe_bytes=(args.stripe_kib << 10)
                 if args.fanout == "striped" else 0,
                 max_inflight_stripes=args.stripe_pace,
                 shm_min_bytes=args.shm_min_bytes)
    # announce the local port for the launcher's downstream workers
    print(json.dumps({"edge_port": hub.port}), flush=True)
    get_telemetry().gauge_set("hub.tier", 1)
    local = _connect_backend(0, "127.0.0.1", hub.port, wire=args.wire,
                             **_lane_kwargs(args))
    # downstream barrier BEFORE dialing the root: the uplink hello
    # claims the whole cohort's ids, which satisfies the root server's
    # startup barrier — so the cohort must actually be registered down
    # here first, or INIT would re-fan into dropped frames
    local.await_peers(node_ids, timeout=60 + 15 * len(node_ids))
    reconnect = args.auto_reconnect if args.auto_reconnect >= 0 else 3
    uplink = _dial_with_retry(
        lambda: EdgeUplinkBackend(node_ids, args.host, args.port,
                                  auto_reconnect=reconnect, wire=args.wire,
                                  **_lane_kwargs(args)))
    mgr = EdgeHubManager(
        uplink, local, hub, init,
        round_timeout=args.round_timeout or None,
        decode_workers=(args.decode_workers
                        if args.hotpath == "fast" else 0),
        defense=_defense_from_args(args), seed=args.seed,
        delta_base_window=args.delta_base_window,
        crash_at_round=(args.crash_at_round
                        if args.crash_at_round >= 0 else None),
    )
    mlog = _node_metrics_logger(args.run_dir, f"edge{args.node_id}")
    _install_flight(args.run_dir, f"edge{args.node_id}")
    stop_flusher = _start_event_flusher(mlog)
    mgr.start()
    mgr.run()  # blocks until FINISH drains + tears the tier down
    stop_flusher()
    if mlog is not None:
        mlog.log_telemetry()
        mlog.close()
    # per-edge accounting for the launcher/campaign (folded vs
    # forwarded-raw is the tree's composition evidence; peak RSS and
    # the local hub's churn counters let fed_tree_run/fed_scale_run
    # attribute memory and rebinds to the correct tier)
    import resource

    stats = mgr.stats()
    stats["peak_rss_kb"] = resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss
    try:
        stats["local_hub"] = hub.stats()
    except Exception:
        pass
    print(json.dumps({f"edge_{args.node_id}_stats": stats}),
          flush=True)


def launch(
    num_clients: int = 3,
    rounds: int = 2,
    *,
    seed: int = 0,
    batch_size: int = 16,
    out_path: str,
    extra_idle_clients: int = 0,
    kill_idle_after: float = 0.0,
    round_timeout: float = 0.0,
    slow_client_delay: float = 0.0,
    kill_slow_client_after: float = 0.0,
    crash_client_at_round: int = -1,
    restart_hub_after: float = 0.0,
    clients_per_round: int = 0,
    spares: int = 0,
    auto_reconnect: int = 0,
    muxers: int = 0,
    muxed_clients: int = 0,
    crash_muxer_at_round: int = -1,
    topology: str = "flat",
    edge_hubs: int = 0,
    crash_edge_hub_at_round: int = -1,
    chaos_plan: str = "",
    codec: str = "none",
    wire: int = 2,
    mesh: str = "",
    partition_rules: str = "",
    input_dim: int = 8,
    lane: str = "tcp",
    shm_mib: int = 64,
    shm_min_bytes: int = 1024,
    bcast: str = "full",
    bcast_codec: str = "",
    delta_base_window: int = 4,
    round_mode: str = "sync",
    cut_size: int = 0,
    max_staleness: int = 2,
    stale_policy: str = "poly",
    stale_alpha: float = 0.5,
    traffic_plan: str = "",
    mux_rejoin_every_round: bool = False,
    hotpath: str = "fast",
    fanout: str = "striped",
    stripe_kib: int = 256,
    stripe_pace: int = 8,
    decode_workers: int = 2,
    train_samples: int = 60,
    run_dir: str = "",
    trace: bool = False,
    stats_plane: str = "on",
    report_interval: float = 1.0,
    slo: str = "",
    defense: str = "none",
    norm_bound: float = 0.0,
    outlier_mult: float = 0.0,
    conn_cap: float = 0.0,
    dp_clip: float = 0.0,
    dp_noise: float = 0.0,
    trim_frac: float = 0.2,
    info=None,
    env=None,
    server_env=None,
    timeout: float = 180.0,
):
    """Spawn hub + server + clients as OS processes and wait for the
    federation to finish; returns the server's exit code (0 = the
    configured rounds completed and ``out_path`` was written).

    ``extra_idle_clients`` registers clients beyond ``num_clients`` that
    the server never samples — one is SIGKILLed once the launcher has
    CONFIRMED its hub registration (``await_peers``), exercising the
    hub's dead-peer handling mid-run without wedging the round.

    ``slow_client_delay`` makes the LAST sampled client (node id
    ``num_clients``) sleep that long before each local update;
    ``kill_slow_client_after`` SIGKILLs it mid-sleep — i.e. a SAMPLED
    client dies mid-round.  With ``round_timeout`` set the server's
    deadline aggregates without it and logs the dropout.

    Chaos knobs (``tools/chaos_run.py`` and the marked-slow scenarios in
    ``tests/test_distributed_process.py``):

    - ``crash_client_at_round``: the LAST sampled client hard-exits
      (``os._exit``) when that round's sync arrives — deterministic
      SIGKILL-at-round-r;
    - ``restart_hub_after``: SIGKILL the hub that long after the whole
      federation registered, then restart it on the SAME port — workers
      must auto-reconnect (pass ``auto_reconnect``) and the deadline
      must absorb the frames lost in the outage;
    - ``chaos_plan``: ``FaultPlan`` JSON shipped to workers via the
      ``FEDML_TPU_CHAOS`` env var (message-level drop/corrupt/...);
    - ``info``: optional dict the launcher fills with the server's
      final stdout JSON (fault counters) and the hub's shutdown stats.

    Virtual-client multiplexing: with ``muxers=M`` the first
    ``muxed_clients`` client ids (default: ALL of them) are driven by M
    muxer processes instead of one process each — client count and
    process count decouple, which is how a 10,000-client federation
    fits on one box.  Remaining ids still get dedicated client
    processes (a MIXED cohort: muxed + per-process + old hello-v1
    dialers on one hub).  ``crash_muxer_at_round`` hard-exits the FIRST
    muxer when that round's sync arrives — hundreds of virtual clients
    vanish at once (the ``muxer_crash`` chaos scenario).

    Hierarchical aggregation (``topology="tree"``, ``edge_hubs=E``):
    the sampled id space is partitioned contiguously into E edge-hub
    cohorts — WHOLE worker processes (a muxer and its full virtual
    range, or a per-process client) are assigned to exactly one edge —
    and each cohort's workers dial their edge's LOCAL hub instead of
    the root.  The edge folds its cohort's uploads into one partial
    aggregate per round (``algorithms/edge_hub``), so the root sees E
    connections and E folds instead of O(clients); the fp64 num/den
    partials compose exactly, so the final model is byte-identical to
    the flat run's.  Idle clients stay on the root hub (they are never
    sampled — pure connection load, which is the root's job to carry).
    ``crash_edge_hub_at_round`` hard-exits the FIRST edge hub when that
    round's sync arrives — a whole cohort orphaned at once (the
    ``edge_hub_crash`` chaos scenario; the round degrades visibly and
    the federation finishes NaN-free on the survivors).
    """
    env = dict(env or os.environ)
    if server_env is not None:
        server_env = dict(server_env)
    if chaos_plan:
        env["FEDML_TPU_CHAOS"] = chaos_plan
        if server_env is not None:
            server_env["FEDML_TPU_CHAOS"] = chaos_plan
    if traffic_plan:
        # open-loop traffic rides the env exactly like the chaos plan:
        # workers parse TrafficModel JSON before their jax imports
        env["FEDML_TPU_TRAFFIC"] = traffic_plan
        if server_env is not None:
            server_env["FEDML_TPU_TRAFFIC"] = traffic_plan
    if trace:
        # distributed tracing rides the env: every process (hub,
        # server, clients) stamps hops and shares one run id so the
        # merged timeline is self-correlating
        extra = {"FEDML_TPU_TRACE": "1",
                 "FEDML_TPU_RUN_ID": f"fed-s{seed}-n{num_clients}"}
        env.update(extra)
        if server_env is not None:
            server_env.update(extra)
    me = [sys.executable, "-m", "fedml_tpu.experiments.distributed_fedavg"]
    rd_flags = ["--run-dir", run_dir] if run_dir else []
    hub = None
    hubs = []
    procs = []
    killed_registered_peer = False
    try:
        hub_flags = rd_flags + ["--fanout", fanout,
                                "--stripe-kib", str(stripe_kib),
                                "--stripe-pace", str(stripe_pace)]
        if shm_min_bytes != 1024:
            hub_flags += ["--shm-min-bytes", str(shm_min_bytes)]
        hub = subprocess.Popen(
            me + ["--role", "hub", "--port", "0"] + hub_flags,
            stdout=subprocess.PIPE, text=True, env=env,
        )
        hubs.append(hub)
        port_line = hub.stdout.readline()
        if not port_line:
            raise RuntimeError("hub died before announcing its port")
        port = json.loads(port_line)["hub_port"]
        common = ["--host", "127.0.0.1", "--port", str(port),
                  "--num-clients", str(num_clients), "--rounds", str(rounds),
                  "--seed", str(seed), "--batch-size", str(batch_size)] \
            + rd_flags
        if codec and codec != "none":
            common += ["--codec", codec]
        if mesh:
            # muxer cohorts step on a dp x mp device mesh; other roles
            # ignore the flags (same pattern as --codec)
            common += ["--mesh", mesh]
        if partition_rules:
            common += ["--partition-rules", partition_rules]
        if wire != 2:
            common += ["--wire", str(wire)]
        if input_dim != 8:
            common += ["--input-dim", str(input_dim)]
        if lane != "tcp":
            common += ["--lane", lane]
        if shm_mib != 64:
            common += ["--shm-mib", str(shm_mib)]
        if shm_min_bytes != 1024:
            common += ["--shm-min-bytes", str(shm_min_bytes)]
        if bcast != "full":
            common += ["--bcast", bcast]
        if bcast_codec:
            common += ["--bcast-codec", bcast_codec]
        if delta_base_window != 4:
            common += ["--delta-base-window", str(delta_base_window)]
        if round_mode != "sync":
            common += ["--round-mode", round_mode]
        if cut_size:
            common += ["--cut-size", str(cut_size)]
        if max_staleness != 2:
            common += ["--max-staleness", str(max_staleness)]
        if stale_policy != "poly":
            common += ["--stale-policy", stale_policy]
        if stale_alpha != 0.5:
            common += ["--stale-alpha", str(stale_alpha)]
        if hotpath != "fast":
            common += ["--hotpath", hotpath]
        if decode_workers != 2:
            common += ["--decode-workers", str(decode_workers)]
        if train_samples != 60:
            common += ["--train-samples", str(train_samples)]
        if stats_plane != "on":
            common += ["--stats-plane", stats_plane]
        if report_interval != 1.0:
            common += ["--report-interval", str(report_interval)]
        if slo:
            common += ["--slo", slo]
        if round_timeout:
            common += ["--round-timeout", str(round_timeout)]
        if clients_per_round:
            # required for spares to bite: with the default (everyone
            # sampled) broadcast_size = min(K+S, num_clients) collapses
            # back to K and over-sampling is a no-op
            common += ["--clients-per-round", str(clients_per_round)]
        if spares:
            common += ["--spares", str(spares)]
        if auto_reconnect:
            common += ["--auto-reconnect", str(auto_reconnect)]
        # robust-aggregation knobs: the server's decision — and in tree
        # topology ALSO each edge hub's, because per-upload screening
        # runs at the edge with the identical config (clients stay
        # oblivious either way)
        defense_flags = []
        if defense != "none":
            defense_flags += ["--defense", defense]
        for flag, val, dflt in (("--norm-bound", norm_bound, 0.0),
                                ("--outlier-mult", outlier_mult, 0.0),
                                ("--conn-cap", conn_cap, 0.0),
                                ("--dp-clip", dp_clip, 0.0),
                                ("--dp-noise", dp_noise, 0.0),
                                ("--trim-frac", trim_frac, 0.2)):
            if val != dflt:
                defense_flags += [flag, str(val)]
        # worker units in node-id order: each is an indivisible PROCESS
        # (a muxer owns its whole contiguous virtual-id range), which
        # is the granularity the tree partition assigns to edges
        muxed = 0
        mux_specs = []
        if muxers:
            muxed = min(muxed_clients or num_clients, num_clients)
            base_sz, rem = divmod(muxed, muxers)
            start = 1
            for j in range(muxers):
                size = base_sz + (1 if j < rem else 0)
                if size > 0:
                    mux_specs.append((start, size))
                    start += size
        units = [("muxer", s, sz) for s, sz in mux_specs] \
            + [("client", i + 1, 1) for i in range(muxed, num_clients)]
        use_tree = topology == "tree" and edge_hubs > 0
        if use_tree:
            # contiguous partition balanced by client count: a unit
            # lands in the group whose proportional share its ids fall
            # into, so whole muxers never straddle an edge boundary
            tree_groups = [[] for _ in range(edge_hubs)]
            acc, gi = 0, 0
            for u in units:
                tree_groups[gi].append(u)
                acc += u[2]
                if (gi < edge_hubs - 1
                        and acc >= (gi + 1) * num_clients / edge_hubs):
                    gi += 1
            groups = [g for g in tree_groups if g]
        else:
            groups = [units] if units else []
        mux_procs = []
        clients = []
        edge_procs = []
        tier_flags = ["--fanout", fanout,
                      "--stripe-kib", str(stripe_kib),
                      "--stripe-pace", str(stripe_pace)]
        for gi, group in enumerate(groups):
            wport = port
            if use_tree:
                first = group[0][1]
                count = sum(u[2] for u in group)
                ep = subprocess.Popen(
                    me + ["--role", "edge_hub", "--node-id", str(first),
                          "--virtual-clients", str(count)]
                    + common + defense_flags + tier_flags
                    + (["--crash-at-round", str(crash_edge_hub_at_round)]
                       if crash_edge_hub_at_round >= 0 and gi == 0
                       else []),
                    stdout=subprocess.PIPE, text=True, env=env,
                )
                edge_procs.append(ep)
                line = ep.stdout.readline()
                if not line:
                    raise RuntimeError(
                        f"edge hub {gi} died before announcing its port")
                wport = json.loads(line)["edge_port"]
            # the cohort dials ITS tier's hub: a trailing --port
            # overrides the root port baked into `common` (argparse
            # keeps the last occurrence)
            port_override = ([] if wport == port
                             else ["--port", str(wport)])
            for kind, start, size in group:
                if kind == "muxer":
                    mux_procs.append(subprocess.Popen(
                        me + ["--role", "muxer", "--node-id", str(start),
                              "--virtual-clients", str(size)] + common
                        + port_override
                        + (["--rejoin-every-round"]
                           if mux_rejoin_every_round else [])
                        + (["--crash-at-round", str(crash_muxer_at_round)]
                           if crash_muxer_at_round >= 0 and mux_specs
                           and (start, size) == mux_specs[0] else []),
                        env=env,
                        # muxer stdout carries one upload-digest JSON
                        # line PER virtual client — digest comparisons
                        # against a per-process run are topology-blind
                        stdout=(subprocess.PIPE if info is not None
                                else None),
                        text=True if info is not None else None,
                    ))
                else:
                    clients.append(subprocess.Popen(
                        me + ["--role", "client",
                              "--node-id", str(start)] + common
                        + port_override
                        + (["--train-delay", str(slow_client_delay)]
                           if slow_client_delay and start == num_clients
                           else [])
                        + (["--crash-at-round",
                            str(crash_client_at_round)]
                           if crash_client_at_round >= 0
                           and start == num_clients else []),
                        env=env,
                        # client stdout carries the upload-digest JSON
                        # line the compression measurement compares
                        # across re-runs
                        stdout=(subprocess.PIPE if info is not None
                                else None),
                        text=True if info is not None else None,
                    ))
        procs += mux_procs + clients + edge_procs
        idle = [
            subprocess.Popen(
                me + ["--role", "client",
                      "--node-id", str(num_clients + 1 + j)] + common,
                env=env,
            )
            for j in range(extra_idle_clients)
        ]
        procs += idle
        # server_env lets the SERVER run on a different backend than the
        # clients — e.g. aggregation on the one real TPU chip while 16
        # client processes train on CPU (only one process may hold the
        # tunnel lease)
        server = subprocess.Popen(
            me + ["--role", "server", "--out", out_path] + common
            + defense_flags,
            env=dict(server_env) if server_env is not None else env,
            stdout=subprocess.PIPE if info is not None else None,
            text=True if info is not None else None,
        )
        procs.append(server)
        if restart_hub_after:
            # wait until the WHOLE federation registered (the startup
            # barrier passed), let a round get going, then SIGKILL the
            # hub and restart it on the same port: every worker must
            # re-dial + re-register, and frames lost in the outage are
            # absorbed by the round deadline
            from fedml_tpu.comm.tcp import TcpBackend

            mon = TcpBackend(9997, "127.0.0.1", port)
            mon.await_peers([0] + list(range(1, num_clients + 1)),
                            timeout=60 + 15 * num_clients)
            mon.stop()
            time.sleep(restart_hub_after)
            hub.kill()  # SIGKILL: no sentinel, no graceful close
            hub.wait(timeout=10)
            time.sleep(0.5)  # a beat of real downtime
            hub = subprocess.Popen(
                me + ["--role", "hub", "--port", str(port)] + hub_flags,
                stdout=subprocess.PIPE, text=True, env=env,
            )
            hubs.append(hub)
            if not hub.stdout.readline():
                raise RuntimeError("restarted hub died before binding")
        if kill_slow_client_after and slow_client_delay and clients:
            # wait until EVERYONE (clients + server) is registered — the
            # server's await_peers barrier has then passed, so killing
            # the slow client can no longer wedge startup; by now it is
            # asleep in its first local update (train_delay) — a SAMPLED
            # client dying mid-round
            from fedml_tpu.comm.tcp import TcpBackend

            mon = TcpBackend(9998, "127.0.0.1", port)
            mon.await_peers([0] + list(range(1, num_clients + 1)),
                            timeout=60 + 15 * num_clients)
            mon.stop()
            time.sleep(kill_slow_client_after)
            clients[-1].kill()
        if idle:
            # monitor connection: wait until the doomed peer is actually
            # registered, so the kill exercises hub dead-peer cleanup
            # rather than landing on a process that never connected
            from fedml_tpu.comm.tcp import TcpBackend

            monitor = TcpBackend(9999, "127.0.0.1", port)
            monitor.await_peers([num_clients + 1],
                                timeout=60 + 15 * num_clients)
            if kill_idle_after:
                time.sleep(kill_idle_after)
            idle[0].kill()
            killed_registered_peer = True
            monitor.stop()
        rc = server.wait(timeout=timeout)
        if info is not None:
            _collect_json_lines(server.stdout, info)
        for c in clients + mux_procs + edge_procs:
            out = None
            try:
                if c.stdout is not None:
                    # communicate DRAINS stdout while waiting: a muxer
                    # prints one digest line per virtual client, which
                    # overruns the 64 KB pipe at a few hundred virtual
                    # clients — a bare wait() would deadlock against
                    # the child's blocked write and then kill it
                    out, _ = c.communicate(timeout=30)
                else:
                    c.wait(timeout=30)
            except subprocess.TimeoutExpired:
                # a wedged client must not fail the launcher: under
                # chaos a client whose FINISH was lost blocks forever —
                # reap it (the server outcome is what the caller asserts)
                c.kill()
                if c.stdout is not None:
                    try:
                        out, _ = c.communicate(timeout=5)
                    except Exception:
                        out = None
            if info is not None and out:
                _collect_json_lines(out, info)
        if extra_idle_clients:
            assert killed_registered_peer
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if hub is not None and hub.poll() is None:
            hub.terminate()
            hub.wait(timeout=10)
            if info is not None:
                _collect_json_lines(hub.stdout, info)
        for h in hubs:
            if h.poll() is None:
                h.kill()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--role",
                   choices=["hub", "server", "client", "muxer",
                            "edge_hub"],
                   required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--node-id", type=int, default=0)
    # muxer role: ONE process drives this many virtual clients (node
    # ids --node-id .. --node-id + N - 1) over ONE hub connection,
    # training each round's cohort in one vmapped jit step — client
    # count and process count decouple (ROADMAP item 2)
    p.add_argument("--virtual-clients", type=int, default=1)
    p.add_argument("--num-clients", type=int, default=3)
    p.add_argument("--clients-per-round", type=int, default=0)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--out", default="")
    # straggler knobs: server-side round deadline (s; 0 = wait forever,
    # the reference's behavior) and client-side artificial train delay
    p.add_argument("--round-timeout", type=float, default=0.0)
    p.add_argument("--train-delay", type=float, default=0.0)
    # fault-tolerance knobs (chaos layer): over-sampled spare clients,
    # reconnect budget (-1 = role default: 0 for the server — legacy
    # fail-fast — and 3 for clients; an explicit 0 means 0 for both),
    # deterministic client crash
    p.add_argument("--spares", type=int, default=0)
    p.add_argument("--auto-reconnect", type=int, default=-1)
    p.add_argument("--crash-at-round", type=int, default=-1)
    # update-compression knobs (fedml_tpu/compress): the server
    # announces --codec on every sync; clients encode their delta with
    # it.  --wire 1 forces legacy JSON/base64 frames (the baseline arm
    # of the bytes measurement); --input-dim scales the model so byte
    # ratios measure payload, not envelope.
    p.add_argument("--codec", default="none")
    # rule-driven sharding (parallel/partition.py): the muxer lays its
    # virtual cohort over dp and the model over mp in ONE jit step;
    # other roles accept and ignore the flags (launch() appends them to
    # the shared flag block, like --codec)
    p.add_argument("--mesh", default="")
    p.add_argument("--partition-rules", default="")
    p.add_argument("--wire", type=int, choices=[1, 2], default=2)
    p.add_argument("--input-dim", type=int, default=8)
    # raw-speed transport knobs (fedml_tpu/comm/shm.py +
    # fedavg_cross_device delta mode): --lane shm moves same-box
    # payload bytes through a per-connection shared-memory ring slab
    # (--shm-mib sized, payloads under --shm-min-bytes stay inline;
    # cross-host peers / full rings fall back to TCP per frame,
    # counted); --bcast delta ships each sync as the int8-encoded
    # chain update against the receiver's last-acked round
    # (--bcast-codec overrides the chain codec, --delta-base-window
    # bounds the per-round delta log — older bases get a full resend)
    p.add_argument("--lane", choices=["tcp", "shm"], default="tcp")
    p.add_argument("--shm-mib", type=int, default=64)
    p.add_argument("--shm-min-bytes", type=int, default=1024)
    p.add_argument("--bcast", choices=["full", "delta"], default="full")
    p.add_argument("--bcast-codec", default="")
    p.add_argument("--delta-base-window", type=int, default=4)
    # churn-soak knob (muxer role): drop + re-hello the hub connection
    # and forget delta bases after every trained round
    p.add_argument("--rejoin-every-round", action="store_true")
    # async buffered rounds (server role): fold-on-arrival with cuts
    # every --cut-size arrivals (0 = clients_per_round) instead of the
    # synchronous barrier; in-window stale uploads (base round within
    # --max-staleness of current) fold at the --stale-policy discount
    # w(r-b) — poly: (1+d)^-alpha, const: 1 inside the window — while
    # out-of-window ones still hit the reject firewall.  --stale-alpha 0
    # is the byte-identity arm (w == 1 exactly).
    p.add_argument("--round-mode", choices=["sync", "async"],
                   default="sync")
    p.add_argument("--cut-size", type=int, default=0)
    p.add_argument("--max-staleness", type=int, default=2)
    p.add_argument("--stale-policy", choices=["poly", "const"],
                   default="poly")
    p.add_argument("--stale-alpha", type=float, default=0.5)
    # wire hot-path knobs: --hotpath legacy reverts the server to
    # per-node unicast broadcast + buffered close-time aggregation (the
    # pre-multicast behavior — the latency measurement's baseline arm
    # and the interop mode for peers that can't derive identity from
    # their node id); --train-samples scales per-client local compute
    # so latency runs can pick a comm-dominant regime
    p.add_argument("--hotpath", choices=["fast", "legacy"], default="fast")
    # fan-out/pipeline knobs: --fanout striped splits hub multicast
    # payloads into --stripe-kib KiB crc'd stripes, head-starts every
    # receiver's stripe 0, then drains tails at --stripe-pace frames
    # per connection per drain quantum (small pace = fair round-robin
    # streaming, large = staggered-completion locality; whole = the
    # PR-5 whole-frame baseline); --decode-workers sizes the server's
    # off-reader-thread upload decode pool (0 = serial decode on the
    # reader thread, the pre-pipeline behavior)
    p.add_argument("--fanout", choices=["striped", "whole"],
                   default="striped")
    p.add_argument("--stripe-kib", type=int, default=256)
    p.add_argument("--stripe-pace", type=int, default=8)
    p.add_argument("--decode-workers", type=int, default=2)
    p.add_argument("--train-samples", type=int, default=60)
    # observability knobs: --run-dir makes EVERY process (hub included)
    # append its telemetry registry to its own metrics-<tag>.jsonl in
    # the shared directory; --trace turns on per-hop distributed trace
    # stamping (equivalent to FEDML_TPU_TRACE=1 in the environment);
    # --stats-interval paces the hub's periodic gauge snapshot
    p.add_argument("--run-dir", default="")
    p.add_argument("--trace", action="store_true")
    p.add_argument("--stats-interval", type=float, default=1.0)
    # in-band stats plane (fedml_tpu/obs/digest + obs/slo): clients and
    # muxers ship one mergeable telemetry-digest frame per
    # --report-interval per CONNECTION; the server merges the rollup,
    # evaluates --slo objectives per round, and (with --run-dir) writes
    # the live status.json + final slo_report.json that tools/fed_slo.py
    # renders.  --stats-plane off is the overhead-measurement baseline.
    p.add_argument("--stats-plane", choices=["on", "off"], default="on")
    p.add_argument("--report-interval", type=float, default=1.0)
    p.add_argument("--slo", default="",
                   help="SLO spec: inline JSON or a path to a JSON file "
                        "(obs/slo.SloSpec fields)")
    # robust-aggregation knobs (fedml_tpu/robust; server role):
    # --defense streaming = per-upload norm clip (--norm-bound) +
    # outlier-score reject (--outlier-mult, in units of the bound) +
    # per-connection contribution caps (--conn-cap, fraction of round
    # weight — the anti-Sybil lever for muxed cohorts); --defense
    # median|trimmed_mean = buffered coordinate-wise Byzantine
    # estimators (--trim-frac per side).  --dp-clip/--dp-noise layer
    # client-level DP (delta clip + seeded gaussian noise) on any mode.
    p.add_argument("--defense",
                   choices=["none", "streaming", "median", "trimmed_mean"],
                   default="none")
    p.add_argument("--norm-bound", type=float, default=0.0)
    p.add_argument("--outlier-mult", type=float, default=0.0)
    p.add_argument("--conn-cap", type=float, default=0.0)
    p.add_argument("--dp-clip", type=float, default=0.0)
    p.add_argument("--dp-noise", type=float, default=0.0)
    p.add_argument("--trim-frac", type=float, default=0.2)
    args = p.parse_args(argv)
    if args.trace:
        # before any comm import reads (and caches) the switch
        os.environ["FEDML_TPU_TRACE"] = "1"
    if args.role == "hub":
        run_hub(args.host, args.port, args.run_dir, args.stats_interval,
                fanout=args.fanout, stripe_kib=args.stripe_kib,
                stripe_pace=args.stripe_pace,
                shm_min_bytes=args.shm_min_bytes)
    elif args.role == "server":
        run_server(args)
    elif args.role == "muxer":
        run_muxer(args)
    elif args.role == "edge_hub":
        run_edge_hub(args)
    else:
        run_client(args)


if __name__ == "__main__":
    main()
