"""Entry shim — reference parity with ``fedml_experiments/*/main_fednas.py``."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(["--algorithm", "fednas", *sys.argv[1:]])
