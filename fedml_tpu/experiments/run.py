"""Unified experiment entry point — the L5 layer.

The reference ships one ``main_<algo>.py`` + mpirun shell script per
algorithm (``fedml_experiments/``, SURVEY.md §1 L5).  Here a single
typed config + dispatcher covers the whole matrix; per-algorithm
``main_<algo>.py`` shims (same directory) preserve the familiar entry
names.  Usage:

    python -m fedml_tpu.experiments.run --algorithm fedavg \
        --model resnet56 --dataset cifar10 --client_num_in_total 10 \
        --client_num_per_round 4 --comm_round 10 --epochs 1

Flag names follow the reference's canonical set
(``main_fedavg.py:46-105``).  ``--ci 1`` shrinks everything for smoke
runs (reference ``FedAVGAggregator.py:115-120`` semantics).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

from fedml_tpu.core.config import config_to_json, parse_config
from fedml_tpu.core.metrics import MetricsLogger, setup_logging
from fedml_tpu.experiments.registry import (create_model, load_data,
                                            shrink_dataset,
                                            task_loss_for_dataset)

ALGORITHMS = (
    "fedavg", "fedopt", "fedprox", "fednova", "fedavg_robust",
    "hierarchical", "decentralized", "fedgkt", "fednas", "centralized",
    "turboaggregate", "splitnn", "vfl", "base_framework", "fedllm",
)


@dataclasses.dataclass
class ExperimentConfig:
    """Canonical experiment flags (reference main_fedavg.py:46-105)."""

    algorithm: str = "fedavg"
    model: str = "resnet56"
    dataset: str = "cifar10"
    data_dir: str = ""
    partition_method: str = "hetero"
    partition_alpha: float = 0.5
    client_num_in_total: int = 10
    client_num_per_round: int = 4
    batch_size: int = 64
    client_optimizer: str = "sgd"
    lr: float = 0.03
    momentum: float = 0.0
    wd: float = 0.001
    epochs: int = 1
    comm_round: int = 10
    frequency_of_the_test: int = 5
    seed: int = 0
    ci: int = 0
    # fedopt
    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    # fedprox
    mu: float = 0.1
    # robust
    defense_type: str = "norm_diff_clipping"
    norm_bound: float = 5.0
    stddev: float = 0.025
    # hierarchical
    group_num: int = 2
    group_comm_round: int = 2
    # fednas
    stage: str = "search"
    arch_lr: float = 3e-4
    lr_min: float = 0.001  # cosine weight-LR floor (--learning_rate_min)
    lambda_train_regularizer: float = 1.0
    arch_order: int = 1  # 2 = unrolled second-order DARTS architect
    # fedgkt
    temperature: float = 3.0
    alpha_kd: float = 1.0
    epochs_server: int = 1
    # fedllm (federated transformer fine-tuning; beyond-reference family)
    embed_dim: int = 64
    num_heads: int = 4
    num_layers: int = 2
    tp_degree: int = 1  # >1: DP x TP on a (clients, model) device mesh
    sp_degree: int = 1  # >1: DP x SP — long-context clients, ring attention
    # rule-driven sharding engine (fedml_tpu/parallel/partition.py):
    # --mesh "dp,mp" (also "dp=4,mp=2" / "auto,2") lays the cohort over
    # dp and the model over mp in ONE jit step; --partition_rules picks
    # the (regex -> PartitionSpec) table: a canonical name (fedllm,
    # resnet) or a JSON rule file.  Exclusive with tp/sp_degree;
    # composes with compress/compress_ef (the residual store shards
    # client rows over dp).
    mesh: str = ""
    partition_rules: str = ""
    # beyond-reference knobs available on the FedAvg-engine family
    compute_dtype: str = ""  # "bf16" = mixed-precision local training
    drop_prob: float = 0.0  # failure injection: P(client dies mid-round)
    # update compression (fedml_tpu/compress; FedAvg-engine family):
    # lossy uplink codec simulated inside the compiled round —
    # int8/qsgd8, int4/qsgd4, bf16, topk<rate>; "" = off.  compress_ef
    # threads the error-feedback residual store (required for topk).
    compress: str = ""
    compress_ef: int = 0
    # the reference's CIFAR-family loaders augment UNCONDITIONALLY
    # (crop+flip, +Cutout(16) for cifar10/100 — cifar10/data_loader.py:
    # 57-99, cifar100:85-91, cinic10:91-92); 0 disables for ablations
    data_augmentation: int = 1
    # smoke-tier shrink knobs (0 = unlimited): cap each client's shard /
    # the test set AFTER the real loader runs — the task is never swapped
    max_samples_per_client: int = 0
    max_test_samples: int = 0
    # observability: every main() run emits <run_dir>/metrics.jsonl
    # (per-round spans, comm counters, compile events — read it with
    # tools/trace_summary.py); "" = auto runs/<algo>-<dataset>-<stamp>
    run_dir: str = ""
    # fault tolerance (fedml_tpu/faults; FedAvg-engine family): save the
    # full (variables, opt state, round_idx, rng key) pytree every N
    # completed rounds; --resume 1 continues BIT-identically from the
    # latest readable checkpoint.  checkpoint_dir defaults to a stable
    # runs/ckpt/<algo>-<dataset>-seed<seed> path so a resumed process
    # finds its predecessor's saves without sharing a run_dir.
    checkpoint_every: int = 0
    checkpoint_dir: str = ""
    resume: int = 0
    # fault injection: hard-exit (os._exit, as a SIGKILL would) right
    # before this round trains — the crash half of the chaos layer's
    # crash-then-resume bit-identity check; -1 = off
    crash_at_round: int = -1


def _apply_ci(cfg: ExperimentConfig) -> ExperimentConfig:
    """``--ci 1`` = shrink-only smoke preset.

    The reference's CI substitutes the task itself (its CI scripts run a
    fixed tiny config regardless of flags), which lets broken (model,
    dataset) wiring survive — the round-2 stackoverflow_lr crash lived
    in exactly that blind spot.  Here CI clamps sizes via the public
    shrink knobs and NEVER changes algorithm/model/dataset/loss.
    """
    if cfg.ci:
        if cfg.algorithm == "fedllm":  # token-sequence family: keep task,
            # shrink the transformer too
            return dataclasses.replace(
                cfg,
                client_num_in_total=min(cfg.client_num_in_total, 4),
                client_num_per_round=min(cfg.client_num_per_round, 4),
                comm_round=min(cfg.comm_round, 2),
                batch_size=min(cfg.batch_size, 4),
                embed_dim=min(cfg.embed_dim, 32), num_layers=1,
                max_samples_per_client=cfg.max_samples_per_client or 16,
                max_test_samples=cfg.max_test_samples or 32,
            )
        return dataclasses.replace(
            cfg, client_num_in_total=min(cfg.client_num_in_total, 3),
            client_num_per_round=min(cfg.client_num_per_round, 3),
            comm_round=min(cfg.comm_round, 2), batch_size=min(cfg.batch_size, 8),
            max_samples_per_client=cfg.max_samples_per_client or 16,
            max_test_samples=cfg.max_test_samples or 64,
        )
    return cfg


def _run_fedllm(cfg: ExperimentConfig, ds, t0, log_fn, metrics=None) -> dict:
    """Federated transformer fine-tuning over token sequences (the
    long-context family the reference lacks).  Three drivers:

    - ``tp_degree == sp_degree == 1``: the standard simulation driver;
    - ``tp_degree > 1``: DP x TP on a (clients, model) mesh
      (``parallel/gspmd.py``), transformer Megatron-sharded inside
      every client;
    - ``sp_degree > 1``: DP x SP on a (clients, sp) mesh
      (``parallel/dp_sp.py``), each client's sequences sharded with
      ring attention — federated long-context fine-tuning.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.models.transformer import transformer_lm

    seq_len = int(ds.train_x.shape[1])
    vocab = max(int(ds.num_classes), int(ds.train_x.max()) + 1)
    bundle = transformer_lm(
        vocab_size=vocab, embed_dim=cfg.embed_dim, num_heads=cfg.num_heads,
        num_layers=cfg.num_layers, seq_len=seq_len,
    )

    if cfg.mesh and (cfg.tp_degree > 1 or cfg.sp_degree > 1):
        raise ValueError(
            "--mesh is the rule-driven sharding engine and is exclusive "
            "with tp_degree/sp_degree (those pick the heuristic meshes)"
        )
    if cfg.tp_degree <= 1 and cfg.sp_degree <= 1 and not cfg.mesh:
        from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation

        sim = FedAvgSimulation(bundle, ds, FedAvgConfig(
            num_clients=ds.num_clients,
            clients_per_round=min(cfg.client_num_per_round, ds.num_clients),
            comm_rounds=cfg.comm_round, epochs=cfg.epochs,
            batch_size=cfg.batch_size, client_optimizer=cfg.client_optimizer,
            lr=cfg.lr, momentum=cfg.momentum, weight_decay=cfg.wd,
            frequency_of_the_test=cfg.frequency_of_the_test, seed=cfg.seed,
            compute_dtype=cfg.compute_dtype or None, drop_prob=cfg.drop_prob,
        ), metrics=metrics)
        # run() already merges evaluate_global() into the final round
        hist = sim.run(log_fn=log_fn)
        return {"history": hist, "final": hist[-1],
                "wall_s": time.time() - t0}

    from fedml_tpu.algorithms.fedavg import ServerState, resolve_compute_dtype
    from fedml_tpu.core.client import make_client_optimizer, make_local_update
    from fedml_tpu.core.types import pack_clients

    if cfg.tp_degree > 1 and cfg.sp_degree > 1:
        raise ValueError(
            "tp_degree and sp_degree cannot both exceed 1 (a 3-D "
            "clients x model x sp mesh is not wired up)"
        )
    K = min(cfg.client_num_per_round, ds.num_clients)
    if cfg.mesh:
        from fedml_tpu.parallel.mesh import mesh_from_spec

        rule_mesh = mesh_from_spec(cfg.mesh)
        dp = int(rule_mesh.shape["dp"])
    else:
        degree = cfg.tp_degree if cfg.tp_degree > 1 else cfg.sp_degree
        if jax.device_count() % degree:
            raise ValueError(
                f"parallel degree {degree} does not divide device count "
                f"{jax.device_count()}"
            )
        dp = jax.device_count() // degree
    if K % dp:
        raise ValueError(f"cohort {K} not divisible by dp width {dp}")
    opt = make_client_optimizer(
        cfg.client_optimizer, cfg.lr, momentum=cfg.momentum,
        weight_decay=cfg.wd,
    )
    cdtype = resolve_compute_dtype(cfg.compute_dtype or None)
    key = jax.random.PRNGKey(cfg.seed)

    if cfg.mesh:
        from fedml_tpu.parallel.partition import (
            make_rule_round_fn, resolve_rules,
        )

        table = resolve_rules(cfg.partition_rules or "fedllm")
        lu = make_local_update(
            bundle, opt, epochs=cfg.epochs, compute_dtype=cdtype,
        )
        variables = bundle.init(key)
        codec = cfg.compress or None
        ef = bool(cfg.compress_ef) and codec is not None
        residuals = ()
        if ef:
            # EF residual store rows shard over dp alongside the cohort
            if ds.num_clients % dp:
                raise ValueError(
                    f"client_num_in_total {ds.num_clients} not divisible "
                    f"by dp width {dp} (the EF residual store shards its "
                    f"client rows over dp)"
                )
            residuals = jax.tree_util.tree_map(
                lambda l: jnp.zeros(
                    (ds.num_clients,) + l.shape, jnp.float32
                ),
                variables,
            )
        state = ServerState(
            variables=variables, opt_state=(),
            round_idx=jnp.zeros((), jnp.int32), key=key,
            residuals=residuals,
        )
        round_fn, shard_state, shard_data = make_rule_round_fn(
            rule_mesh, lu, variables, table,
            codec=codec, error_feedback=ef,
        )
        state = shard_state(state)
        mesh = rule_mesh
    elif cfg.tp_degree > 1:
        from fedml_tpu.parallel.gspmd import (
            make_dp_tp_mesh, make_dp_tp_round_fn,
        )

        mesh = make_dp_tp_mesh(dp, cfg.tp_degree)
        lu = make_local_update(
            bundle, opt, epochs=cfg.epochs, compute_dtype=cdtype,
        )
        state = ServerState(
            variables=bundle.init(key), opt_state=(),
            round_idx=jnp.zeros((), jnp.int32), key=key,
        )
        round_fn, shard_state, shard_data = make_dp_tp_round_fn(
            mesh, lu, state.variables
        )
        state = shard_state(state)
    else:
        # DP x SP: each client's sequences sharded over an sp axis with
        # ring attention — federated LONG-CONTEXT fine-tuning
        # (parallel/dp_sp.py; parity vs single-device in tests/test_dp_sp.py)
        from fedml_tpu.parallel.dp_sp import (
            make_dp_sp_mesh, make_dp_sp_round_fn,
        )

        if seq_len % cfg.sp_degree:
            raise ValueError(
                f"sequence length {seq_len} not divisible by sp_degree "
                f"{cfg.sp_degree}"
            )
        mesh = make_dp_sp_mesh(dp, cfg.sp_degree)
        round_fn, shard_data, init_fn = make_dp_sp_round_fn(
            mesh, vocab_size=vocab, embed_dim=cfg.embed_dim,
            num_heads=cfg.num_heads, num_layers=cfg.num_layers,
            max_len=seq_len, optimizer=opt, epochs=cfg.epochs,
            compute_dtype=cdtype,
            block_size=max(1, min(512, seq_len // cfg.sp_degree)),
        )
        state = ServerState(
            variables=init_fn(key), opt_state=(),
            round_idx=jnp.zeros((), jnp.int32), key=key,
        )
    hist = []
    from fedml_tpu.core.types import cohort_steps_per_epoch

    steps = cohort_steps_per_epoch(ds, cfg.batch_size)
    from fedml_tpu.core.sampling import host_sample_ids

    # same evaluator + cadence as the simulation driver, so all fedllm
    # paths stay comparable (jit runs the fp32 eval forward with the
    # TP-sharded or replicated variables in place — no gather needed)
    from fedml_tpu.core.client import eval_summary, make_evaluator
    from fedml_tpu.core.types import batch_eval_pack

    evaluator = make_evaluator(bundle)
    tx, ty, tm = batch_eval_pack(ds.test_x, ds.test_y, max(cfg.batch_size, 64))

    def eval_global(variables):
        return eval_summary(evaluator(
            variables, jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(tm)
        ))

    for r in range(cfg.comm_round):
        # shared sampler: all fedllm paths are cohort-comparable
        ids = host_sample_ids(cfg.seed, r, ds.num_clients, K)
        # round-independent pack seed: same convention as the simulation
        # and cross-device drivers (the local update re-permutes per
        # epoch on-device; the base order carries no stochasticity)
        pack = pack_clients(ds, ids, cfg.batch_size, steps_per_epoch=steps,
                            seed=cfg.seed, reuse_buffers=True)
        participation = np.ones(K, np.float32)
        if cfg.drop_prob > 0.0:
            from fedml_tpu.core.sampling import inject_dropout

            participation = np.asarray(inject_dropout(
                jax.random.PRNGKey(cfg.seed), r,
                jnp.asarray(participation), cfg.drop_prob,
            ))
        state, m = round_fn(state, *shard_data((
            pack.x, pack.y, pack.mask, pack.num_samples,
            participation, np.asarray(ids, np.int32),
        )))
        row = {"round": r, **{k: float(v) for k, v in m.items()}}
        if row.get("count"):
            row["train_loss"] = row["loss_sum"] / row["count"]
        if r % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1:
            row.update(eval_global(state.variables))
        hist.append(row)
        if metrics is not None:
            metrics.log(row, step=r)
        if log_fn:
            log_fn(row)
    return {"history": hist, "final": hist[-1], "mesh": str(mesh.shape),
            "wall_s": time.time() - t0}


# sims that take the observability sink directly (per-round spans, comm
# accounting, compile tracking live INSIDE their round loop); everything
# else gets its history rows logged post-hoc by run_experiment
_METRICS_NATIVE = frozenset((
    "fedavg", "fedprox", "fedopt", "fednova", "fedavg_robust",
    "hierarchical", "fedllm",
))

# the FedAvg-engine family: the only drivers wired into
# CheckpointManager (attach_checkpointing/resume on FedAvgSimulation)
_RESUMABLE = frozenset((
    "fedavg", "fedprox", "fedopt", "fednova", "fedavg_robust",
    "hierarchical",
))


def run_experiment(cfg: ExperimentConfig, log_fn=print, metrics=None) -> dict:
    cfg = _apply_ci(cfg)
    if (cfg.resume or cfg.checkpoint_every) and cfg.algorithm not in _RESUMABLE:
        # checked BEFORE any work: an explicit resume/checkpoint ask on
        # a driver without checkpoint wiring would otherwise be silently
        # ignored — for --resume that means retraining from round 0
        # while claiming rc=0, the exact masquerade the fail-loud
        # contract below exists to prevent
        raise SystemExit(
            f"--resume/--checkpoint_every: algorithm {cfg.algorithm!r} has "
            f"no checkpoint wiring (supported: {sorted(_RESUMABLE)})"
        )
    t0 = time.time()
    # a file-less logger still feeds the process telemetry registry;
    # main() passes a run_dir-backed one so metrics.jsonl is emitted
    metrics = metrics if metrics is not None else MetricsLogger()
    out = _dispatch(cfg, log_fn, metrics, t0)
    if cfg.algorithm not in _METRICS_NATIVE:
        # post-hoc logging for drivers without a metrics sink of their
        # own.  fednas --stage train already logged its TRAIN rounds
        # live (metrics= threaded into fednas_train_stage) under the
        # same round indices the search stage used — so the search rows
        # are written as kind-tagged records, keeping exactly one plain
        # round stream per file (trace_summary keys round rows on the
        # absence of "kind")
        search_aside = "train_history" in out
        for row in out.get("history") or []:
            if isinstance(row, dict):
                if search_aside:
                    metrics.log({"kind": "search_round", **row})
                else:
                    metrics.log(row, step=row.get("round"))
    return out


def _dispatch(cfg: ExperimentConfig, log_fn, metrics, t0) -> dict:
    """Algorithm switch: build data/model/driver and run (L5 body)."""
    if cfg.algorithm == "base_framework":  # tutorial template: no model/data
        from fedml_tpu.algorithms.base_framework import run_base_framework

        hist = run_base_framework(cfg.client_num_in_total, cfg.comm_round)
        return {"history": hist, "final": hist[-1] if hist else None,
                "wall_s": time.time() - t0}

    if cfg.algorithm == "vfl":  # vertical FL uses its own tabular data
        from fedml_tpu.algorithms.vfl import VerticalFederation, run_vfl
        from fedml_tpu.data.tabular import load_lending_club
        from fedml_tpu.models.finance import vfl_party

        x, y, splits = load_lending_club(cfg.data_dir or "./data/lending_club_loan")
        if cfg.max_samples_per_client:
            # the shrink contract holds for vfl too: parties share rows,
            # so cap the TABLE (train rows + test rows), not per-party
            cap = (cfg.max_samples_per_client * cfg.client_num_in_total
                   + (cfg.max_test_samples or 64))
            x, y = x[:cap], y[:cap]
        n_test = max(32, len(y) // 5)
        xs = [x[:, s] for s in splits]
        fed = VerticalFederation(
            [vfl_party(xi.shape[1], 16) for xi in xs], lr=cfg.lr
        )
        _, hist = run_vfl(
            fed, [xi[:-n_test] for xi in xs], y[:-n_test],
            [xi[-n_test:] for xi in xs], y[-n_test:],
            epochs=cfg.comm_round, batch_size=cfg.batch_size,
        )
        return {"history": hist, "wall_s": time.time() - t0}

    ds = shrink_dataset(
        load_data(cfg.dataset, cfg.data_dir, cfg.client_num_in_total,
                  cfg.partition_method, cfg.partition_alpha, cfg.seed),
        cfg.max_samples_per_client, cfg.max_test_samples,
    )
    loss_fn = task_loss_for_dataset(cfg.dataset)

    if cfg.algorithm == "splitnn":
        from fedml_tpu.algorithms.splitnn import SplitNNSimulation
        from fedml_tpu.models.cnn import cnn_split_pair

        bottom, top = cnn_split_pair(ds.num_classes, ds.train_x.shape[1:])
        parts = [
            (ds.train_x[idx], ds.train_y[idx])
            for idx in ds.train_client_idx.values()
        ]
        sim = SplitNNSimulation(
            bottom, top, parts, test_data=(ds.test_x, ds.test_y),
            batch_size=cfg.batch_size, lr=cfg.lr, seed=cfg.seed,
        )
        hist = []
        for _ in range(cfg.comm_round):
            hist.extend(sim.run_epoch())
        return {"history": hist, "wall_s": time.time() - t0}

    if cfg.algorithm == "fedgkt":
        from fedml_tpu.algorithms.fedgkt import FedGKT, FedGKTConfig
        from fedml_tpu.models.resnet_gkt import resnet8_56, resnet56_server

        img = ds.train_x.shape[1]
        algo = FedGKT(
            resnet8_56(ds.num_classes, img), resnet56_server(ds.num_classes, img),
            ds, FedGKTConfig(
                num_clients=ds.num_clients, comm_rounds=cfg.comm_round,
                epochs_client=cfg.epochs, epochs_server=cfg.epochs_server,
                batch_size=cfg.batch_size, lr_client=cfg.lr, lr_server=cfg.lr,
                temperature=cfg.temperature, alpha=cfg.alpha_kd, seed=cfg.seed,
            ))
        hist = algo.run()
        return {"history": hist, "wall_s": time.time() - t0}

    if cfg.algorithm == "fednas":
        from fedml_tpu.algorithms.fedavg import FedAvgConfig
        from fedml_tpu.algorithms.fednas import (FedNASConfig, FedNASSearch,
                                                 fednas_train_stage)
        from fedml_tpu.models.darts.search import darts_search

        img = ds.train_x.shape[1]
        chans = int(ds.train_x.shape[-1])
        search = FedNASSearch(
            darts_search(C=8, num_classes=ds.num_classes, layers=4,
                         image_size=img, in_channels=chans),
            ds, FedNASConfig(
                num_clients=ds.num_clients, comm_rounds=cfg.comm_round,
                epochs=cfg.epochs, batch_size=cfg.batch_size, lr=cfg.lr,
                lr_min=cfg.lr_min, arch_lr=cfg.arch_lr,
                lambda_train_regularizer=cfg.lambda_train_regularizer,
                arch_order=cfg.arch_order,
                seed=cfg.seed,
            ))
        hist = search.run()
        genotype = search.genotype()
        out = {"history": hist, "genotype": str(genotype),
               "wall_s": time.time() - t0}
        if cfg.stage == "train":
            # reference train stage: SGD(momentum, wd) + grad clip
            # (FedNASTrainer.py:134-141,185) — same knobs as search
            sim = fednas_train_stage(genotype, ds, metrics=metrics, config=FedAvgConfig(
                num_clients=ds.num_clients,
                clients_per_round=cfg.client_num_per_round,
                comm_rounds=cfg.comm_round, epochs=cfg.epochs,
                batch_size=cfg.batch_size, lr=cfg.lr, seed=cfg.seed,
                momentum=cfg.momentum or 0.9, weight_decay=cfg.wd,
                grad_clip=5.0,
            ), C=8, layers=4, image_size=img, in_channels=chans,
                lr_min=cfg.lr_min)
            out["train_history"] = sim.run(log_fn=log_fn)
        return out

    if cfg.algorithm == "fedllm":
        return _run_fedllm(cfg, ds, t0, log_fn, metrics=metrics)

    bundle = create_model(cfg.model, cfg.dataset, ds.num_classes,
                          input_shape=tuple(ds.train_x.shape[1:]))

    if cfg.algorithm == "centralized":
        from fedml_tpu.algorithms.centralized import CentralizedTrainer

        trainer = CentralizedTrainer(
            bundle, ds, batch_size=cfg.batch_size, lr=cfg.lr,
            optimizer=cfg.client_optimizer, weight_decay=cfg.wd,
            momentum=cfg.momentum, seed=cfg.seed, loss_fn=loss_fn,
        )
        hist = [trainer.train(epochs=cfg.epochs)
                for _ in range(cfg.comm_round)]
        hist[-1].update(trainer.evaluate())
        return {"history": hist, "final": hist[-1],
                "wall_s": time.time() - t0}

    if cfg.algorithm == "decentralized":
        from fedml_tpu.algorithms.decentralized import DecentralizedSimulation
        from fedml_tpu.core.topology import SymmetricTopologyManager

        tm = SymmetricTopologyManager(
            ds.num_clients, neighbor_num=min(2, ds.num_clients - 1),
            seed=cfg.seed,
        )
        sim = DecentralizedSimulation(
            bundle, ds, tm.generate_topology(), epochs=cfg.epochs,
            batch_size=cfg.batch_size, lr=cfg.lr, seed=cfg.seed,
            loss_fn=loss_fn,
        )
        hist = sim.run(cfg.comm_round)
        final = sim.evaluate_worker(0)
        return {"history": hist, "final": final, "wall_s": time.time() - t0}

    if cfg.algorithm == "turboaggregate":
        from fedml_tpu.algorithms.turboaggregate import (
            TurboAggregateConfig, TurboAggregateSimulation)

        algo = TurboAggregateSimulation(bundle, ds, TurboAggregateConfig(
            num_clients=ds.num_clients, comm_rounds=cfg.comm_round,
            epochs=cfg.epochs, batch_size=cfg.batch_size, lr=cfg.lr,
            seed=cfg.seed,
        ), loss_fn=loss_fn)
        hist = algo.run()
        return {"history": hist, "wall_s": time.time() - t0}

    # the FedAvg-engine family
    from fedml_tpu.algorithms import fedavg as fa

    # reference parity: the CIFAR-family loaders bake augmentation into
    # their train transform — published accuracies are unreachable
    # without it (measured: the r3 north-star run memorized).  Here it
    # is the jit-compiled per-epoch augment inside the local update.
    engine_kw = {"metrics": metrics}
    if cfg.data_augmentation and ds.train_x.ndim == 4:
        from fedml_tpu.data.augment import make_image_augment

        if cfg.dataset in ("cifar10", "cifar100"):
            engine_kw["augment_fn"] = make_image_augment(
                pad=4, flip=True, cutout=16)
        elif cfg.dataset == "cinic10":
            engine_kw["augment_fn"] = make_image_augment(
                pad=4, flip=True, cutout=None)

    common = dict(
        num_clients=ds.num_clients,
        clients_per_round=cfg.client_num_per_round,
        comm_rounds=cfg.comm_round, epochs=cfg.epochs,
        batch_size=cfg.batch_size, client_optimizer=cfg.client_optimizer,
        lr=cfg.lr, momentum=cfg.momentum, weight_decay=cfg.wd,
        frequency_of_the_test=cfg.frequency_of_the_test, seed=cfg.seed,
        compute_dtype=cfg.compute_dtype or None,
        drop_prob=cfg.drop_prob,
        compress_codec=cfg.compress or None,
        compress_ef=bool(cfg.compress_ef),
    )
    if cfg.algorithm == "fedavg":
        sim = fa.FedAvgSimulation(bundle, ds, fa.FedAvgConfig(**common),
                                  loss_fn=loss_fn, **engine_kw)
    elif cfg.algorithm == "fedprox":
        from fedml_tpu.algorithms.fedprox import FedProxSimulation

        sim = FedProxSimulation(bundle, ds, fa.FedAvgConfig(**common),
                                mu=cfg.mu, loss_fn=loss_fn, **engine_kw)
    elif cfg.algorithm == "fedopt":
        from fedml_tpu.algorithms.fedopt import FedOptSimulation

        sim = FedOptSimulation(
            bundle, ds, fa.FedAvgConfig(**common),
            server_optimizer=cfg.server_optimizer, server_lr=cfg.server_lr,
            loss_fn=loss_fn, **engine_kw,
        )
    elif cfg.algorithm == "fednova":
        nova_cfg = fa.FedAvgConfig(**{**common, "weight_decay": 0.0})
        from fedml_tpu.algorithms.fednova import FedNovaSimulation

        sim = FedNovaSimulation(bundle, ds, nova_cfg, loss_fn=loss_fn,
                                **engine_kw)
    elif cfg.algorithm == "fedavg_robust":
        from fedml_tpu.algorithms.fedavg_robust import FedAvgRobustSimulation

        sim = FedAvgRobustSimulation(
            bundle, ds, fa.FedAvgConfig(**common),
            defense_type=cfg.defense_type, norm_bound=cfg.norm_bound,
            stddev=cfg.stddev, loss_fn=loss_fn, **engine_kw,
        )
    elif cfg.algorithm == "hierarchical":
        from fedml_tpu.algorithms.hierarchical import HierarchicalSimulation

        sim = HierarchicalSimulation(
            bundle, ds, fa.FedAvgConfig(**common),
            num_groups=cfg.group_num, group_comm_round=cfg.group_comm_round,
            loss_fn=loss_fn, **engine_kw,
        )
    else:
        raise ValueError(f"unknown algorithm: {cfg.algorithm}")

    # no hasattr guard: run_experiment already refused non-_RESUMABLE
    # algorithms up front, and every _RESUMABLE driver is a
    # FedAvgSimulation subclass — a drifted entry should AttributeError
    # loudly here, not silently skip the resume
    done = 0
    if cfg.checkpoint_every or cfg.resume:
        import hashlib

        from fedml_tpu.core.checkpoint import CheckpointManager

        # the default dir must be (a) stable between the original run
        # and its `--resume 1` relaunch and (b) UNIQUE per experiment:
        # keying only (algo, dataset, seed) would let two sweep arms
        # differing in lr/model/... share a dir and silently resume
        # from each other's state (same treedef — no error would fire).
        # Hash the full config minus the knobs that legitimately differ
        # across the crash/resume pair.
        # comm_round excluded too: "train 6 rounds, then resume with
        # --comm_round 12 to extend" is the canonical resume move and
        # must map to the SAME directory
        stable = {k: v for k, v in dataclasses.asdict(cfg).items()
                  if k not in ("run_dir", "resume", "crash_at_round",
                               "checkpoint_dir", "checkpoint_every",
                               "comm_round")}
        tag = hashlib.sha1(
            json.dumps(stable, sort_keys=True).encode()
        ).hexdigest()[:10]
        ckdir = cfg.checkpoint_dir or os.path.join(
            "runs", "ckpt",
            f"{cfg.algorithm}-{cfg.dataset}-seed{cfg.seed}-{tag}",
        )
        sim.attach_checkpointing(
            CheckpointManager(ckdir), cfg.checkpoint_every or 1
        )
        if cfg.resume:
            done = sim.resume()
            if done == 0:
                # an EXPLICIT resume that restores nothing must fail
                # loudly: silently retraining from round 0 (typo'd
                # --checkpoint_dir, relocated default dir) would
                # masquerade as a successful resume
                raise SystemExit(
                    f"--resume 1: no readable checkpoint in {ckdir} "
                    "(pass the original --checkpoint_dir, or drop "
                    "--resume to start fresh)"
                )
    if cfg.crash_at_round >= 0:
        sim.crash_at_round = cfg.crash_at_round
    hist = sim.run(rounds=max(0, cfg.comm_round - done), log_fn=log_fn)
    # run() merges evaluate_global() into the final round already
    out = {"history": hist, "final": hist[-1] if hist else None,
           "wall_s": time.time() - t0}
    if done:
        out["resumed_rounds"] = done
    return out


def main(argv=None):
    cfg = parse_config(ExperimentConfig, argv)
    if cfg.algorithm not in ALGORITHMS:
        raise SystemExit(f"--algorithm must be one of {ALGORITHMS}")
    print(config_to_json(cfg))
    setup_logging()
    from fedml_tpu.obs.jax_hooks import (install_jax_monitoring,
                                         record_device_memory)

    install_jax_monitoring()
    # pid suffix: two arms of a sweep launched in the same wall-clock
    # second must not append into one metrics.jsonl
    run_dir = cfg.run_dir or os.path.join(
        "runs",
        f"{cfg.algorithm}-{cfg.dataset}-"
        f"{time.strftime('%Y%m%d-%H%M%S')}-p{os.getpid()}",
    )
    # context manager: the JSONL handle closes on EVERY exit path —
    # a crashed run still leaves a readable metrics.jsonl behind
    with MetricsLogger(run_dir=run_dir) as metrics:
        metrics.log({"kind": "config",
                     **json.loads(config_to_json(cfg))})
        # log_fn=None: with INFO logging on, MetricsLogger already
        # surfaces every row on the console — print would double it
        out = run_experiment(cfg, log_fn=None, metrics=metrics)
        # final telemetry merge: device-memory high-water gauges, then
        # counters/histograms + pending compile events into metrics.jsonl
        record_device_memory(metrics.telemetry)
        metrics.log_telemetry()
    tail = out.get("final") or (out["history"][-1] if out.get("history") else {})
    print(json.dumps({"final": tail, "wall_s": round(out["wall_s"], 2),
                      "run_dir": run_dir}, default=str))
    return out


if __name__ == "__main__":
    main(sys.argv[1:])
