"""Entry shim — reference parity with ``fedml_experiments/distributed/base_framework``."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(["--algorithm", "base_framework", *sys.argv[1:]])
