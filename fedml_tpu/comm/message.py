"""Message envelope + the FL control-plane vocabulary.

Reference ``fedml_core/distributed/communication/message.py:5-74``: a
typed key-value envelope with reserved keys for type/sender/receiver and
a JSON codec; model weights travel inside the dict
(``MSG_ARG_KEY_MODEL_PARAMS``).  The semantic message types come from
``fedml_api/distributed/fedavg/message_define.py:6-31``.

On TPU the data plane (weights) rides XLA collectives; this Message
layer is the HOST control plane for loosely-coupled/cross-device modes
(the reference's MQTT role) and for the inproc simulation backend.
Arrays are encoded as nested lists (the reference's
``transform_tensor_to_list`` codec, ``fedavg/utils.py:5-14``) or as
base64 float32 buffers — the compact default.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict

import numpy as np

# --- reserved keys ---------------------------------------------------------
MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"
MSG_ARG_KEY_LOCAL_METRICS = "local_metrics"

# --- message types (semantic vocabulary) -----------------------------------
MSG_TYPE_S2C_INIT_CONFIG = "S2C_INIT_CONFIG"
MSG_TYPE_S2C_SYNC_MODEL = "S2C_SYNC_MODEL"
MSG_TYPE_C2S_SEND_MODEL = "C2S_SEND_MODEL"
MSG_TYPE_C2S_SEND_STATS = "C2S_SEND_STATS"
MSG_TYPE_S2C_FINISH = "S2C_FINISH"
# split-learning extras (reference split_nn/message_define.py:6-16)
MSG_TYPE_C2S_SEND_ACTS = "C2S_SEND_ACTS"
MSG_TYPE_S2C_SEND_GRADS = "S2C_SEND_GRADS"
MSG_TYPE_C2C_SEMAPHORE = "C2C_SEMAPHORE"


class Message:
    def __init__(self, msg_type: str = "", sender: int = 0, receiver: int = 0):
        self.params: Dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender,
            MSG_ARG_KEY_RECEIVER: receiver,
        }

    # -- reference API surface --
    def add_params(self, key: str, value: Any) -> "Message":
        self.params[key] = value
        return self

    add = add_params

    def get(self, key: str, default=None) -> Any:
        return self.params.get(key, default)

    @property
    def type(self) -> str:
        return self.params[MSG_ARG_KEY_TYPE]

    @property
    def sender(self) -> int:
        return self.params[MSG_ARG_KEY_SENDER]

    @property
    def receiver(self) -> int:
        return self.params[MSG_ARG_KEY_RECEIVER]

    def to_json(self) -> str:
        return json.dumps(self.params, default=_encode_value)

    @classmethod
    def from_json(cls, payload: str) -> "Message":
        return cls.from_obj(json.loads(payload))

    @classmethod
    def from_obj(cls, obj: dict) -> "Message":
        """Build from an already-parsed JSON dict (avoids re-parsing
        multi-MB frames on hot receive paths)."""
        m = cls()
        m.params = {k: _decode_value(v) for k, v in obj.items()}
        return m

    def __repr__(self):
        return f"Message({self.type}, {self.sender}->{self.receiver}, keys={list(self.params)})"


# --- pytree <-> wire codecs -------------------------------------------------

def tree_to_wire(tree: Any) -> Any:
    """Pytree of arrays → JSON-able nested structure with b64 buffers."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten(tree)
    return {
        "__wiretree__": 1,
        "leaves": [_encode_array(np.asarray(l)) for l in leaves],
    }


def tree_from_wire(obj: Any, like: Any) -> Any:
    """Decode against a structural template ``like`` (same treedef)."""
    import jax

    leaves_like, treedef = jax.tree_util.tree_flatten(like)
    leaves = [_decode_array(e) for e in obj["leaves"]]
    assert len(leaves) == len(leaves_like), "wire/treedef leaf count mismatch"
    leaves = [
        np.asarray(l, dtype=np.asarray(ref).dtype).reshape(np.asarray(ref).shape)
        for l, ref in zip(leaves, leaves_like)
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _encode_array(a: np.ndarray) -> dict:
    return {
        "__ndarray__": base64.b64encode(np.ascontiguousarray(a).tobytes()).decode(),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def _decode_array(obj: dict) -> np.ndarray:
    buf = base64.b64decode(obj["__ndarray__"])
    return np.frombuffer(buf, dtype=np.dtype(obj["dtype"])).reshape(obj["shape"])


def _encode_value(v):
    if isinstance(v, np.ndarray):
        return _encode_array(v)
    if isinstance(v, (np.integer, np.floating)):
        return v.item()
    if hasattr(v, "dtype") and hasattr(v, "shape"):  # jax array
        return _encode_array(np.asarray(v))
    raise TypeError(f"not JSON-serializable: {type(v)}")


def _decode_value(v):
    """Recursive decode: arrays survive the roundtrip at ANY nesting depth
    (encoding recurses via json.dumps default=, so decoding must too)."""
    if isinstance(v, dict):
        if "__ndarray__" in v:
            return _decode_array(v)
        if "__wiretree__" in v:
            return v  # wire pytree: decoded lazily via tree_from_wire (needs template)
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def tensor_to_list(tree: Any) -> Any:
    """The reference's mobile/MQTT codec (``fedavg/utils.py:11-14``):
    arrays become nested python lists."""
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a).tolist(), tree)


def list_to_tensor(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(
        lambda l: np.asarray(l, dtype=np.float32),
        tree,
        is_leaf=lambda x: isinstance(x, list),
    )
