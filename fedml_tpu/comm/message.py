"""Message envelope + the FL control-plane vocabulary.

Reference ``fedml_core/distributed/communication/message.py:5-74``: a
typed key-value envelope with reserved keys for type/sender/receiver and
a JSON codec; model weights travel inside the dict
(``MSG_ARG_KEY_MODEL_PARAMS``).  The semantic message types come from
``fedml_api/distributed/fedavg/message_define.py:6-31``.

On TPU the data plane (weights) rides XLA collectives; this Message
layer is the HOST control plane for loosely-coupled/cross-device modes
(the reference's MQTT role) and for the inproc simulation backend.
Arrays are encoded as nested lists (the reference's
``transform_tensor_to_list`` codec, ``fedavg/utils.py:5-14``), as
base64 float32 buffers (wiretree v1, the legacy default), or — the
compact default since the compression subsystem — as **wiretree v2**:
raw numpy leaves that the frame codec (``to_frame``/``from_frame``)
ships as length-prefixed binary buffers after a one-line JSON header,
killing the 4/3x base64 inflation even for uncompressed traffic.  A v2
wiretree may additionally carry a ``codec`` name (``fedml_tpu.compress``
registry) and a ``delta`` flag: its leaves are then per-leaf codec
encodings of a model UPDATE rather than raw parameters.

Interop contract (pinned by ``tests/test_compress.py``): v1 frames
(b64 JSON lines) still decode everywhere, and a v2 wiretree serialized
through the legacy JSON path (``to_json``) degrades gracefully — its
raw leaves b64-encode like any array and decode back.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, List

import numpy as np

# --- reserved wire-format keys ---------------------------------------------
# Protocol vocabulary, defined ONCE (the fedlint wire-schema rule bans
# literal copies elsewhere — a second copy keeps "working" while the
# canonical one evolves).  ``TRACE_KEY`` (= "__trace__") lives in
# ``obs/trace_ctx.py`` with the same contract.
HUB_KEY = "__hub__"  # hub control frames (register/ack/ping/mcast/stop)
# __hub__ kind of a striped-multicast continuation frame: the hub splits
# a large mcast payload into fixed-size stripes fanned round-robin
# across connections; receivers reassemble (comm/tcp.py)
MCAST_STRIPE_KIND = "mcast_stripe"
# __hub__ kind of a muxed-delivery wrapper: a broadcast copy addressed
# to SEVERAL virtual node ids that share one physical connection (hello
# v2 registration).  The outer header names the target ids; the payload
# is ONE complete inner frame the demuxing backend fans out locally
# (comm/mux.py) — the shared payload crosses the wire once per
# CONNECTION, never once per virtual node.
MUX_KIND = "mux"
# shared-memory lane doorbell (comm/shm.py): a frame header carrying
# this key announces that its ``__binlen__`` payload bytes live in the
# connection's shm slab at this descriptor sequence number instead of
# following on the socket — the header (and frame ORDER) stays on TCP,
# only the payload bytes move through the ring
SHM_SEQ_KEY = "__shmseq__"
FRAME_BINLEN_KEY = "__binlen__"  # header: raw payload bytes that follow
FRAME_NDBUF_KEY = "__ndbuf__"  # header entry: [offset, nbytes] buffer ref
WIRETREE_KEY = "__wiretree__"  # wire pytree envelope (version tag)
NDARRAY_KEY = "__ndarray__"  # v1 b64 array leaf

# --- reserved keys ---------------------------------------------------------
MSG_ARG_KEY_TYPE = "msg_type"
MSG_ARG_KEY_SENDER = "sender"
MSG_ARG_KEY_RECEIVER = "receiver"
MSG_ARG_KEY_MODEL_PARAMS = "model_params"
MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
MSG_ARG_KEY_ROUND_INDEX = "round_idx"
MSG_ARG_KEY_LOCAL_METRICS = "local_metrics"

# --- message types (semantic vocabulary) -----------------------------------
MSG_TYPE_S2C_INIT_CONFIG = "S2C_INIT_CONFIG"
MSG_TYPE_S2C_SYNC_MODEL = "S2C_SYNC_MODEL"
MSG_TYPE_C2S_SEND_MODEL = "C2S_SEND_MODEL"
MSG_TYPE_C2S_SEND_STATS = "C2S_SEND_STATS"
MSG_TYPE_S2C_FINISH = "S2C_FINISH"
# in-band stats plane (fedml_tpu/obs/digest.py): one mergeable
# telemetry-digest frame per report interval per CONNECTION — the
# payload rides the reserved ``__digest__`` key (DIGEST_KEY, defined
# there), and the frame is deliberately outside faults.DEFAULT_FAULTABLE
# (observability loss must be injected explicitly, never as a side
# effect of a model-frame fault mix)
MSG_TYPE_C2S_TELEMETRY = "C2S_TELEMETRY"
# delta-broadcast resync (fedavg_cross_device): a client that received
# a delta sync against a base round it no longer caches (fresh process,
# rejoined muxer) asks the server for a full-model resend; the server
# clears the node's ack and unicasts the current round's full sync
MSG_TYPE_C2S_RESYNC = "C2S_RESYNC"
# sync-envelope param naming the base round a delta broadcast applies
# to (the receiver reconstructs base + the shipped per-round deltas)
MSG_ARG_KEY_DELTA_BASE = "delta_base"
# hierarchical aggregation (fedml_tpu/algorithms/edge_hub.py): an edge
# hub terminates its cohort's connections, folds their uploads with the
# same O(1) streaming aggregation the root runs, and uplinks ONE
# pre-folded (sum n·model, sum n) pair per round.  The num/den
# formulation composes exactly (fp64 sums are order-independent at
# training magnitudes), so a tree run's final model is byte-identical
# to the flat run's.  Registered in analysis/wire_schema.py: a literal
# copy of this tag in a second module is wire-format drift.
MSG_TYPE_E2S_PARTIAL = "E2S_PARTIAL"
# E2S_PARTIAL param: {node_id (str): num_samples} for every upload the
# edge folded into this frame — the root materializes them as this
# round's reporters (participation accounting, delta-broadcast acks,
# duplicate screening) without ever seeing the per-client models
MSG_ARG_KEY_CONTRIBUTORS = "contributors"
# split-learning extras (reference split_nn/message_define.py:6-16)
MSG_TYPE_C2S_SEND_ACTS = "C2S_SEND_ACTS"
MSG_TYPE_S2C_SEND_GRADS = "S2C_SEND_GRADS"
MSG_TYPE_C2C_SEMAPHORE = "C2C_SEMAPHORE"


class Message:
    # payload residency (shm lane): when a frame's binary payload was
    # mapped out of a shared-memory slab, the receiving backend attaches
    # the refcounted region here so consumers that hand the message to
    # ANOTHER thread (decode pools, chaos delay timers) can pin the
    # bytes past the delivery scope — see ``pin_payload``.  None (the
    # class default) = payload owns its memory, pinning is a no-op.
    _region = None

    def __init__(self, msg_type: str = "", sender: int = 0, receiver: int = 0):
        self.params: Dict[str, Any] = {
            MSG_ARG_KEY_TYPE: msg_type,
            MSG_ARG_KEY_SENDER: sender,
            MSG_ARG_KEY_RECEIVER: receiver,
        }
        # memoized to_frame_parts() encoding: broadcast fan-out and send
        # retries reuse ONE immutable buffer list instead of re-encoding
        # a multi-MB frame per receiver/attempt
        self._frame_parts = None

    # -- reference API surface --
    def add_params(self, key: str, value: Any) -> "Message":
        self.params[key] = value
        self._frame_parts = None  # invalidate any cached wire encoding
        return self

    add = add_params

    def get(self, key: str, default=None) -> Any:
        return self.params.get(key, default)

    @property
    def type(self) -> str:
        return self.params[MSG_ARG_KEY_TYPE]

    @property
    def sender(self) -> int:
        return self.params[MSG_ARG_KEY_SENDER]

    @property
    def receiver(self) -> int:
        return self.params[MSG_ARG_KEY_RECEIVER]

    def to_json(self) -> str:
        return json.dumps(self.params, default=_encode_value)

    def to_frame(self) -> bytes:
        """Binary wire frame: one JSON header line, then raw buffers.

        Every array value (at any nesting depth) is lifted out of the
        JSON into a concatenated binary payload and replaced by an
        ``{"__ndbuf__": [offset, nbytes], dtype, shape}`` reference;
        the header's top-level ``__binlen__`` key carries the payload
        length so readers (hub, backend) know exactly how many raw
        bytes follow the newline.  Messages without arrays serialize
        to a plain JSON line — readable and v1-identical.
        """
        return b"".join(self.to_frame_parts())

    def to_frame_parts(self) -> List:
        """Zero-copy form of ``to_frame``: a list of buffers (header
        line first, then raw array memoryviews) whose concatenation IS
        the frame.  Nothing multi-MB is copied: array leaves stay
        memoryviews over their backing storage, and transports write
        them with a vectored ``sendmsg`` instead of joining.

        The list is memoized on the instance (``add_params``
        invalidates), so broadcast fan-out, unicast fallback, and send
        retries all reuse ONE immutable encoding — the per-round sync
        frame is serialized exactly once however many nodes it reaches.
        """
        parts = self._frame_parts
        if parts is not None:
            return parts
        bufs: List = []
        header = _extract_buffers(self.params, bufs, [0])
        if not bufs:
            parts = [(self.to_json() + "\n").encode()]
        else:
            header[FRAME_BINLEN_KEY] = sum(len(b) for b in bufs)
            parts = [
                json.dumps(header, default=_encode_value).encode() + b"\n",
                *bufs,
            ]
        self._frame_parts = parts
        return parts

    def clone_for(self, receiver: int) -> "Message":
        """Shallow per-receiver copy (payload objects shared, nothing
        re-encoded): how the base multicast fan-out and the chaos
        layer's per-receiver faults address one node of a broadcast."""
        m = Message()
        m.params = dict(self.params)
        m.params[MSG_ARG_KEY_RECEIVER] = receiver
        # clones share the payload objects, so they share its residency:
        # a pinned clone must keep the SAME slab region alive
        m._region = self._region
        return m

    def pin_payload(self):
        """Keep a slab-resident payload alive past the delivery scope:
        returns a release callable the consumer MUST invoke when done
        (a no-op callable for ordinary heap-backed payloads).  Callers
        that defer work to another thread pin BEFORE scheduling."""
        region = self._region
        if region is None:
            return lambda: None
        region.retain()
        return region.release

    @classmethod
    def from_frame(cls, header_obj: dict, payload: bytes = b"") -> "Message":
        """Inverse of ``to_frame`` given the parsed header line and the
        raw payload bytes that followed it."""
        obj = {k: v for k, v in header_obj.items() if k != FRAME_BINLEN_KEY}
        return cls.from_obj(_inject_buffers(obj, payload))

    @classmethod
    def from_frame_bytes(cls, data: bytes) -> "Message":
        """Parse ONE complete binary frame held in memory (header line +
        raw payload): the stripe-reassembly inverse of ``to_frame``,
        where the frame arrives as buffered chunks instead of off a
        stream reader.  Raises ``ValueError`` on a frame with no header
        line or a payload shorter than its ``__binlen__`` announcement
        (a reassembly that lost bytes must surface as a dropped logical
        frame, never a half-decoded model)."""
        from fedml_tpu.comm.shm import split_frame_line

        end = split_frame_line(data)  # bytes OR slab memoryview
        if end < 0:
            raise ValueError("frame has no header line")
        nl = end - 1
        header = json.loads(bytes(data[:nl + 1])
                            if isinstance(data, memoryview)
                            else data[:nl + 1])
        # memoryview slices: the multi-MB payload is never copied —
        # decoded arrays are read-only views into ``data`` (exactly the
        # stream-reader path's buffer-sharing contract)
        payload = memoryview(data)[nl + 1:]
        binlen = header.get(FRAME_BINLEN_KEY) or 0
        if len(payload) < binlen:
            raise ValueError(
                f"frame payload truncated: {len(payload)} < {binlen}"
            )
        return cls.from_frame(header, payload[:binlen] if binlen
                              else b"")

    @classmethod
    def from_json(cls, payload: str) -> "Message":
        return cls.from_obj(json.loads(payload))

    @classmethod
    def from_obj(cls, obj: dict) -> "Message":
        """Build from an already-parsed JSON dict (avoids re-parsing
        multi-MB frames on hot receive paths)."""
        m = cls()
        m.params = {k: _decode_value(v) for k, v in obj.items()}
        return m

    def __repr__(self):
        return f"Message({self.type}, {self.sender}->{self.receiver}, keys={list(self.params)})"


# --- pytree <-> wire codecs -------------------------------------------------


def tree_to_wire(tree: Any, *, version: int = 2, codec=None, key=None,
                 delta: bool = False) -> Any:
    """Pytree of arrays → wire structure.

    ``version=2`` (default): raw numpy leaves, shipped as binary
    buffers by the frame codec (or b64 by the legacy JSON path).
    ``version=1``: the legacy b64 leaf dicts.  With ``codec`` (a
    ``fedml_tpu.compress`` LeafCodec) leaves are codec encodings of a
    model UPDATE, seeded by ``key`` — always a v2 wiretree; ``delta``
    marks the payload as an update to add to a base model rather than
    full parameters (the receiver checks this flag).
    """
    import jax

    if codec is not None:
        from fedml_tpu.compress import wire_encode_tree

        return {
            WIRETREE_KEY: 2,
            "codec": codec.name,
            "delta": bool(delta),
            "leaves": wire_encode_tree(codec, tree, key),
        }
    leaves, _ = jax.tree_util.tree_flatten(tree)
    if version == 1:
        return {
            WIRETREE_KEY: 1,
            "leaves": [_encode_array(np.asarray(l)) for l in leaves],
        }
    return {
        WIRETREE_KEY: 2,
        "leaves": [np.ascontiguousarray(np.asarray(l)) for l in leaves],
    }


def tree_codec_name(obj: Any) -> str:
    """Codec a wire pytree was encoded with ('' = uncompressed)."""
    return obj.get("codec", "") if isinstance(obj, dict) else ""


def tree_is_delta(obj: Any) -> bool:
    """True when the wire pytree carries a model UPDATE (add to base)."""
    return bool(obj.get("delta")) if isinstance(obj, dict) else False


def tree_from_wire(obj: Any, like: Any) -> Any:
    """Decode against a structural template ``like`` (same treedef).

    Handles every wire generation: v1 b64 leaf dicts, v2 raw arrays
    (or ``__ndbuf__``-injected views), a v2 tree that traveled the
    legacy JSON path (its raw leaves b64-rewrapped), and codec-encoded
    v2 trees (decoded to fp32 via the named codec — the caller applies
    the ``delta`` semantics).
    """
    import jax

    name = tree_codec_name(obj)
    if name and name != "none":
        from fedml_tpu.compress import get_codec, wire_decode_tree

        entries = [
            {**e, "enc": {k: (_decode_array(v)
                              if isinstance(v, dict) and NDARRAY_KEY in v
                              else np.asarray(v))
                          for k, v in e["enc"].items()}}
            for e in obj["leaves"]
        ]
        return wire_decode_tree(get_codec(name), entries, like)
    leaves_like, treedef = jax.tree_util.tree_flatten(like)
    leaves = [
        _decode_array(e) if isinstance(e, dict) and NDARRAY_KEY in e
        else np.asarray(e)
        for e in obj["leaves"]
    ]
    assert len(leaves) == len(leaves_like), "wire/treedef leaf count mismatch"
    leaves = [
        np.asarray(l, dtype=np.asarray(ref).dtype).reshape(np.asarray(ref).shape)
        for l, ref in zip(leaves, leaves_like)
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _np_dtype(name: str) -> np.dtype:
    """np.dtype with the ml_dtypes extras (bfloat16 etc.) registered —
    numpy alone rejects 'bfloat16' unless ml_dtypes was imported."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _encode_array(a: np.ndarray) -> dict:
    return {
        NDARRAY_KEY: base64.b64encode(np.ascontiguousarray(a).tobytes()).decode(),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def _decode_array(obj: dict) -> np.ndarray:
    buf = base64.b64decode(obj[NDARRAY_KEY])
    return np.frombuffer(buf, dtype=_np_dtype(obj["dtype"])).reshape(obj["shape"])


def _is_raw_array(v) -> bool:
    """A real array (numpy or jax) — NOT a numpy scalar, which stays an
    inline JSON number."""
    if isinstance(v, np.generic):
        return False
    return isinstance(v, np.ndarray) or (
        hasattr(v, "dtype") and hasattr(v, "shape") and hasattr(v, "nbytes")
    )


def _extract_buffers(v, bufs: List, offset: List[int]):
    """Deep-copy ``v`` with every raw array replaced by an
    ``__ndbuf__`` reference; a zero-copy memoryview of the array bytes
    appends to ``bufs`` (the view keeps its array alive)."""
    if _is_raw_array(v):
        a = np.ascontiguousarray(np.asarray(v))
        # reshape(-1) is copy-free on a contiguous array and gives a
        # 1-D buffer cast("B") accepts even for 0-d leaves
        try:
            b = memoryview(a.reshape(-1)).cast("B")
        except (TypeError, ValueError, BufferError):
            b = a.tobytes()  # exotic dtypes (ml_dtypes) may refuse cast
        ref = {
            FRAME_NDBUF_KEY: [offset[0], len(b)],
            "dtype": str(a.dtype),
            "shape": list(a.shape),
        }
        bufs.append(b)
        offset[0] += len(b)
        return ref
    if isinstance(v, dict):
        return {k: _extract_buffers(x, bufs, offset) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_extract_buffers(x, bufs, offset) for x in v]
    return v


def _inject_buffers(v, payload: bytes):
    """Inverse of ``_extract_buffers``: materialize ``__ndbuf__``
    references as (read-only) numpy views into ``payload``."""
    if isinstance(v, dict):
        if FRAME_NDBUF_KEY in v:
            off, n = v[FRAME_NDBUF_KEY]
            return np.frombuffer(
                payload[off:off + n], dtype=_np_dtype(v["dtype"])
            ).reshape(v["shape"])
        return {k: _inject_buffers(x, payload) for k, x in v.items()}
    if isinstance(v, list):
        return [_inject_buffers(x, payload) for x in v]
    return v


def _encode_value(v):
    if isinstance(v, np.ndarray):
        return _encode_array(v)
    if isinstance(v, (np.integer, np.floating)):
        return v.item()
    if hasattr(v, "dtype") and hasattr(v, "shape"):  # jax array
        return _encode_array(np.asarray(v))
    raise TypeError(f"not JSON-serializable: {type(v)}")


def _decode_value(v):
    """Recursive decode: arrays survive the roundtrip at ANY nesting depth
    (encoding recurses via json.dumps default=, so decoding must too)."""
    if isinstance(v, dict):
        if NDARRAY_KEY in v:
            return _decode_array(v)
        if WIRETREE_KEY in v:
            return v  # wire pytree: decoded lazily via tree_from_wire (needs template)
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def tensor_to_list(tree: Any) -> Any:
    """The reference's mobile/MQTT codec (``fedavg/utils.py:11-14``):
    arrays become nested python lists."""
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a).tolist(), tree)


def list_to_tensor(tree: Any, like: Any = None) -> Any:
    """Inverse of ``tensor_to_list``.  With ``like`` (a structural
    template) each leaf is cast to the TEMPLATE's dtype, so bf16/int
    leaves survive a list-codec wire round-trip; without it, the
    legacy float32 cast is preserved (the reference's mobile codec
    assumed f32 throughout)."""
    import jax

    if like is None:
        return jax.tree_util.tree_map(
            lambda l: np.asarray(l, dtype=np.float32),
            tree,
            is_leaf=lambda x: isinstance(x, list),
        )
    return jax.tree_util.tree_map(
        lambda l, ref: np.asarray(
            l, dtype=np.asarray(ref).dtype
        ).reshape(np.shape(ref)),
        tree,
        like,
        is_leaf=lambda x: isinstance(x, list),
    )
