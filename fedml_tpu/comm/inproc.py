"""In-process communication bus — the simulation backend.

Replaces the reference's "multi-node without a cluster" testing mode
(localhost mpirun, SURVEY.md §4.4) with a deterministic single-threaded
bus: messages enqueue globally in send order and are drained round-robin
by each node's ``run()`` (or by ``InprocBus.drain()`` for a fully
synchronous co-routine-style simulation).  No threads, no sleeps, no
polling — the reference's 0.3 s busy-wait loops (``com_manager.py:78``)
have no equivalent here.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict

from fedml_tpu.comm.backend import CommBackend
from fedml_tpu.comm.message import Message
from fedml_tpu.obs import trace_ctx
from fedml_tpu.obs.comm_obs import message_nbytes


class InprocBus:
    """Shared router for any number of InprocBackend endpoints."""

    def __init__(self):
        self.stopped: Dict[int, bool] = {}
        self._registered: Dict[int, bool] = {}
        self._backends: Dict[int, "InprocBackend"] = {}
        # one global FIFO of (receiver, msg): delivery follows true
        # cross-node send order, exactly as the drain docstring promises
        self._fifo: deque = deque()
        # quiesce hooks: called when the FIFO runs dry, may enqueue more
        # (return True if they did).  This is how the chaos layer models
        # LATE delivery deterministically: a held message re-enters the
        # bus only after everything in-flight drained — the synchronous
        # twin of a post-deadline straggler frame.
        self._quiesce_hooks = []

    def register(self, node_id: int) -> "InprocBackend":
        self._registered[node_id] = True
        self.stopped[node_id] = False
        return InprocBackend(node_id, self)

    def route(self, msg: Message) -> None:
        if msg.receiver not in self._registered:
            raise KeyError(f"unknown receiver {msg.receiver}")
        self._fifo.append(msg)

    def add_quiesce_hook(self, fn) -> None:
        """Register ``fn() -> bool`` to run at drain quiescence; a True
        return means it enqueued messages and the drain continues."""
        self._quiesce_hooks.append(fn)

    def drain(self, max_steps: int = 100000) -> int:
        """Deliver queued messages in global send order until quiescent;
        handlers may enqueue more.  Messages to stopped nodes are
        discarded (the node has finished).  Returns deliveries."""
        delivered = 0
        for _ in range(max_steps):
            if not self._fifo:
                # list-comp first: EVERY hook runs even if an earlier
                # one released something (any() alone would short-circuit
                # and starve later backends' held messages)
                if any([h() for h in self._quiesce_hooks]):
                    continue
                return delivered
            msg = self._fifo.popleft()
            if self.stopped.get(msg.receiver, True):
                continue
            # wire size stamped once at send time (the bus never
            # serializes; re-estimating per delivery would double cost)
            self._backends[msg.receiver]._notify(
                msg, nbytes=getattr(msg, "wire_nbytes", None)
            )
            delivered += 1
        raise RuntimeError("inproc bus did not quiesce (message storm?)")

    def attach(self, backend: "InprocBackend"):
        self._backends[backend.node_id] = backend


class InprocBackend(CommBackend):
    def __init__(self, node_id: int, bus: InprocBus):
        super().__init__(node_id)
        self.bus = bus
        bus.attach(self)

    def send_message(self, msg: Message) -> None:
        t0 = time.perf_counter()
        # hop stamps on the simulation bus too (no hub hops): the same
        # msg OBJECT travels to the receiver, so stamping is strictly
        # copy-on-write (trace_ctx.stamp_ctx forks the hop list)
        trace_ctx.ensure(msg, self.node_id)
        trace_ctx.stamp_msg(msg, self.node_id, "send")
        msg.wire_nbytes = message_nbytes(msg)
        self.bus.route(msg)
        self._record_send(msg, msg.wire_nbytes, time.perf_counter() - t0)

    def run(self) -> None:
        # synchronous: delivery is driven by bus.drain()
        self.bus.drain()

    def stop(self) -> None:
        self.bus.stopped[self.node_id] = True
