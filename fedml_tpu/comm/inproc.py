"""In-process communication bus — the simulation backend.

Replaces the reference's "multi-node without a cluster" testing mode
(localhost mpirun, SURVEY.md §4.4) with a deterministic single-threaded
bus: messages enqueue globally in send order and are drained round-robin
by each node's ``run()`` (or by ``InprocBus.drain()`` for a fully
synchronous co-routine-style simulation).  No threads, no sleeps, no
polling — the reference's 0.3 s busy-wait loops (``com_manager.py:78``)
have no equivalent here.
"""

from __future__ import annotations

from collections import deque
from typing import Dict

from fedml_tpu.comm.backend import CommBackend
from fedml_tpu.comm.message import Message


class InprocBus:
    """Shared router for any number of InprocBackend endpoints."""

    def __init__(self):
        self.queues: Dict[int, deque] = {}
        self.stopped: Dict[int, bool] = {}
        self._backends: Dict[int, "InprocBackend"] = {}

    def register(self, node_id: int) -> "InprocBackend":
        self.queues[node_id] = deque()
        self.stopped[node_id] = False
        return InprocBackend(node_id, self)

    def route(self, msg: Message) -> None:
        if msg.receiver not in self.queues:
            raise KeyError(f"unknown receiver {msg.receiver}")
        self.queues[msg.receiver].append(msg)

    def drain(self, max_steps: int = 100000) -> int:
        """Deliver queued messages (in global arrival order across nodes)
        until quiescent; handlers may enqueue more.  Returns deliveries."""
        delivered = 0
        for _ in range(max_steps):
            progressed = False
            for node_id, q in self.queues.items():
                if q and not self.stopped[node_id]:
                    msg = q.popleft()
                    self._backends[node_id]._notify(msg)
                    delivered += 1
                    progressed = True
                    break  # strict global ordering
            if not progressed:
                return delivered
        raise RuntimeError("inproc bus did not quiesce (message storm?)")

    def attach(self, backend: "InprocBackend"):
        self._backends[backend.node_id] = backend


class InprocBackend(CommBackend):
    def __init__(self, node_id: int, bus: InprocBus):
        super().__init__(node_id)
        self.bus = bus
        bus.attach(self)

    def send_message(self, msg: Message) -> None:
        self.bus.route(msg)

    def run(self) -> None:
        # synchronous: delivery is driven by bus.drain()
        self.bus.drain()

    def stop(self) -> None:
        self.bus.stopped[self.node_id] = True
