"""Reactor-plane primitives for the hub data plane (``comm/tcp.py``).

The selector-driven hub replaces the two-blocking-threads-per-connection
read plane with ONE event-loop thread, which needs two things a blocking
``makefile('rb')`` reader never did:

- **A streaming frame parser** (`FrameParser`): the loop reads whatever
  the kernel has — half a header, three pipelined frames, the tail of a
  payload — and the parser turns those arbitrary chunk boundaries back
  into whole v1/v2 frames, incrementally, per connection.

- **Refcounted reusable payload buffers** (`BufPool` / `BufRegion`):
  inbound TCP payload bytes land directly in a pooled buffer that
  implements the same ``retain()/release()/view`` protocol as
  ``shm.ShmRegion``, so the routing layer pins ONE buffer per enqueued
  copy and the bytes are reclaimed (for reuse) when the last send queue
  drains them — the TCP-inbound twin of the shm lane's zero-copy pins,
  closing the last materializing hop on the hub path.

Both are transport-pure and single-connection-scoped: no hub state, no
sockets, no locks beyond the pool's freelist — which is what makes them
unit-testable against torn/pipelined byte streams without a federation.
"""

from __future__ import annotations

import json
import threading
from typing import List, Optional, Tuple

from fedml_tpu.comm.message import FRAME_BINLEN_KEY, SHM_SEQ_KEY

# An unterminated "header" this large is a binary flood or a garbage
# peer, never a frame: real inbound headers top out at the mcast
# receiver list (~7 bytes/id), so 64 MiB covers about 9M explicit ids —
# far past the point where range claims take over — while still killing
# a runaway accumulation long before it exhausts hub memory.
DEFAULT_MAX_HEADER = 64 << 20

# Largest ``__binlen__`` a header may announce.  The bound exists so a
# malformed (but valid-JSON) header can't drive ``pool.acquire`` into a
# multi-gigabyte ``bytearray`` allocation: anything past it is
# connection-fatal like a garbled header, never a MemoryError in the
# event loop.  4 GiB clears every real payload (full fp32 model
# broadcasts included) by a wide margin.
DEFAULT_MAX_PAYLOAD = 4 << 30

# Pool buffers below this round up to one page-friendly class; tiny
# payloads (control frames) then share a handful of hot buffers instead
# of fragmenting the freelist into dozens of size classes.
_MIN_CLASS = 1 << 12


class FrameError(Exception):
    """Connection-fatal parse failure.  Mirrors the blocking reader's
    policy: frames may carry raw binary payloads, so a stream that
    garbles one header can never resynchronize — the connection dies,
    the peer re-dials (its retry/auto_reconnect path)."""


class BufRegion:
    """One inbound TCP payload's refcounted window into a pooled buffer.

    Same contract as ``shm.ShmRegion``: created with one reference (the
    reader's delivery scope); every consumer that outlives that scope
    ``retain()``s first and ``release()``s when done — the buffer
    returns to its pool only at zero.  ``view`` is writable (the
    reactor ``recv_into``s the payload straight into it) but consumers
    treat it as immutable once the frame completes."""

    __slots__ = ("_pool", "_buf", "view", "_refs")

    def __init__(self, pool: "BufPool", buf: bytearray, nbytes: int):
        self._pool = pool
        self._buf = buf
        self.view = memoryview(buf)[:nbytes]
        self._refs = 1

    def retain(self) -> None:
        with self._pool._lock:
            self._refs += 1

    def release(self) -> None:
        pool = self._pool
        with pool._lock:
            self._refs -= 1
            if self._refs > 0:
                return
            pool.live -= 1
        try:
            self.view.release()
        except BufferError:
            # a consumer kept a numpy view alive past its release() —
            # the memoryview object survives via that reference; reuse
            # is still safe because such a consumer broke the retain
            # contract and its arrays are dead by contract
            pass
        pool._recycle(self._buf)


class BufPool:
    """Size-classed freelist of reusable payload buffers, bounded.

    ``acquire(n)`` hands out a ``BufRegion`` over a buffer of the next
    power-of-two class ≥ n (reused when the freelist has one, fresh
    otherwise); a region's final ``release()`` recycles the buffer
    unless the pool already holds ``max_pooled_bytes`` — beyond the
    bound buffers are simply dropped to the allocator, so a burst of
    giant payloads can't pin memory forever.  ``live`` counts regions
    handed out and not yet fully released — the leak-test observable
    (a churn soak must drive it back to 0)."""

    def __init__(self, max_pooled_bytes: int = 64 << 20):
        self._lock = threading.Lock()
        self._free = {}  # size class -> [bytearray, ...]
        self._pooled = 0
        self._max = int(max_pooled_bytes)
        self.live = 0      # regions out, not yet released to zero
        self.acquires = 0  # total acquire() calls
        self.reuses = 0    # acquires served from the freelist

    @staticmethod
    def _size_class(nbytes: int) -> int:
        if nbytes <= _MIN_CLASS:
            return _MIN_CLASS
        return 1 << (nbytes - 1).bit_length()

    def acquire(self, nbytes: int) -> BufRegion:
        size = self._size_class(nbytes)
        buf = None
        with self._lock:
            self.acquires += 1
            self.live += 1
            lst = self._free.get(size)
            if lst:
                buf = lst.pop()
                self._pooled -= size
                self.reuses += 1
        if buf is None:
            buf = bytearray(size)
        return BufRegion(self, buf, nbytes)

    def _recycle(self, buf: bytearray) -> None:
        size = len(buf)
        with self._lock:
            if self._pooled + size <= self._max:
                self._free.setdefault(size, []).append(buf)
                self._pooled += size

    def stats(self) -> dict:
        with self._lock:
            return {"live": self.live, "acquires": self.acquires,
                    "reuses": self.reuses, "pooled_bytes": self._pooled}


class FrameParser:
    """Incremental v1/v2 frame parser for ONE connection's byte stream.

    Two states.  In HEADER the parser accumulates bytes up to the
    newline that ends the JSON header line; in PAYLOAD it owns a pooled
    ``BufRegion`` sized to the header's ``__binlen__`` and fills it.
    The reactor drives it with a recv-into contract instead of feed():

        n = sock.recv_into(parser.recv_target())
        for hdr, line, payload, region in parser.consumed(n): ...

    ``recv_target()`` is the scratch buffer while parsing headers and
    the region's unfilled tail once in PAYLOAD — so payload bytes land
    in their final resting place in one kernel copy, with no
    intermediate buffer.  Only a payload PREFIX that arrives in the
    same chunk as its header crosses the scratch (copied once into the
    region; bounded by one scratch read).  Chunk boundaries are
    arbitrary: a header torn across reads accumulates, a payload torn
    across reads fills incrementally, and several pipelined frames in
    one read all come back from one ``consumed()`` call.

    Frames whose header carries the shm doorbell key (``__shmseq__``)
    announce payload bytes that live in the connection's slab, NOT on
    the stream — they complete immediately with no payload/region; the
    hub maps the slab bytes itself.  A garbled header (bad JSON,
    non-UTF-8 bytes, or JSON that isn't an object), a ``__binlen__``
    that isn't a non-negative int within ``max_payload_bytes``, and an
    unterminated header past ``max_header_bytes`` all raise
    ``FrameError`` — connection-fatal, the blocking reader's exact
    policy.

    Completed frames are ``(hdr, line, payload, region)``: the parsed
    header dict, the raw header line (newline included — forwarding
    paths ship it verbatim), the payload (a region view, or ``b""``
    for header-only frames), and the ``BufRegion`` backing it (or
    ``None``).  The caller owns the region's initial reference."""

    HEADER = 0
    PAYLOAD = 1

    __slots__ = ("_pool", "_scratch", "_sview", "_hdr", "_max_hdr",
                 "_max_payload", "_state", "_fhdr", "_fline", "_region",
                 "_filled", "_need")

    def __init__(self, pool: Optional[BufPool] = None,
                 scratch_bytes: int = 256 << 10,
                 max_header_bytes: int = DEFAULT_MAX_HEADER,
                 max_payload_bytes: int = DEFAULT_MAX_PAYLOAD,
                 scratch: Optional[bytearray] = None):
        self._pool = pool if pool is not None else BufPool()
        # ``scratch`` may be SHARED across every parser of one event
        # loop: scratch bytes never survive a consumed() call (partial
        # headers accumulate into the parser-owned ``_hdr``, payload
        # prefixes copy into the region inside the same ``_feed``), and
        # a single-threaded loop reads one socket at a time — so one
        # hub-wide buffer replaces a per-connection allocation that
        # would otherwise dominate reactor RSS at high fan-in
        # (512 conns x 256 KiB = 128 MB of idle scratch).
        self._scratch = (scratch if scratch is not None
                         else bytearray(scratch_bytes))
        self._sview = memoryview(self._scratch)
        self._hdr = bytearray()        # partial header across chunks
        self._max_hdr = int(max_header_bytes)
        self._max_payload = int(max_payload_bytes)
        self._state = self.HEADER
        self._fhdr: Optional[dict] = None
        self._fline: bytes = b""
        self._region: Optional[BufRegion] = None
        self._filled = 0
        self._need = 0

    def recv_target(self) -> memoryview:
        """The buffer the next ``recv_into`` should fill."""
        if self._state == self.PAYLOAD:
            return self._region.view[self._filled:]
        return self._sview

    def close(self) -> None:
        """Release the in-progress frame's region (the connection died
        mid-payload) so the pool's ``live`` accounting returns to zero
        even for torn streams."""
        if self._region is not None:
            self._region.release()
            self._region = None
        self._state = self.HEADER

    def consumed(self, n: int) -> List[Tuple]:
        """Process ``n`` bytes just written into ``recv_target()``;
        return every frame they completed (possibly none, possibly
        several)."""
        if self._state == self.PAYLOAD:
            self._filled += n
            if self._filled < self._need:
                return []
            return [self._finish()]
        return self._feed(n)

    def _fatal(self, frames: List[Tuple], msg: str):
        """Connection-fatal parse failure: release every completed-but-
        undelivered frame's region (the caller never sees them) plus
        any in-progress one, then raise."""
        for _hdr, _line, _payload, region in frames:
            if region is not None:
                region.release()
        self.close()
        raise FrameError(msg)

    def _feed(self, n: int) -> List[Tuple]:
        frames: List[Tuple] = []
        pos = 0
        while pos < n:
            if self._state == self.PAYLOAD:
                # a header earlier in this chunk opened a payload; its
                # prefix rides the same scratch read — copy it into the
                # region (the only scratch->region copy on the path)
                take = min(n - pos, self._need - self._filled)
                self._region.view[self._filled:self._filled + take] = \
                    self._sview[pos:pos + take]
                self._filled += take
                pos += take
                if self._filled >= self._need:
                    frames.append(self._finish())
                continue
            idx = self._scratch.find(b"\n", pos, n)
            if idx < 0:
                self._hdr += self._sview[pos:n]
                if len(self._hdr) > self._max_hdr:
                    self._fatal(frames,
                                f"unterminated header past "
                                f"{self._max_hdr} bytes — binary flood "
                                f"or garbage peer")
                break
            if self._hdr:
                self._hdr += self._sview[pos:idx + 1]
                line = bytes(self._hdr)
                self._hdr = bytearray()
            else:
                line = bytes(self._sview[pos:idx + 1])
            pos = idx + 1
            if len(line) > self._max_hdr:
                self._fatal(frames,
                            f"header line of {len(line)} bytes "
                            f"exceeds the {self._max_hdr} cap")
            try:
                # ValueError, not JSONDecodeError: non-UTF-8 bytes
                # raise UnicodeDecodeError (a ValueError sibling, NOT a
                # JSONDecodeError) and must hit the same fatal path —
                # otherwise one binary-garbage peer kills the loop
                hdr = json.loads(line)
            except ValueError as e:
                self._fatal(frames, f"garbled header: {e}")
            if not isinstance(hdr, dict):
                self._fatal(frames,
                            f"header is {type(hdr).__name__}, "
                            f"not an object")
            binlen = hdr.get(FRAME_BINLEN_KEY)
            if binlen and SHM_SEQ_KEY not in hdr:
                # binlen comes off the wire: a non-numeric, negative,
                # or absurd value must die as a FrameError here, never
                # escape as ValueError / broken PAYLOAD state /
                # MemoryError inside pool.acquire
                try:
                    need = int(binlen)
                except (TypeError, ValueError):
                    need = -1
                if need < 0 or need > self._max_payload:
                    self._fatal(frames,
                                f"bad {FRAME_BINLEN_KEY} {binlen!r}: "
                                f"need an int in "
                                f"[0, {self._max_payload}]")
                if need:
                    self._need = need
                    self._fhdr = hdr
                    self._fline = line
                    self._region = self._pool.acquire(need)
                    self._filled = 0
                    self._state = self.PAYLOAD
                    continue
                # truthy binlen that still parses to 0 (e.g. "0"):
                # fall through — a zero-byte payload is a header-only
                # frame, same as a falsy/absent binlen
            # header-only frame: v1 line, control frame, or an shm
            # doorbell whose bytes live in the slab
            frames.append((hdr, line, b"", None))
        return frames

    def _finish(self) -> Tuple:
        region = self._region
        out = (self._fhdr, self._fline, region.view, region)
        self._state = self.HEADER
        self._fhdr = None
        self._fline = b""
        self._region = None
        self._filled = 0
        self._need = 0
        return out
