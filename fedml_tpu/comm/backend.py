"""Communication backend protocol.

Reference ``fedml_core/distributed/communication/base_com_manager.py:7-27``
(+ ``observer.py:4-7``): ``send_message`` / ``add_observer`` /
``handle_receive_message`` / ``stop_receive_message``, with backend
selection by string switch (``client_manager.py:19-28``).

Rebuild (SURVEY.md §2.7): a ``CommBackend`` protocol with

- ``inproc``  — deterministic in-process bus (simulation; replaces the
  reference's localhost-mpirun testing mode, no threads, no polling);
- ``tcp``     — JSON-lines-over-TCP hub for the loosely-coupled
  cross-device role the reference gives MQTT (works cross-process /
  cross-host on DCN, zero external deps);
- ``ici``     — NOT a message backend at all: inside a slice the data
  plane is XLA collectives compiled into the round program
  (``fedml_tpu.parallel.spmd``); only control metadata would ever
  travel here.

The reference's thread-kill-via-ctypes and 0.3 s polling loops
(SURVEY.md §5.2) are designed away: inproc is synchronous, tcp uses
blocking socket reads on a dedicated reader thread with a sentinel
shutdown message, and device-side sync is XLA's.
"""

from __future__ import annotations

import abc
import logging
import time
from typing import Callable, Dict, List, Optional

from fedml_tpu.comm.message import Message
from fedml_tpu.obs import comm_obs, trace_ctx

Handler = Callable[[Message], None]


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: str, msg: Message) -> None:
        ...


class CommBackend(abc.ABC):
    """Transport: deliver Message envelopes between integer node ids."""

    def __init__(self, node_id: int):
        self.node_id = node_id
        self._observers: List[Observer] = []

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    def send_multicast(self, msg: Message, receivers) -> None:
        """Fan ONE message out to many receivers.

        Base implementation: per-receiver shallow clones through
        ``send_message`` (payload objects shared, so nothing is
        re-encoded).  Transports with a native fan-out primitive (the
        TCP hub's ``__hub__: mcast`` frame) override this to ship the
        payload once; the chaos wrapper overrides it to apply fault
        rules per receiver — a dropped copy is one node's, not the
        whole broadcast's.
        """
        for r in receivers:
            self.send_message(msg.clone_for(int(r)))

    def set_stripe_fault_hook(self, hook) -> None:
        """Install a per-stripe fault hook (chaos layer).  Transports
        without striped delivery (inproc; tcp wire 1) have no stripes
        to fault — the base implementation ignores the hook, so a
        stripe-faulting plan degrades to a no-op instead of an
        AttributeError on those transports.  ``TcpBackend`` overrides
        with its reassembly-path hook."""

    @abc.abstractmethod
    def run(self) -> None:
        """Deliver incoming messages to observers until stopped."""

    @abc.abstractmethod
    def stop(self) -> None:
        ...

    def add_observer(self, obs: Observer) -> None:
        self._observers.append(obs)

    def remove_observer(self, obs: Observer) -> None:
        self._observers.remove(obs)

    def _record_send(self, msg: Message, nbytes: Optional[int],
                     seconds: Optional[float]) -> None:
        """Transports call this from ``send_message`` with the wire size
        (exact, or ``comm_obs.message_nbytes`` where nothing serializes)
        and the time spent serializing+writing."""
        comm_obs.record_send(msg.type, nbytes, seconds)

    def _notify(self, msg: Message, nbytes: Optional[int] = None) -> None:
        # recv-side telemetry lives in the observer-notify path, so every
        # transport and every NodeManager is measured with no changes
        comm_obs.record_recv(msg.type, nbytes)
        trace_ctx.on_recv(msg, self.node_id)
        for obs in list(self._observers):
            obs.receive_message(msg.type, msg)


class NodeManager(Observer):
    """Base for server/client managers: handler registry + event loop.

    Reference ``fedml_core/distributed/{client,server}``
    (``client_manager.py:12-65``): ``register_message_receive_handler``,
    ``send_message``, ``run``, ``finish`` — minus the
    ``MPI.COMM_WORLD.Abort()`` shutdown (a graceful FINISH message +
    backend stop instead).
    """

    def __init__(self, backend: CommBackend):
        self.backend = backend
        self.backend.add_observer(self)
        self._handlers: Dict[str, Handler] = {}
        self.register_message_receive_handlers()

    # subclasses override
    def register_message_receive_handlers(self) -> None:
        ...

    def register_message_receive_handler(self, msg_type: str, fn: Handler) -> None:
        self._handlers[msg_type] = fn

    def receive_message(self, msg_type: str, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            # the hop chain still emits: a dropped stray/late/duplicate
            # frame's full path is exactly the evidence chaos triage
            # wants in the merged timeline
            trace_ctx.on_handled(msg, self.backend.node_id)
            # A stray or late frame (a post-deadline model upload, a
            # duplicate from a chaos run, a half-upgraded peer) is an
            # EXPECTED event in a fault-tolerant federation — raising
            # here used to kill the node's reader thread and silently
            # wedge the whole run.  Log + count instead; chaos runs
            # assert the counter against their injection schedule.
            comm_obs.record_unhandled(msg_type)
            logging.warning(
                "node %d: no handler for %r from node %s — dropped",
                self.backend.node_id, msg_type, msg.sender,
            )
            return
        t0 = time.perf_counter()
        try:
            handler(msg)
        finally:
            # handler latency = the node's real work per message type
            # (server aggregate, client local train)
            comm_obs.record_handle(msg_type, time.perf_counter() - t0)
            # 'done' stamp + trace_hop emission on the RECEIVER's
            # registry: done - recv IS the handler (train/fold) time in
            # the merged timeline
            trace_ctx.on_handled(msg, self.backend.node_id)

    def send_message(self, msg: Message) -> None:
        self.backend.send_message(msg)

    def send_multicast(self, msg: Message, receivers) -> None:
        self.backend.send_multicast(msg, receivers)

    def run(self) -> None:
        self.backend.run()

    def finish(self) -> None:
        self.backend.stop()
