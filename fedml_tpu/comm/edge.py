"""Edge-hub uplink — one connection claiming a whole downstream cohort.

The hierarchical aggregation tree (``algorithms/edge_hub``) puts an
edge hub between the root hub and a slice of the federation: the edge
terminates its cohort's connections on a LOCAL hub, folds their uploads
into one (sum n·model, sum n) pair, and uplinks partials over this
backend.  On the root side the uplink is indistinguishable from a muxer
connection: a **hello v2** frame registers every downstream node id on
one socket, so the root hub's routing, mcast per-conn dedup, mux wraps,
stripes, shm lanes, and the server's delta-broadcast ack grouping all
compose over the extra hop with NO root-side changes.

The delivery side is where this differs from ``TcpMuxBackend``: a muxer
clones each wrapped broadcast per co-located virtual node (500 local
deliveries), but the edge hub re-fans the frame out to its OWN
connections — it needs the inner message exactly ONCE, with the target
id list attached, never per-node clones.  Mux-wrapped and striped
inbound frames are therefore unwrapped and delivered a single time with
``msg._mux_nodes`` carrying the cohort slice the root addressed.
"""

from __future__ import annotations

import logging
from typing import Optional

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.tcp import TcpBackend


def mux_nodes(msg: Message):
    """The downstream node ids a wrapped broadcast frame addressed, or
    None for a plain unicast (attached by ``EdgeUplinkBackend``)."""
    return getattr(msg, "_mux_nodes", None)


class EdgeUplinkBackend(TcpBackend):
    """The edge hub's upstream connection to the root hub.

    Registers the whole downstream cohort's node ids (hello v2) and
    delivers each wrapped broadcast ONCE with the addressed id list
    attached — the edge tier's re-fan-out replaces the muxer's local
    clone loop.  Unicast frames (resync replies to a single downstream
    node) deliver unchanged; the edge manager forwards them down."""

    def __init__(self, node_ids, host: str, port: int, **kw):
        ids = [int(i) for i in node_ids]
        if not ids:
            raise ValueError("EdgeUplinkBackend needs at least one "
                             "downstream node id")
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate downstream node ids: {ids}")
        self.node_ids = ids
        super().__init__(ids[0], host, port, **kw)

    def _hello_obj(self) -> dict:
        ids = self.node_ids
        if len(ids) > 2 and ids == list(range(ids[0], ids[-1] + 1)):
            # contiguous cohort (the launcher partitions contiguously):
            # claim it as ONE [lo, hi] range so the root hub keeps
            # O(edges) routing state instead of an entry per virtual
            # node — the 100k-client registration tax was measured at
            # +33 MB of root RSS with per-id claims
            return {"node_ranges": [[ids[0], ids[-1]]]}
        return {"node_ids": ids}

    @staticmethod
    def _addressed(frame: dict):
        """The cohort slice a wrapped frame addressed: an explicit id
        list, or a compacted ``[lo, hi]`` range expanded locally (the
        root never ships 100k-id lists to a range-claim conn)."""
        nodes = frame.get("nodes")
        if nodes is not None:
            return nodes
        rng = frame.get("range")
        if rng is not None:
            lo, hi = int(rng[0]), int(rng[1])
            return list(range(lo, hi + 1))
        return None

    def _on_mux_frame(self, frame: dict, payload, nbytes: int,
                      region=None) -> None:
        try:
            msg = Message.from_frame_bytes(payload)
        except Exception:
            logging.warning(
                "edge uplink %d: undecodable mux-wrapped frame (%s) — "
                "broadcast copy dropped", self.node_id,
                frame.get("msg_type"),
            )
            return
        msg._region = region
        msg._mux_nodes = self._addressed(frame)
        self._notify(msg, nbytes=nbytes)

    def _deliver_reassembled(self, msg: Message, ent: dict) -> None:
        nodes = self._addressed(ent)
        if nodes is not None:
            msg._mux_nodes = nodes
        self._notify(msg, nbytes=ent["nbytes"])
