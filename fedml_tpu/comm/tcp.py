"""TCP hub backend — the cross-device / DCN control plane.

Fills the role the reference gives MQTT
(``communication/mqtt/mqtt_comm_manager.py:14-126``: broker pub/sub with
JSON payloads for loosely-coupled mobile clients) with zero external
dependencies: a hub process accepts connections, each node registers its
integer id (hub ACKs the registration — sends before the ACK cannot
race past an unregistered receiver), and JSON-lines frames are routed
by receiver id.  Weights ride the Message codec (base64 f32 buffers, or
the reference's list-codec via ``tensor_to_list`` for mobile parity).

Design notes vs the reference's MPI threads (SURVEY.md §5.2): one
blocking reader thread per connection, shutdown via sentinel frame and
socket close — no ctypes thread kills, no polling sleeps.  A dead or
misbehaving peer only loses its own frames: routing errors are caught,
the stale connection is dropped, and other nodes keep flowing.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
from typing import Dict

from fedml_tpu.comm.backend import CommBackend
from fedml_tpu.comm.message import Message
from fedml_tpu.obs.telemetry import get_telemetry

_SENTINEL = {"__hub__": "stop"}
_ACK = {"__hub__": "ack"}


class TcpHub:
    """Central router: node_id → connection. Start once per federation."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()
        # frames to unregistered/dead receivers are dropped BY DESIGN
        # (the deadline server treats the receiver as a straggler), but
        # invisibly so until now: count them per message type so chaos
        # runs can reconcile observed drops against injected ones
        self.dropped_frames: Dict[str, int] = {}
        self._conns: Dict[int, socket.socket] = {}
        # per-connection send locks: sendall on a multi-MB frame loops
        # over partial sends, so two reader threads forwarding to the
        # same receiver concurrently would interleave mid-payload
        self._send_locks: Dict[int, threading.Lock] = {}
        self._lock = threading.Lock()
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket):
        node_id = None
        try:
            f = conn.makefile("rb")
            hello = f.readline()
            if not hello:
                return
            node_id = json.loads(hello)["node_id"]
            # ACK BEFORE registering: once registered, _forward from
            # other reader threads may write to this conn concurrently,
            # and an ACK interleaved with a routed frame would hand the
            # dialing client garbage as its handshake line.  A frame
            # routed in the ack→register window is dropped — but nobody
            # can have observed this node as registered yet (await_peers
            # reads the registry), so that is the normal unregistered-
            # receiver drop, not a race.
            conn.sendall((json.dumps(_ACK) + "\n").encode())
            with self._lock:
                self._conns[node_id] = conn
                self._send_locks[node_id] = threading.Lock()
            while True:
                line = f.readline()
                if not line:
                    break
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError:
                    continue  # drop malformed frame, keep the connection
                if frame.get("__hub__") == "peers":
                    # membership introspection: reply to THIS node with
                    # the currently registered ids (startup barrier —
                    # frames to unregistered receivers are dropped, so
                    # coordinators must await their cohort first)
                    with self._lock:
                        ids = sorted(self._conns)
                    self._forward(
                        node_id,
                        (json.dumps({"__hub__": "peers", "ids": ids}) + "\n").encode(),
                    )
                    continue
                if frame.get("__hub__") == "stop":
                    break
                receiver = frame.get("receiver")
                if receiver is not None:
                    self._forward(receiver, line,
                                  msg_type=frame.get("msg_type"))
        except OSError:
            pass  # peer vanished: fall through to cleanup
        finally:
            if node_id is not None:
                with self._lock:
                    # identity guard: a re-registered node may have
                    # replaced this conn; don't deregister the live one
                    if self._conns.get(node_id) is conn:
                        self._conns.pop(node_id, None)
                        self._send_locks.pop(node_id, None)
            try:
                conn.close()
            except OSError:
                pass

    def _forward(self, receiver: int, raw_line: bytes, msg_type=None):
        with self._lock:
            conn = self._conns.get(receiver)
            send_lock = self._send_locks.get(receiver)
        if conn is None or send_lock is None:
            self._count_drop(receiver, msg_type)
            return
        try:
            with send_lock:
                conn.sendall(
                    raw_line if raw_line.endswith(b"\n") else raw_line + b"\n"
                )
        except OSError:
            # dead receiver: unregister so later sends don't retry it;
            # its own reader thread finishes cleanup
            self._count_drop(receiver, msg_type)
            with self._lock:
                if self._conns.get(receiver) is conn:
                    self._conns.pop(receiver, None)
                    self._send_locks.pop(receiver, None)

    def _count_drop(self, receiver: int, msg_type) -> None:
        mt = msg_type or "__hub__"
        with self._lock:
            self.dropped_frames[mt] = self.dropped_frames.get(mt, 0) + 1
        get_telemetry().inc("hub.dropped_frames", msg_type=mt)
        logging.debug("hub: dropped %s frame to unreachable node %s",
                      mt, receiver)

    def stats(self) -> dict:
        """Hub-side fault accounting (``run_hub`` prints this at
        shutdown so multi-process chaos drivers can collect it)."""
        with self._lock:
            return {"dropped_frames": dict(self.dropped_frames)}

    def stop(self):
        self._running = False
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()


class TcpBackend(CommBackend):
    """One node's hub connection.

    ``auto_reconnect`` (attempts; 0 = off) makes ``run()`` survive a
    dropped hub connection: on EOF/reset the backend dials again,
    re-registers (the hub's identity guard swaps the live conn,
    ``TcpHub._serve_conn``), and resumes the read loop.  Frames routed
    while disconnected are lost — by design the round-deadline server
    (``fedavg_cross_device``) treats the node as a straggler for that
    round and it rejoins at the next sync.
    """

    def __init__(self, node_id: int, host: str, port: int,
                 timeout: float = 30.0, auto_reconnect: int = 0,
                 send_retries: int = 3):
        super().__init__(node_id)
        self._host, self._port, self._timeout = host, port, timeout
        self.auto_reconnect = auto_reconnect
        # bounded retry budget for send_message: a transient OSError
        # (hub restarting, conn mid-swap by the reconnect path) used to
        # be terminal for the SENDER even though the reader thread was
        # about to re-dial.  0 = fail fast (the pre-fault behavior).
        self.send_retries = max(0, int(send_retries))
        self._stopped = threading.Event()
        # serializes send_message against _dial's socket swap: without
        # it, a send between "socket connected" and "hello written"
        # lands BEFORE the registration line and the hub parses the
        # message frame as the hello (KeyError, conn dropped, frame lost)
        self._send_lock = threading.Lock()
        self._dial()

    def _dial(self):
        with self._send_lock:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._timeout
            )
            try:
                sock.sendall(
                    (json.dumps({"node_id": self.node_id}) + "\n").encode()
                )
                f = sock.makefile("rb")
                # wait for the hub's registration ACK — guaranteed to be
                # the FIRST line on the conn (the hub ACKs before
                # registering, so no routed frame can precede or
                # interleave it); afterwards, any frame sent TO this
                # node can be delivered
                ack = f.readline()
                if not ack or json.loads(ack).get("__hub__") != "ack":
                    raise ConnectionError(
                        f"node {self.node_id}: no hub ACK"
                    )
            except BaseException:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            sock.settimeout(None)
            # close the connection being replaced (reconnect path) —
            # without this every reconnect cycle leaks an fd
            for stale in (getattr(self, "_file", None),
                          getattr(self, "_sock", None)):
                if stale is not None:
                    try:
                        stale.close()
                    except OSError:
                        pass
            self._sock, self._file = sock, f

    def send_message(self, msg: Message) -> None:
        # to_json() is already one valid JSON line (newlines escape inside
        # JSON strings) — no re-parse needed
        t0 = time.perf_counter()
        data = (msg.to_json() + "\n").encode()
        # Bounded retry with exponential backoff + jitter: each attempt
        # re-reads self._sock, so a reconnect (reader thread's _dial
        # swapping the socket) between attempts is picked up.  A retry
        # after a PARTIAL sendall can hand the hub a garbled first line
        # — the hub drops malformed frames, so the worst case is one
        # lost frame (the round deadline's job), never stream corruption.
        # A backend killed by _kill_connection must not retry: the
        # stream is desync-fatal by contract and callers expect OSError.
        delay = 0.05
        for attempt in range(self.send_retries + 1):
            try:
                with self._send_lock:
                    self._sock.sendall(data)
                break
            except OSError:
                if self._stopped.is_set() or attempt >= self.send_retries:
                    raise
                get_telemetry().inc("comm.send_retries", msg_type=msg.type)
                time.sleep(delay * (1.0 + random.random()))
                delay = min(delay * 2.0, 2.0)
        # exact wire bytes; latency covers serialize + socket write
        # (including any backoff — a retried send IS that slow)
        self._record_send(msg, len(data), time.perf_counter() - t0)

    def drop_connection(self) -> None:
        """Fault injection: sever the hub connection WITHOUT stopping
        the backend — ``run()`` sees EOF and, with ``auto_reconnect``,
        re-dials and re-registers exactly as for a real network drop."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def await_peers(self, ids, timeout: float = 60.0) -> None:
        """Block until every node id in ``ids`` is registered at the hub.

        MUST be called before ``run()`` (it reads replies off the shared
        socket); pre-protocol, the only inbound frames are peers
        replies, so the read is unambiguous.  This is the startup
        barrier: the hub drops frames to unregistered receivers, so a
        coordinator that broadcasts before its cohort registered would
        hang the federation.
        """
        import time as _time

        want = set(int(i) for i in ids)
        deadline = _time.monotonic() + timeout
        # Bound each readline by the remaining budget: the socket runs
        # blocking (timeout None) for the normal message loop, and a hub
        # that accepts the request but never replies (wedged process)
        # would otherwise hang this "raises TimeoutError" function forever.
        try:
            while (remaining := deadline - _time.monotonic()) > 0:
                self._sock.settimeout(max(remaining, 0.05))
                try:
                    self._sock.sendall(
                        (json.dumps({"__hub__": "peers"}) + "\n").encode()
                    )
                    line = self._file.readline()
                except TimeoutError:
                    # A timed-out readline (or partial sendall) leaves
                    # the stream mid-frame: the buffered reader discards
                    # the partial bytes, so any later read would parse
                    # the frame's TAIL as a fresh line (ADVICE r2).  The
                    # connection can no longer be trusted frame-aligned —
                    # kill it so reuse fails loudly instead of corrupting.
                    self._kill_connection()
                    raise TimeoutError(
                        f"node {self.node_id}: hub read timed out mid-"
                        "frame during await_peers; connection closed "
                        "(a resumed read could split a frame)"
                    ) from None
                except OSError as e:
                    # a reset/closed socket is a dead hub, not slow peers
                    raise ConnectionError(
                        f"node {self.node_id}: hub connection failed during "
                        f"await_peers: {e}"
                    ) from e
                if not line:
                    raise ConnectionError(
                        f"node {self.node_id}: hub closed during await_peers"
                    )
                frame = json.loads(line)
                if frame.get("__hub__") == "peers":
                    if want <= set(frame.get("ids", [])):
                        return
                    _time.sleep(0.05)
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass  # _kill_connection already closed it
        # budget spent between reads: every readline returned a FULL line,
        # so the stream is still frame-aligned and the backend is reusable
        raise TimeoutError(
            f"node {self.node_id}: peers {sorted(want)} not all registered "
            f"within {timeout}s"
        )

    def _kill_connection(self) -> None:
        """Mark the backend unusable and close the socket (desync-fatal
        paths): later send_message/run calls get OSError immediately."""
        self._stopped.set()
        # shutdown() disables the connection immediately even though the
        # makefile() reader still holds a reference to the fd (a bare
        # close() is deferred by that refcount and sends would still work)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for closer in (self._file.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass

    def run(self) -> None:
        retries = self.auto_reconnect
        lost_at = None  # perf_counter stamp of the FIRST EOF of an outage
        while not self._stopped.is_set():
            try:
                line = self._file.readline()
            except OSError:
                line = b""
            if not line:
                if self._stopped.is_set() or retries <= 0:
                    return
                retries -= 1
                import time as _time

                if lost_at is None:
                    lost_at = _time.perf_counter()
                _time.sleep(0.2)
                try:
                    self._dial()  # re-register; hub swaps the live conn
                    # recovery span: total time this node was off the hub
                    # (first EOF -> re-registered), the number a chaos
                    # soak reads to bound reconnect impact
                    t = get_telemetry()
                    t.inc("comm.reconnects")
                    t.observe("span.reconnect_s",
                              _time.perf_counter() - lost_at)
                    lost_at = None
                    logging.warning(
                        "node %d: hub connection lost — reconnected "
                        "(%d retries left)", self.node_id, retries,
                    )
                    continue
                except (OSError, ConnectionError, json.JSONDecodeError):
                    # JSONDecodeError: a hub that died mid-ACK leaves a
                    # partial line — that's a failed dial, not a reason
                    # to kill the reader thread with retries remaining
                    logging.exception(
                        "node %d: reconnect failed", self.node_id
                    )
                    continue  # retry until the budget runs out
            try:
                frame = json.loads(line)
            except json.JSONDecodeError:
                logging.exception("node %d: dropping malformed frame", self.node_id)
                continue
            if frame.get("__hub__") == "stop":
                return
            try:
                self._notify(Message.from_obj(frame), nbytes=len(line))
            except Exception:
                # a handler error must not kill the reader thread — the
                # node would silently stop receiving and the federation
                # would hang with no attributable cause
                logging.exception("node %d: message handler failed", self.node_id)

    def run_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.sendall((json.dumps(_SENTINEL) + "\n").encode())
            self._sock.close()
        except OSError:
            pass
