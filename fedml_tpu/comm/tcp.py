"""TCP hub backend — the cross-device / DCN control plane.

Fills the role the reference gives MQTT
(``communication/mqtt/mqtt_comm_manager.py:14-126``: broker pub/sub with
JSON payloads for loosely-coupled mobile clients) with zero external
dependencies: a hub process accepts connections, each node registers its
integer id (hub ACKs the registration — sends before the ACK cannot
race past an unregistered receiver), and frames are routed by receiver
id.  Two frame generations share the stream:

- **v1** — one JSON line per message (arrays as base64 f32 buffers, or
  the reference's list-codec via ``tensor_to_list`` for mobile parity);
- **v2** (default) — a JSON header line whose top-level ``__binlen__``
  key announces exactly that many raw payload bytes following the
  newline (``Message.to_frame``).  Readers that see no ``__binlen__``
  treat the line as a complete v1 frame, so both generations interop on
  one hub and the 4/3x base64 inflation is gone from model traffic.

Design notes vs the reference's MPI threads (SURVEY.md §5.2): the hub
data plane runs in one of two modes (``FEDML_TPU_HUB_MODE``, or the
``mode=`` kwarg):

- **reactor** (default) — a single ``selectors``-based event-loop
  thread multiplexes every connection non-blocking: accepts, streaming
  header/payload reads (``comm/reactor.py``'s ``FrameParser``), shm
  doorbells, and writability-driven queue drains.  O(1) threads per
  hub regardless of connection count — the scaling plane for the
  512-conn-and-up regime — and inbound TCP payloads land in pooled
  refcounted buffers (``BufRegion``) that ride the send queues as
  pins, so routing is zero-copy end to end on BOTH transports.
- **threaded** — the original one-blocking-reader-thread-per-connection
  model with a sender pool; kept as the A/B byte-identity baseline
  (both modes build frame bytes through the same drain helpers, so
  federations pin sha256-identical across them).

Either way: shutdown via sentinel frame and socket close — no ctypes
thread kills, no polling sleeps.  A dead or misbehaving peer only
loses its own frames: routing errors are caught, the stale connection
is dropped, and other nodes keep flowing.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import queue
import selectors
import socket
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

from fedml_tpu.analysis.locks import assert_held, make_lock
from fedml_tpu.comm.backend import CommBackend
from fedml_tpu.comm.message import (
    FRAME_BINLEN_KEY,
    HUB_KEY,
    MCAST_STRIPE_KIND,
    MUX_KIND,
    SHM_SEQ_KEY,
    Message,
)
from fedml_tpu.comm.reactor import BufPool, FrameError, FrameParser
from fedml_tpu.comm.shm import (
    DEFAULT_DATA_BYTES,
    DEFAULT_MIN_BYTES,
    DEFAULT_SLOTS,
    ShmLane,
    ShmLaneError,
    split_frame_line,
)
from fedml_tpu.obs import flight, trace_ctx
from fedml_tpu.obs.telemetry import get_telemetry

_SENTINEL = {HUB_KEY: "stop"}
_ACK = {HUB_KEY: "ack"}

# hub data-plane selection: "reactor" (selector event loop, default) or
# "threaded" (one blocking reader thread per connection + sender pool)
ENV_HUB_MODE = "FEDML_TPU_HUB_MODE"


class _HubConnError(Exception):
    """Connection-fatal condition noticed mid-serve (lane errors,
    doorbells on lane-less conns, invalid hellos): the reactor and the
    threaded reader both translate it into 'drop this connection',
    which is the established policy for every unrecoverable per-conn
    fault — the peer pays one reconnect, the router never wedges."""


def _retry_jitter(node_id: int, attempt: int) -> float:
    """Deterministic send-retry jitter in [0, 1): a fold_in-style hash
    of (node, attempt), so two nodes backing off together still
    de-synchronize but a chaos-soak re-run reproduces the exact retry
    timing (the last seedless draw in the round path — everything else
    flows from explicit seeds)."""
    digest = hashlib.sha256(f"retry|{node_id}|{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2.0 ** 64

# Per-socket buffer target: model frames are multi-MB, and the Linux
# defaults (~208 KiB) force many small send/recv cycles per frame even
# on loopback.  The kernel clamps to net.core.{r,w}mem_max — tuning is
# best-effort by design.
_TCP_SOCK_BUF = 4 << 20

# Striped-multicast reassembly budget — a shared contract between the
# two ends: ``TcpBackend`` buffers at most this many payload bytes
# across its in-progress stripe streams, and ``TcpHub`` refuses to
# stripe any frame larger than HALF of it (headroom for one
# interleaved stream) — an over-budget frame falls back to whole-frame
# fan-out instead of being striped into a guaranteed receiver-side
# overflow abort on every client.
_MAX_REASM_BYTES = 64 << 20


def _split_traced_mcast(frame: dict, payload: bytes):
    """For a TRACED mcast, split the inner frame at its header line,
    stamp ``hub_in``, and return ``(parsed header dict, payload-tail
    memoryview)``; ``(None, None)`` for untraced frames or an
    unparseable header (the frame then ships verbatim, unstamped).
    One definition for the whole-frame and striped fan-out paths — the
    traced-frame contract must not diverge between them."""
    if not frame.get(trace_ctx.TRACE_KEY):
        return None, None
    hdr = None
    # split_frame_line, not .find: ``payload`` may be a pinned slab
    # memoryview (zero-copy hub inbound) — only the header LINE is
    # ever materialized, the multi-MB tail stays a view
    end = split_frame_line(payload)
    if end > 0:
        try:
            hdr = json.loads(bytes(payload[:end]))
        except json.JSONDecodeError:
            hdr = None
    if hdr is None or trace_ctx.TRACE_KEY not in hdr:
        return None, None
    trace_ctx.hub_stamp(hdr, "hub_in")
    return hdr, memoryview(payload)[end:]


def _tune_socket(sock: socket.socket) -> None:
    """TCP_NODELAY + sized buffers on every hub/backend socket: frames
    are written whole (vectored), so Nagle batching only adds latency,
    and sized buffers keep a multi-MB frame moving in few syscalls."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _TCP_SOCK_BUF)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, _TCP_SOCK_BUF)
    except OSError:
        pass  # exotic stacks/containers may refuse; correctness unaffected


def _iov_max() -> int:
    try:
        import os

        return os.sysconf("SC_IOV_MAX")
    except (OSError, ValueError, AttributeError):
        return 1024  # POSIX minimum is 16; Linux ships 1024


_IOV_MAX = _iov_max()


def _sendall_parts(sock: socket.socket, parts) -> None:
    """Vectored ``sendall`` of one complete frame given as a buffer
    list: multi-MB payloads are never concatenated into a fresh
    bytestring (``Message.to_frame_parts`` zero-copy contract).
    Partial ``sendmsg`` returns advance memoryviews until done; each
    call is capped at IOV_MAX buffers (a deep pytree emits one buffer
    per leaf — past the cap sendmsg would fail with EMSGSIZE)."""
    pending = [p if isinstance(p, memoryview) else memoryview(p)
               for p in parts]
    pending = [v if v.format == "B" and v.ndim == 1 else v.cast("B")
               for v in pending]
    if not hasattr(sock, "sendmsg"):  # non-POSIX fallback
        sock.sendall(b"".join(pending))
        return
    while pending:
        sent = sock.sendmsg(pending[:_IOV_MAX])
        while sent:
            head = pending[0]
            if sent >= len(head):
                sent -= len(head)
                pending.pop(0)
            else:
                pending[0] = head[sent:]
                sent = 0


class _Conn:
    """One registered CONNECTION: socket + bounded outbound frame queue.

    Since hello v2 a connection may carry MANY node ids (a muxer process
    driving hundreds of virtual clients over one socket): ``ids`` is the
    live set of node ids currently routed here (ids can be re-claimed by
    a later connection — the rebind policy in ``_serve_conn``), ``mux``
    marks a v2 registration (broadcast copies to it are wrapped in a
    ``__hub__: mux`` outer header naming the co-located target ids so
    the demuxing backend can fan out locally), ``cid`` is a small
    process-unique connection id for per-connection telemetry series,
    and ``dead`` tells a sender-pool worker to stop draining (set when
    the reader exits or every id was rebound away).

    ``scheduled`` enforces a single drainer at a time (a connection is
    only ever serviced by the one sender worker it was handed to), so
    per-connection order is FIFO and frames can never interleave
    mid-payload — the invariant the old per-conn send locks provided,
    now without serializing the fan-out behind the router thread.

    Queue entries are ``(msg_type, parts, hdr, nbytes, rids, region)``:
    for an untraced frame ``hdr`` is None and ``parts`` is the complete
    wire frame; for a TRACED frame ``hdr`` is the parsed header dict (shared
    across an mcast's receiver queues) — or a deferred ``(kind, meta,
    inner header)`` tuple — and ``parts`` holds only the payload tail:
    the sender worker re-encodes the header line with a fresh
    ``hub_out`` stamp at drain time, so ``hub_out - hub_in`` is this
    frame's real queue wait and the payload bytes are still the one
    shared immutable object.  ``rids`` is the tuple of node ids the
    entry addresses: the drain re-checks them against ``ids`` so a
    frame queued for an id that was REBOUND to a newer connection
    while waiting dies with straggler semantics instead of being
    delivered to the displaced owner (the rebind policy's "old conn
    loses it" must hold for in-flight frames too).  ``region`` is the
    refcounted slab pin (``ShmRegion``) backing a zero-copy laned
    payload, or None: every enqueue occurrence holds one retain() and
    every drain — sent, dropped, dead-conn leftover — releases exactly
    one, so the ring reclaims the bytes when the LAST queue drains them
    and never before.

    ``heads`` is a strict-priority queue in front of ``frames``: a
    striped mcast enqueues every receiver's stripe 0 there, and a
    sender worker always drains heads — across ALL connections — before
    tail frames, requeuing its connection after the last head so every
    other connection's pending head drains before any connection's
    tail.  That is the head-start contract: all K receivers START
    streaming within one head round instead of the last one waiting
    behind K-1 whole fan-outs (enqueue order alone cannot guarantee
    this — tails land while heads are still draining and a paced visit
    would drain head+tail together).

    Reactor-plane fields (``parser``..``want_write``) exist on every
    conn but are live only under ``mode="reactor"``, where they are
    touched EXCLUSIVELY by the event-loop thread (single-threaded by
    construction — the reactor's analogue of the single-drainer rule):
    ``parser`` is the conn's streaming ``FrameParser``, ``phase`` the
    handshake state machine (0 = awaiting hello, 1 = clock-sync, 2 =
    registered), ``wpend`` the partial-write continuation (memoryviews
    of the in-flight frame still to write), ``wcommit``/``wregion``
    the lane-commit token and payload pin released when that frame
    fully flushes, and ``want_write`` whether the socket is parked on
    EVENT_WRITE."""

    __slots__ = ("sock", "frames", "heads", "nbytes", "scheduled",
                 "ids", "ranges", "mux", "cid", "dead", "lane",
                 "parser", "phase", "nid0", "wpend", "wcommit",
                 "wregion", "wmsg_type", "want_write")

    def __init__(self, sock: socket.socket, ids=(), mux: bool = False,
                 lane=None, ranges=()):
        self.sock = sock
        self.frames: deque = deque()  # (msg_type, parts, hdr, nbytes, rids, region)
        self.heads: deque = deque()  # same entries, strict priority
        self.nbytes = 0
        self.scheduled = False
        self.ids = set(ids)
        # hello v2 RANGE claim (an edge-hub uplink owning a contiguous
        # cohort): inclusive (lo, hi) intervals routed here WITHOUT a
        # per-id entry in the hub's node map — the whole point of the
        # claim is that the root tier's state stays O(connections), not
        # O(virtual clients).  A range is an atom: it can only be lost
        # by the connection dying or a later claim displacing the WHOLE
        # conn (never id-by-id), so drain-time rebind filtering skips
        # per-id checks for pure-range conns.
        self.ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)
        self.mux = mux
        self.cid = 0
        self.dead = False
        # shared-memory lane (comm/shm.py), attached at hello when the
        # dialer advertised a slab: large payload bytes in BOTH
        # directions ride its rings while every header stays on this
        # socket (order, control frames, and fallback are the stream's)
        self.lane = lane
        # reactor-plane state (loop-thread-only; see class docstring)
        self.parser = None
        self.phase = 0
        self.nid0 = None  # the hello's primary node id (reply target)
        self.wpend: List = []
        self.wcommit = None
        self.wregion = None
        self.wmsg_type = None
        self.want_write = False

    def covers(self, nid: int) -> bool:
        """True when this conn routes ``nid`` (per-id claim or range)."""
        if nid in self.ids:
            return True
        for lo, hi in self.ranges:
            if lo <= nid <= hi:
                return True
        return False

    def claimed(self) -> int:
        """Total node ids this conn claims (ids + range sizes)."""
        return len(self.ids) + sum(hi - lo + 1 for lo, hi in self.ranges)


class TcpHub:
    """Central router: node_id → connection. Start once per federation.

    Outbound frames ride per-connection bounded queues drained by a
    small sender pool — a reader thread only ever ENQUEUES (O(1)), so
    one slow receiver no longer stalls routing for everyone else, and a
    ``__hub__: mcast`` frame (one payload + a receiver list) fans out
    by enqueueing the SAME immutable bytes to every receiver: the
    server→hub broadcast leg carries each sync exactly once."""

    # lock-discipline contract (fedlint): reader threads, the sender
    # pool, and the accept path all share these — every touch goes
    # through self._lock (per-conn payload state lives on _Conn and is
    # protected by the same lock; the single-drainer rule serializes
    # the socket itself)
    _GUARDED_BY = {
        "_conns": "_lock",
        "_range_conns": "_lock",
        "dropped_frames": "_lock",
        "backpressure_drops": "_lock",
        "mcast_frames": "_lock",
        "mcast_copies": "_lock",
        "striped_mcasts": "_lock",
        "stripe_frames": "_lock",
        "node_rebinds": "_lock",
        "shm_frames": "_lock",
        "shm_bytes": "_lock",
        "shm_fallbacks": "_lock",
        "shm_hub_copies": "_lock",
        "zero_copy_forwards": "_lock",
        "_drainq": "_lock",
        "_reader_count": "_lock",
    }

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 senders: int = 4, max_queue_bytes: int = 256 << 20,
                 max_queue_frames: int = 4096,
                 stripe_bytes: int = 0, max_inflight_stripes: int = 8,
                 shm_min_bytes: int = DEFAULT_MIN_BYTES,
                 mode: Optional[str] = None):
        self._mode = (mode or os.environ.get(ENV_HUB_MODE)
                      or "reactor").strip().lower()
        if self._mode not in ("reactor", "threaded"):
            raise ValueError(f"unknown hub mode {self._mode!r} "
                             f"(want 'reactor' or 'threaded')")
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()
        # striped fan-out: an mcast payload larger than ``stripe_bytes``
        # (0 = off, ship whole frames) is split into fixed-size
        # ``__hub__: mcast_stripe`` continuation frames that receivers
        # reassemble (TcpBackend).  ``max_inflight_stripes`` is the
        # pacing quantum: a sender-pool worker writes at most that many
        # frames to ONE connection before rotating to the back of the
        # ready queue, so all receivers stream concurrently instead of
        # the last one waiting behind K whole-frame sends — the PR-6
        # measured fan-out wall (bcast_queue 436.7 ms at 32 clients).
        self._stripe_bytes = max(0, int(stripe_bytes))
        self._pace = max(1, int(max_inflight_stripes))
        self._sid = itertools.count(1)  # process-unique stripe-stream ids
        self.striped_mcasts = 0
        self.stripe_frames = 0
        # frames to unregistered/dead receivers are dropped BY DESIGN
        # (the deadline server treats the receiver as a straggler), but
        # invisibly so until now: count them per message type so chaos
        # runs can reconcile observed drops against injected ones
        self.dropped_frames: Dict[str, int] = {}
        # backpressure: a receiver whose queue exceeds the bound loses
        # the NEW frame (counted in dropped_frames too) — bounded memory
        # beats an unbounded queue wedging the hub behind a dead-slow peer
        self.backpressure_drops = 0
        self.mcast_frames = 0
        self.mcast_copies = 0
        # duplicate-registration policy (pinned): a hello claiming an
        # already-registered node id REBINDS it — the new connection
        # wins, the old one loses the id (and is closed once it holds
        # none), and every rebound id is counted here + as the
        # ``hub.node_rebinds`` telemetry series.  Covers both the
        # reconnect case (the old conn is half-dead) and a genuine
        # two-live-conns conflict (last dialer wins, visibly).
        self.node_rebinds = 0
        # shared-memory lane accounting: frames/bytes the hub moved
        # through slab rings (either direction) and payloads that fell
        # back to inline TCP (ring full, descriptor queue full,
        # oversized) — the fallback is per-frame and silent on the
        # wire, so the counter is the only evidence it happened
        self.shm_frames = 0
        self.shm_bytes = 0
        self.shm_fallbacks = 0
        # zero-copy inbound: laned payloads normally route as refcounted
        # slab PINS (ShmRegion) straight into the send queues — this
        # counts the defensive materializations (pin-pressure valve)
        # that copied instead, so the fast path's "no copies" claim is
        # testable rather than assumed
        self.shm_hub_copies = 0
        # and the fast path itself: queue entries enqueued carrying a
        # slab PIN (one retain, zero payload copies) — the positive
        # counterpart of shm_hub_copies, so the zero-copy forward rate
        # is a measurement, not an absence of evidence
        self.zero_copy_forwards = 0
        # payloads below this ride inline TCP (policy, not fallback)
        self._shm_min = max(0, int(shm_min_bytes))
        self._max_queue_bytes = max_queue_bytes
        self._max_queue_frames = max_queue_frames
        # node id -> connection; MANY-TO-ONE since hello v2 (a muxer
        # registers all its virtual node ids on one socket)
        self._conns: Dict[int, _Conn] = {}
        # connections that claimed contiguous id RANGES (edge-hub
        # uplinks): kept OUT of the per-id map so the root hub's memory
        # stays O(connections) no matter how many virtual clients live
        # behind each edge — routing falls back to a scan of this short
        # list on a per-id miss
        self._range_conns: List[_Conn] = []
        self._cids = itertools.count(1)  # per-connection telemetry ids
        self._lock = make_lock("TcpHub._lock")
        self._ready: "queue.SimpleQueue" = queue.SimpleQueue()
        self._running = True
        self._reader_count = 0  # live threaded-mode reader threads
        self._senders: List[threading.Thread] = []
        if self._mode == "threaded":
            self._senders = [
                threading.Thread(target=self._sender_loop, daemon=True)
                for _ in range(max(1, int(senders)))
            ]
            for t in self._senders:
                t.start()
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True)
            self._accept_thread.start()
            return
        # reactor mode: ONE event-loop thread owns accepts, reads, and
        # writability-driven drains for every connection.  The wakeup
        # pipe is the documented off-loop entry point: stop() and any
        # cross-thread _forward tickle it so the sleeping select wakes
        # and services the drain queue (chaos timer-delayed deliveries
        # need no special path — they arrive as ordinary socket
        # readability, see README "Federation transport").
        self._srv.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._srv, selectors.EVENT_READ, None)
        self._wakeup_r, self._wakeup_w = os.pipe()
        os.set_blocking(self._wakeup_r, False)
        os.set_blocking(self._wakeup_w, False)
        self._sel.register(self._wakeup_r, selectors.EVENT_READ,
                           "wakeup")
        # conns with newly-enqueued frames awaiting a drain visit
        # (scheduled=True dedups; drained at each loop batch end)
        self._drainq: deque = deque()
        self._bufpool = BufPool()
        # ONE recv scratch for every connection's parser: the loop
        # reads one socket at a time and scratch bytes never outlive a
        # consumed() call, so sharing it keeps reactor RSS flat in the
        # connection count (see FrameParser.__init__)
        self._rx_scratch = bytearray(256 << 10)
        self._loop_tid = None
        self._loop_thread = threading.Thread(
            target=self._reactor_loop, daemon=True)
        self._loop_thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    @staticmethod
    def _parse_hello(hello_obj: dict):
        """Decode a hello frame into ``(ids, ranges, mux, node_id)``.
        Raises ``_HubConnError`` for an empty/inverted claim — nothing
        to route, the connection is useless by its own admission."""
        if "node_ranges" in hello_obj:
            # hello v2 RANGE claim (edge-hub uplink): the conn owns
            # whole contiguous id intervals.  No per-id entries are
            # materialized — at 100k virtual clients behind 4 edges
            # the per-id form costs the ROOT hub ~33 MB of node-map
            # + hello-parse state that exists only to say "these
            # 25000 consecutive ids live here"
            ranges = [(int(lo), int(hi))
                      for lo, hi in hello_obj["node_ranges"]]
            if not ranges or any(lo > hi for lo, hi in ranges):
                raise _HubConnError("empty/inverted range claim")
            return [], ranges, True, ranges[0][0]
        if "node_ids" in hello_obj:
            # hello v2: one connection registers MANY node ids (a
            # muxer's virtual clients); v1 dialers keep sending the
            # single node_id form and both interop on one hub
            ids = [int(i) for i in hello_obj["node_ids"]]
            if not ids:
                raise _HubConnError("empty registration")
            return ids, [], True, ids[0]  # ids[0]: peers replies, logs
        nid = int(hello_obj["node_id"])
        return [nid], [], False, nid

    @staticmethod
    def _attach_lane(hello_obj: dict, node_id):
        """shm-lane capability (hello key "shm"): the dialer created
        a slab and advertises it; attach if we can reach it (the
        same-box test IS the attach — a cross-host name simply
        doesn't exist here) and confirm in the ACK.  Any failure
        downgrades the connection to pure TCP, never an error."""
        shm_desc = hello_obj.get("shm")
        if not isinstance(shm_desc, dict):
            return None
        try:
            return ShmLane.attach(shm_desc)
        except Exception as e:
            logging.warning(
                "hub: shm attach for node %s failed (%s: %s) — "
                "connection stays pure TCP", node_id,
                type(e).__name__, e,
            )
            return None

    def _serve_conn(self, conn: socket.socket):
        node_id = None
        ids: List[int] = []
        st = None
        lane = None
        with self._lock:
            self._reader_count += 1
        try:
            _tune_socket(conn)
            f = conn.makefile("rb")
            hello = f.readline()
            if not hello:
                return
            hello_obj = json.loads(hello)
            try:
                ids, ranges, mux, node_id = self._parse_hello(hello_obj)
            except _HubConnError:
                return
            lane = self._attach_lane(hello_obj, node_id)
            # ACK BEFORE registering: once registered, the sender pool
            # may write to this conn concurrently, and an ACK
            # interleaved with a routed frame would hand the dialing
            # client garbage as its handshake line.  A frame routed in
            # the ack→register window is dropped — but nobody can have
            # observed this node as registered yet (await_peers reads
            # the registry), so that is the normal unregistered-
            # receiver drop, not a race.  Old dialers ignore the extra
            # "shm" confirmation key.
            conn.sendall((json.dumps(
                {**_ACK, "shm": lane is not None}
            ) + "\n").encode())
            # clock-sync phase: still UNREGISTERED (no sender worker can
            # touch this conn), so ping replies may be written directly
            # by this reader thread and are guaranteed to be the next
            # line the dialer reads — the request/reply RTT is pure
            # wire + scheduling, never queue wait.  The dialer ends the
            # phase with ``ping_done``; registration happens then, so
            # its min-RTT offset estimate is in hand before any routed
            # frame can arrive.
            while True:
                line = f.readline()
                if not line:
                    return
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError:
                    return  # garbled handshake: connection-fatal
                kind = frame.get(HUB_KEY)
                if kind == "ping":
                    conn.sendall((json.dumps({
                        HUB_KEY: "pong",
                        "t0": frame.get("t0"),
                        "th": time.perf_counter(),
                    }) + "\n").encode())
                    continue
                if kind == "ping_done":
                    break
                # pre-handshake peers (an old dialer): fall through to
                # registration and let the main loop service this line
                break
            st = _Conn(conn, ids=ids, mux=mux, lane=lane, ranges=ranges)
            stale_conns = self._register_conn(st, ids)
            for old in stale_conns:
                # drop the fully-displaced conn: its reader sees EOF and
                # cleans up; queued frames die with it (straggler
                # semantics, same as any dead receiver)
                try:
                    old.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    old.sock.close()
                except OSError:
                    pass
            pending = None if frame.get(HUB_KEY) == "ping_done" \
                else (line, frame)
            while True:
                if pending is not None:
                    line, frame = pending
                    pending = None
                else:
                    line = f.readline()
                    if not line:
                        break
                    try:
                        frame = json.loads(line)
                    except json.JSONDecodeError:
                        # a garbled header is fatal for the CONNECTION,
                        # not just the frame: since frames may carry
                        # binary payloads, the stream cannot
                        # resynchronize — the "bytes" that follow could
                        # be an unannounced payload whose tail would
                        # parse as bogus headers (worst case: a
                        # fabricated __binlen__ blocks this thread on
                        # bytes that never arrive).  Dropping the conn
                        # costs the peer one reconnect (its retry/
                        # auto_reconnect path), never a wedged router.
                        break
                # v2 binary frame: the header announces exactly how many
                # raw payload bytes follow — read them here so routing
                # forwards header+payload as ONE unit and the readline
                # loop never parses payload bytes as lines.  A header
                # carrying the shm doorbell key maps the payload out of
                # the connection's slab instead; a torn descriptor is
                # connection-fatal, exactly like a garbled header.
                payload = b""
                region = None
                binlen = frame.get(FRAME_BINLEN_KEY)
                sseq = frame.pop(SHM_SEQ_KEY, None)
                if binlen and sseq is not None:
                    try:
                        payload, region = self._laned_payload(
                            st, node_id, binlen, sseq)
                    except _HubConnError:
                        break
                elif binlen:
                    payload = f.read(binlen)
                    if len(payload) < binlen:
                        break  # peer died mid-payload: torn frame == EOF
                try:
                    keep = self._route_frame(st, node_id, frame, line,
                                             payload, sseq, region)
                finally:
                    if region is not None:
                        region.release()
                if not keep:
                    break
        except OSError:
            pass  # peer vanished: fall through to cleanup
        finally:
            with self._lock:
                self._reader_count -= 1
            if st is not None:
                self._conn_cleanup(st, ids)
            elif lane is not None:
                lane.close(unlink=True)
            try:
                conn.close()
            except OSError:
                pass

    def _register_conn(self, st: _Conn, ids: List[int]) -> List["_Conn"]:
        """Register a handshake-complete connection: apply the rebind
        policy (new conn wins; range claims displace as atoms), install
        the id/range routes, and return the fully-displaced stale conns
        for the caller to dispose of — the threaded reader shuts their
        sockets down (their own reader threads then clean up on EOF),
        the reactor closes them inline through ``_close_conn_r``."""
        rebound: List[int] = []
        stale_conns: List[_Conn] = []
        ranges = st.ranges
        with self._lock:
            st.cid = next(self._cids)
            # range conns are DISPLACED AS ATOMS: any overlap with a
            # new claim (id or range) kills the old conn's whole
            # claim — an edge IS its cohort; there is no id-by-id
            # partial rebind of a range.  Counted per covered id so
            # the rebind series stays comparable across claim forms.
            for rc in [c for c in self._range_conns if c is not st]:
                hit = any(rc.covers(nid) for nid in ids) or any(
                    lo <= rhi and rlo <= hi
                    for lo, hi in ranges
                    for rlo, rhi in rc.ranges)
                if hit:
                    self.node_rebinds += rc.claimed()
                    rc.dead = True
                    self._range_conns.remove(rc)
                    stale_conns.append(rc)
                    logging.warning(
                        "hub: range claim %s displaces conn cid=%s "
                        "(ranges %s) entirely — rebind",
                        ranges or ids[:8], rc.cid, rc.ranges,
                    )
            if ranges:
                # a new RANGE claim also steals any per-id claims it
                # covers (same new-conn-wins policy; the node map is
                # small wherever range claims happen — the root tier)
                for nid, old in list(self._conns.items()):
                    if old is not st and st.covers(nid):
                        self.node_rebinds += 1
                        rebound.append(nid)
                        old.ids.discard(nid)
                        del self._conns[nid]
                        if not old.ids and not old.ranges:
                            old.dead = True
                            stale_conns.append(old)
                self._range_conns.append(st)
            for nid in ids:
                old = self._conns.get(nid)
                if old is not None and old is not st:
                    # rebind policy (pinned): the NEW conn wins the
                    # id; the old conn loses it and dies entirely
                    # once it holds no ids — counted, never silent
                    self.node_rebinds += 1
                    rebound.append(nid)
                    old.ids.discard(nid)
                    if not old.ids:
                        old.dead = True
                        stale_conns.append(old)
                self._conns[nid] = st
        tel = get_telemetry()
        for nid in rebound:
            tel.inc("hub.node_rebinds")
            logging.warning(
                "hub: node %s re-registered on a new connection — "
                "the old connection loses it (rebind)", nid,
            )
        return stale_conns

    def _laned_payload(self, st: _Conn, node_id, binlen: int, sseq):
        """Resolve one shm doorbell to its payload bytes: a refcounted
        slab pin (``ShmRegion``) on the fast path, a counted
        materialization under pin pressure.  Returns ``(payload,
        region)``; raises ``_HubConnError`` for a lane-less doorbell or
        a lane protocol error — connection-fatal, exactly like a
        garbled header (the doorbell stream cannot resynchronize)."""
        if st.lane is None:
            logging.warning(
                "hub: node %s sent an shm doorbell on a "
                "lane-less connection — dropping it", node_id,
            )
            raise _HubConnError("doorbell on a lane-less connection")
        region = None
        try:
            if st.lane.inbound_backlog() * 2 >= st.lane.nslots:
                # pin-pressure valve: ring reclamation is in-order, so
                # pins parked in one slow conn's send queue hold every
                # LATER frame's bytes too.  With half the descriptor
                # slots still pinned, materialize this frame (one
                # copy, counted) instead of letting the writer's ring
                # stall into inline-TCP fallbacks.
                payload = st.lane.read_copy(sseq, binlen)
                with self._lock:
                    self.shm_hub_copies += 1
                get_telemetry().inc("comm.shm_hub_copies",
                                    reason="pin_pressure")
            else:
                # zero-copy: the routing queues hold refcounted PINS
                # into the slab — the drain plane releases each
                # entry's reference, and the reader's own reference
                # dies right after routing
                region = st.lane.read(sseq, binlen)
                payload = region.view
        except ShmLaneError as e:
            logging.warning(
                "hub: shm lane error from node %s (%s) — "
                "dropping connection", node_id, e,
            )
            raise _HubConnError(str(e)) from None
        with self._lock:
            self.shm_frames += 1
            self.shm_bytes += binlen
        return payload, region

    def _flush_conn_queues(self, st: _Conn) -> None:
        """Release every queued entry of a connection that will never
        drain again: count the drops, release each entry's payload
        pin.  The one place queued refcounts die outside the drain
        path — called on conn death (both planes) and at ``stop()``,
        so a churn soak's pin count provably returns to zero.  (The
        PR-13-era leak this closes: ``stop()`` exited the sender
        workers by sentinel with pinned entries still queued.)"""
        with self._lock:
            leftovers = [(e[0], e[4], e[5]) for e in st.heads]
            leftovers += [(e[0], e[4], e[5]) for e in st.frames]
            st.heads.clear()
            st.frames.clear()
            st.nbytes = 0
        for mt_, rids_, reg_ in leftovers:
            for r in rids_ or ():
                self._count_drop(r, mt_)
            if reg_ is not None:
                reg_.release()

    def _conn_cleanup(self, st: _Conn, ids) -> None:
        """Tear one connection down: deregister the ids/ranges still
        mapping here, flush + release its queued entries, dump the
        black box for a live death, and close the lane + socket.
        Shared by the threaded reader's ``finally`` and the reactor's
        ``_close_conn_r``."""
        lost: List[int] = []
        lost_ranges = 0
        with self._lock:
            st.dead = True
            # identity guard: a re-registered node may have
            # been rebound to a newer conn; deregister only the
            # ids still mapping HERE
            for nid in ids:
                if self._conns.get(nid) is st:
                    self._conns.pop(nid, None)
                    lost.append(nid)
            if st in self._range_conns:
                # a dying range conn takes its whole cohort
                # claim with it (displaced conns were already
                # removed at rebind time and don't reach here)
                self._range_conns.remove(st)
                lost_ranges = sum(
                    hi - lo + 1 for lo, hi in st.ranges)
        # release queued pins NOW: in threaded mode a scheduled sender
        # worker also clears dead conns (whoever pops under the lock
        # wins — never a double release), but a conn dying between
        # visits, or at stop(), must not strand its regions
        self._flush_conn_queues(st)
        if lost_ranges and self._running:
            flight.note("events", "conn_death", cid=st.cid,
                        mux=st.mux, node_ranges=list(st.ranges),
                        n_nodes=lost_ranges)
            flight.trigger(
                "conn_death",
                reason=f"hub conn cid={st.cid} died; lost "
                       f"range claim {list(st.ranges)} "
                       f"({lost_ranges} node id(s))",
            )
        if lost and self._running:
            # a live connection died while the hub is serving —
            # the black box dumps with the per-conn queue
            # gauges and hub_stats ring still warm.  A rebound
            # conn (ids already claimed elsewhere) is NOT a
            # death; ``lost`` is only the ids that went dark.
            flight.note("events", "conn_death", cid=st.cid,
                        mux=st.mux, node_ids=sorted(lost)[:64],
                        n_nodes=len(lost))
            flight.trigger(
                "conn_death",
                reason=f"hub conn cid={st.cid} died; lost "
                       f"{len(lost)} node id(s) "
                       f"{sorted(lost)[:8]}",
            )
        if st.lane is not None:
            # detach AND unlink: a gracefully-stopping dialer
            # unlinks its own slab too (double unlink is a caught
            # no-op), but a CRASHED dialer (os._exit) never will —
            # without this, every peer crash leaks a segment in
            # /dev/shm until reboot.  Mapped regions survive the
            # unlink, so a reconnecting peer's fresh slab is
            # unaffected.  To the peer this must look exactly like
            # a dropped connection, and it does: doorbells stop,
            # the socket closes, the reconnect path re-dials with
            # a fresh slab.
            st.lane.close(unlink=True)
        try:
            st.sock.close()
        except OSError:
            pass

    # -- reactor data plane --------------------------------------------------
    #
    # Everything below runs on the ONE event-loop thread (plus _wake,
    # which any thread may call).  Per-conn reactor fields (parser,
    # phase, wpend, wcommit, wregion, want_write) are loop-thread-only
    # by construction — the reactor's analogue of the single-drainer
    # rule — while the routing/queue state stays under self._lock
    # exactly as in threaded mode.

    def _reactor_loop(self):
        """The hub's event loop: one thread multiplexing accepts,
        streaming reads (header/payload/doorbell), and writability-
        driven queue drains for every connection — O(1) threads per
        hub where the threaded plane burns one reader per conn."""
        self._loop_tid = threading.get_ident()
        tel = get_telemetry()
        sel = self._sel
        while self._running:
            try:
                events = sel.select(timeout=1.0)
            except OSError:
                break
            t0 = time.perf_counter()
            for key, mask in events:
                data = key.data
                try:
                    if data is None:
                        self._on_accept()
                    elif data == "wakeup":
                        try:
                            while os.read(self._wakeup_r, 4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        if data.dead:
                            continue
                        if mask & selectors.EVENT_WRITE:
                            # a parked conn's socket opened up: resume
                            # its drain this batch (scheduled stays
                            # True while parked, so _wake dedup keeps
                            # holding)
                            with self._lock:
                                self._drainq.append(data)
                        if mask & selectors.EVENT_READ:
                            self._on_readable(data)
                except Exception:
                    # last-resort backstop: an escaped event-handler
                    # error must cost ONE connection, never the loop
                    # (a dead loop wedges every conn on the hub)
                    if isinstance(data, _Conn):
                        logging.exception(
                            "hub: reactor event error on conn "
                            "cid=%s — dropping conn", data.cid)
                        self._close_conn_r(data)
                    else:
                        logging.exception("hub: reactor event error")
            self._drain_batch()
            if events:
                # loop lag: time this batch kept the loop away from
                # select — the reactor's health metric (a stall here
                # is queue wait for EVERY connection at once)
                tel.observe("hub.loop_lag_s", time.perf_counter() - t0)
        # shutdown: tear every conn down through the full cleanup path
        # so queued pins are released, then drop the selector + pipe
        for key in list(sel.get_map().values()):
            if isinstance(key.data, _Conn):
                self._close_conn_r(key.data)
        try:
            sel.close()
        except OSError:
            pass
        for fd in (self._wakeup_r, self._wakeup_w):
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            self._srv.close()
        except OSError:
            pass

    def _on_accept(self):
        """Accept every pending connection (edge-triggered burst):
        non-blocking socket, a fresh streaming parser, EVENT_READ
        registration — no thread spawn, which is what keeps per-conn
        accept latency flat through a 512-connection churn storm."""
        while True:
            try:
                conn, _ = self._srv.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            _tune_socket(conn)
            conn.setblocking(False)
            st = _Conn(conn)
            st.parser = FrameParser(pool=self._bufpool,
                                    scratch=self._rx_scratch)
            try:
                self._sel.register(conn, selectors.EVENT_READ, st)
            except (ValueError, KeyError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass

    def _on_readable(self, st: _Conn):
        """Service one conn's readability: recv into the parser's
        target (scratch for headers, the pooled payload region's tail
        once mid-payload), dispatch every completed frame.  Bounded to
        ~16 recvs per event so a firehose conn can't starve the cohort
        — the level-triggered select re-reports whatever is left."""
        sock = st.sock
        for _ in range(16):
            try:
                n = sock.recv_into(st.parser.recv_target())
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._close_conn_r(st)
                return
            if n == 0:
                self._close_conn_r(st)  # EOF
                return
            try:
                frames = st.parser.consumed(n)
            except FrameError as e:
                logging.warning(
                    "hub: conn cid=%s dropped (%s)", st.cid, e)
                self._close_conn_r(st)
                return
            except Exception:
                # never lose the LOOP to a parser bug (same contract
                # as the _on_frame catch-all below): the conn dies
                # alone, _close_conn_r's parser.close() releases any
                # in-progress pooled region
                logging.exception(
                    "hub: parser error on conn cid=%s", st.cid)
                self._close_conn_r(st)
                return
            for idx, (frame, line, payload, region) in enumerate(frames):
                try:
                    keep = self._on_frame(st, frame, line, payload,
                                          region)
                except _HubConnError:
                    keep = False
                except OSError:
                    keep = False
                except Exception:
                    # never lose the LOOP to a routing bug — that
                    # would be every connection wedged at once; the
                    # faulting conn dies alone (threaded parity: an
                    # unexpected error killed that reader thread)
                    logging.exception(
                        "hub: reactor error on conn cid=%s", st.cid)
                    keep = False
                if not keep:
                    for _f, _l, _p, r in frames[idx + 1:]:
                        if r is not None:
                            r.release()
                    self._close_conn_r(st)
                    return

    def _on_frame(self, st: _Conn, frame: dict, line: bytes, payload,
                  region) -> bool:
        """Handle ONE parsed frame: the handshake state machine
        (hello → clock-sync → registered), then the routing dispatch
        shared with the threaded reader.  Returns False when the
        connection must close.  Owns ``region`` (the parser handed the
        reader's reference over) and releases it before returning."""
        if st.phase == 0:
            if region is not None:
                region.release()  # a hello never carries a payload
            ids, ranges, mux, nid0 = self._parse_hello(frame)
            st.ids = set(ids)
            st.ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)
            st.mux = mux
            st.nid0 = nid0
            st.lane = self._attach_lane(frame, nid0)
            # ACK BEFORE registering (same contract as the threaded
            # plane: nothing can be routed here until registration, so
            # the queued ACK is the next bytes the dialer reads)
            self._ctrl_reply(st, {**_ACK, "shm": st.lane is not None})
            st.phase = 1
            return True
        if st.phase == 1:
            if region is not None:
                region.release()
            kind = frame.get(HUB_KEY)
            if kind == "ping":
                # still unregistered: the queued pong is guaranteed to
                # be the dialer's next line — RTT stays pure wire +
                # one loop batch
                self._ctrl_reply(st, {
                    HUB_KEY: "pong",
                    "t0": frame.get("t0"),
                    "th": time.perf_counter(),
                })
                return True
            if kind == "ping_done":
                self._finish_register_r(st)
                return True
            # pre-handshake peers (an old dialer): register now and
            # fall through to route this very frame
            self._finish_register_r(st)
        binlen = frame.get(FRAME_BINLEN_KEY)
        sseq = frame.pop(SHM_SEQ_KEY, None)
        if binlen and sseq is not None:
            payload, region = self._laned_payload(
                st, st.nid0, binlen, sseq)
        try:
            return self._route_frame(st, st.nid0, frame, line,
                                     payload, sseq, region)
        finally:
            if region is not None:
                region.release()

    def _finish_register_r(self, st: _Conn) -> None:
        """Registration, reactor side: install the routes (shared
        rebind policy) and dispose of the displaced conns inline —
        there is no per-conn reader thread to notice their EOF."""
        stale = self._register_conn(st, sorted(st.ids))
        for old in stale:
            self._close_conn_r(old)
        st.phase = 2

    def _ctrl_reply(self, st: _Conn, obj: dict) -> None:
        """Queue a handshake control line (ACK, pong) on the conn's own
        frame queue: before registration nothing else can enqueue to
        it, so the reply is the next bytes the dialer reads — the
        threaded plane's direct-write guarantee, in queue form."""
        data = (json.dumps(obj) + "\n").encode()
        with self._lock:
            if st.dead:
                return
            st.frames.append((None, (data,), None, len(data), (), None))
            st.nbytes += len(data)
            if not st.scheduled:
                st.scheduled = True
                self._drainq.append(st)

    def _drain_batch(self):
        """Drain every conn the batch scheduled.  Head-start contract,
        reactor form: a strict-priority first pass drains queued heads
        (stripe 0s) across ALL conns before any conn's tail pass — so
        every receiver starts streaming within one batch — then tails
        drain to each socket until it would block; the kernel's socket
        buffer is the pacing, and a blocked conn parks on EVENT_WRITE
        instead of holding a worker."""
        with self._lock:
            if not self._drainq:
                return
            batch = list(dict.fromkeys(self._drainq))
            self._drainq.clear()
        for st in batch:
            self._drain_conn(st, heads_only=True)
        for st in batch:
            self._drain_conn(st, heads_only=False)

    def _drain_conn(self, st: _Conn, heads_only: bool = False) -> None:
        """Drain one conn's queues onto its non-blocking socket until
        empty (unschedule + unpark) or the socket would block (park on
        EVENT_WRITE).  Frame bytes are built by the SAME helpers as
        the threaded sender pool (``_entry_wire``/``_prepare_send``),
        so the two planes are byte-identical by construction."""
        if st.dead:
            return
        while True:
            if st.wpend and not self._flush_wpend(st):
                return  # parked (or died) mid-frame
            stale_rids = False
            live_nodes = None
            stale_subset: Tuple = ()
            popped = False
            with self._lock:
                if st.dead:
                    return
                if st.heads:
                    msg_type, parts, hdr, nbytes, rids, region = \
                        st.heads.popleft()
                    st.nbytes -= nbytes
                    popped = True
                elif not heads_only and st.frames:
                    msg_type, parts, hdr, nbytes, rids, region = \
                        st.frames.popleft()
                    st.nbytes -= nbytes
                    popped = True
                elif not heads_only:
                    # fully drained: give the conn up (a later
                    # _forward re-schedules it) and stop watching
                    # writability, or the level-triggered select
                    # would spin on the always-writable socket
                    st.scheduled = False
                if popped:
                    stale_rids, live_nodes, stale_subset = \
                        self._stale_check_locked(st, rids, hdr)
            if not popped:
                if not heads_only:
                    self._unpark(st)
                return
            if stale_rids:
                # every id this entry addressed was rebound away
                if region is not None:
                    region.release()
                for r in rids:
                    self._count_drop(r, msg_type)
                continue
            if live_nodes is not None:
                # partially-rebound mux copy: the stolen ids lose
                # this frame (counted), the live ones still get it
                for r in stale_subset:
                    self._count_drop(r, msg_type)
            try:
                hdr_dict, line, body = self._entry_wire(
                    parts, hdr, live_nodes)
                out_parts, commit = self._prepare_send(
                    st, hdr_dict, line, body)
            except Exception:
                # never lose the loop to a drain bug: the frame dies
                # (counted), the conn keeps draining — threaded-plane
                # parity with the sender worker's catch-all
                logging.exception("hub: reactor drain error for "
                                  "conn cid=%s", st.cid)
                if region is not None:
                    region.release()
                self._count_drop(rids[0] if rids else -1, msg_type)
                continue
            pend = [p if isinstance(p, memoryview) else memoryview(p)
                    for p in out_parts]
            st.wpend = [v if v.format == "B" and v.ndim == 1
                        else v.cast("B") for v in pend]
            st.wcommit = commit
            st.wregion = region
            st.wmsg_type = msg_type
            # loop: the flush at the top writes it out

    def _flush_wpend(self, st: _Conn) -> bool:
        """Write the in-flight frame's remaining bytes.  True when the
        frame fully flushed (lane doorbell committed, payload pin
        released); False when the socket would block (conn parked on
        EVENT_WRITE) or died (torn down inline)."""
        sock = st.sock
        pend = st.wpend
        try:
            while pend:
                if hasattr(sock, "sendmsg"):
                    sent = sock.sendmsg(pend[:_IOV_MAX])
                else:  # non-POSIX fallback
                    sent = sock.send(pend[0])
                while sent:
                    head = pend[0]
                    if sent >= len(head):
                        sent -= len(head)
                        pend.pop(0)
                    else:
                        pend[0] = head[sent:]
                        sent = 0
        except (BlockingIOError, InterruptedError):
            st.wpend = pend
            self._park(st)
            return False
        except OSError:
            # dead receiver: count the in-flight frame, tear the conn
            # down (cleanup flushes + releases everything queued) —
            # the sender pool's OSError contract, reactor form
            self._count_drop(st.nid0 if st.nid0 is not None else -1,
                             st.wmsg_type)
            self._close_conn_r(st)
            return False
        st.wpend = []
        if st.wcommit is not None:
            # doorbell fully on the socket AFTER the payload was fully
            # in the slab: commit announces the descriptor (a writer
            # killed in between leaves nothing deliverable)
            lane, pending, nbody = st.wcommit
            st.wcommit = None
            lane.commit(pending)
            with self._lock:
                self.shm_frames += 1
                self.shm_bytes += nbody
        if st.wregion is not None:
            # sent: this entry's pin dies here — when the LAST queue's
            # copy drains, the region's bytes are reclaimed
            st.wregion.release()
            st.wregion = None
        st.wmsg_type = None
        return True

    def _park(self, st: _Conn) -> None:
        if st.want_write or st.dead:
            return
        try:
            self._sel.modify(st.sock,
                             selectors.EVENT_READ | selectors.EVENT_WRITE,
                             st)
            st.want_write = True
        except (KeyError, ValueError, OSError):
            pass

    def _unpark(self, st: _Conn) -> None:
        if not st.want_write:
            return
        try:
            self._sel.modify(st.sock, selectors.EVENT_READ, st)
        except (KeyError, ValueError, OSError):
            pass
        st.want_write = False

    def _close_conn_r(self, st: _Conn) -> None:
        """Reactor-side conn teardown: selector deregistration, the
        parser's in-progress region, the in-flight frame's pin, then
        the shared cleanup (deregister ids, flush queued pins, flight
        triggers, lane + socket close).  Idempotent — displacement and
        a same-batch EOF may both reach here."""
        if st.parser is None and st.dead:
            return  # already torn down
        try:
            self._sel.unregister(st.sock)
        except (KeyError, ValueError, OSError):
            pass
        if st.parser is not None:
            st.parser.close()
            st.parser = None
        if st.wregion is not None:
            st.wregion.release()
            st.wregion = None
        st.wcommit = None
        st.wpend = []
        st.want_write = False
        self._conn_cleanup(st, list(st.ids))

    def _wake(self, st: _Conn, receiver) -> None:
        """Hand a newly-scheduled conn to its drain plane: the sender
        pool's ready queue (threaded), or the loop's drain queue plus
        — when called OFF the loop thread — a wakeup-pipe tickle so a
        sleeping select services it now.  That pipe is the reactor's
        one cross-thread entry point: stop() uses it too, and chaos
        timer-delayed deliveries need nothing special (they arrive as
        ordinary socket readability on the injecting backend's conn)."""
        if self._mode == "threaded":
            self._ready.put((receiver, st))
            return
        with self._lock:
            self._drainq.append(st)
        if threading.get_ident() != self._loop_tid:
            self._tickle()

    def _tickle(self) -> None:
        try:
            os.write(self._wakeup_w, b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full: a wakeup is already pending

    def _route_frame(self, st: _Conn, node_id: int, frame: dict,
                     line: bytes, payload, sseq, region) -> bool:
        """Route ONE inbound frame (header parsed, payload in hand) —
        the body of the reader loop's dispatch.  Returns False when the
        connection must close (a ``stop`` frame), True otherwise.

        ``payload`` is bytes on the inline-TCP path and a slab
        MEMORYVIEW on the zero-copy lane path; ``region`` is the
        backing pin (or None).  The caller releases the reader's own
        reference right after this returns, so every enqueue below
        retains per entry (``_forward`` / ``_forward_stripes``) — the
        routing layer itself never copies a laned payload."""
        if frame.get(HUB_KEY) == "mcast":
            # hub multicast: ``payload`` is ONE complete inner
            # frame (header line + buffers) shipped once over
            # the server→hub leg; fan it out by enqueueing the
            # SAME immutable bytes per receiver — receivers see
            # an ordinary frame, no client-side support needed
            receivers = frame.get("receivers") or []
            mt = frame.get("msg_type")
            if not payload:
                logging.warning("hub: mcast frame without payload")
                return True
            # per-conn dedup FIRST: receivers sharing a muxed
            # connection collapse to ONE wrapped copy per
            # connection; mcast_copies counts the physical
            # copies actually enqueued (== receivers for v1
            # dialers, == connections under muxing)
            groups, unknown = self._conn_groups(receivers)
            for r in unknown:
                self._count_drop(r, mt)
            with self._lock:
                self.mcast_frames += 1
                self.mcast_copies += len(groups)
            get_telemetry().inc("hub.mcast_frames",
                                msg_type=mt or "?")
            if (self._stripe_bytes
                    and len(payload) > self._stripe_bytes
                    and len(payload) <= _MAX_REASM_BYTES // 2):
                self._fan_out_striped(frame, groups, mt, payload,
                                      region=region)
                return True
            # traced mcast (outer header flags it): split the
            # inner frame at its header line ONCE, stamp hub_in,
            # and queue (parsed header, shared payload-tail
            # view) per receiver — the sender worker re-encodes
            # the small header per copy with its own hub_out
            # stamp while the multi-MB tail stays one object.
            # Mux wraps (traced AND untraced) are DEFERRED
            # (kind, meta, hdr) entries: the worker builds the
            # outer line at drain, filtering the target nodes
            # against the conn's live id set — a rebind while
            # the copy waits must not be fanned out to the
            # stolen id by the displaced owner.
            hdr, tail = _split_traced_mcast(frame, payload)
            for cst, rids in groups:
                if not cst.mux:
                    # plain single-id conn: the pre-mux path
                    if hdr is not None:
                        self._forward(rids[0], (tail,),
                                      msg_type=mt, hdr=hdr,
                                      nbytes=len(payload),
                                      conn=cst, region=region)
                    else:
                        self._forward(rids[0], (payload,),
                                      msg_type=mt, conn=cst,
                                      region=region)
                    continue
                body = (tail,) if hdr is not None else (payload,)
                meta = self._range_meta(cst, rids) \
                    or {"nodes": rids}
                meta["msg_type"] = mt
                ok = self._forward(
                    rids[0], body, msg_type=mt,
                    hdr=(MUX_KIND, meta, hdr),
                    nbytes=len(payload), rids=rids, conn=cst,
                    region=region)
                if not ok:
                    # _forward counted the representative id;
                    # the co-located rest lost the same copy
                    for r in rids[1:]:
                        self._count_drop(r, mt)
            return True
        if frame.get(HUB_KEY) == "peers":
            # membership introspection: reply to THIS node with
            # the currently registered ids (startup barrier —
            # frames to unregistered receivers are dropped, so
            # coordinators must await their cohort first)
            with self._lock:
                peer_ids = sorted(self._conns)
                peer_ranges = sorted(
                    [list(r) for rc in self._range_conns
                     for r in rc.ranges])
            reply_obj = {HUB_KEY: "peers", "ids": peer_ids}
            if peer_ranges:
                # range-claim cohorts are present by [lo, hi], never
                # enumerated — the whole point is O(edges) state
                reply_obj["ranges"] = peer_ranges
            self._forward(
                node_id,
                ((json.dumps(reply_obj) + "\n").encode(),),
            )
            return True
        if frame.get(HUB_KEY) == "conn_map":
            # connection-attribution introspection (the robust
            # aggregator's anti-Sybil lever): the HUB is the
            # authority on which node ids share a physical
            # connection — a malicious muxer cannot lie its
            # virtual cohort into looking like independent
            # connections.  Reply {cid: [node ids]} to the
            # requester; one frame per request, no hot-path
            # cost for anyone who never asks.
            with self._lock:
                by_cid: Dict[int, list] = {}
                for nid, cst in self._conns.items():
                    by_cid.setdefault(cst.cid, []).append(nid)
                range_cids = {str(rc.cid): [list(r) for r in rc.ranges]
                              for rc in self._range_conns}
            reply = {HUB_KEY: "conn_map",
                     "conns": {str(c): sorted(v)
                               for c, v in by_cid.items()}}
            if range_cids:
                # range conns report [lo, hi] spans, not id lists —
                # the anti-Sybil contract still holds (the hub is
                # the authority on the claim either way)
                reply["conn_ranges"] = range_cids
            self._forward(
                node_id,
                ((json.dumps(reply) + "\n").encode(),),
            )
            return True
        if frame.get(HUB_KEY) == "stop":
            return False
        receiver = frame.get("receiver")
        if receiver is not None:
            if trace_ctx.TRACE_KEY in frame:
                # traced unicast: the line IS the header — stamp
                # hub_in on the parsed dict and let the sender
                # worker re-encode it with hub_out at drain
                trace_ctx.hub_stamp(frame, "hub_in")
                self._forward(receiver,
                              (payload,) if payload else (),
                              msg_type=frame.get("msg_type"),
                              hdr=frame,
                              nbytes=len(line) + len(payload),
                              region=region)
            else:
                if sseq is not None:
                    # the raw forward ships this header line:
                    # re-encode it WITHOUT the doorbell key
                    # (popped above) — the receiver must never
                    # be told to read someone else's lane.
                    # Lazy on purpose: the dominant laned
                    # shapes (mcast/control) re-encode at drain
                    # from the parsed dict and never pay this.
                    line = (json.dumps(frame) + "\n").encode()
                self._forward(receiver,
                              (line, payload) if payload else (line,),
                              msg_type=frame.get("msg_type"),
                              region=region)
        return True

    def _lookup_locked(self, receiver: int):  # fedlint: holds=_lock
        """Resolve ``receiver`` to its connection: per-id map first,
        then the (short) range-claim list."""
        assert_held(self._lock, "TcpHub._lookup_locked")
        st = self._conns.get(receiver)
        if st is not None:
            return st
        for rc in self._range_conns:
            for lo, hi in rc.ranges:
                if lo <= receiver <= hi:
                    return rc
        return None

    def _conn_groups(self, receivers):
        """Group a receiver-id list by physical connection (the mcast
        per-conn dedup): ``([(conn, [ids...]), ...], [unknown ids])`` in
        first-appearance order.  A broadcast to 500 co-located virtual
        clients then ships the shared payload once per CONNECTION, not
        once per node."""
        groups: List[tuple] = []
        by_conn: Dict[int, tuple] = {}
        unknown: List[int] = []
        with self._lock:
            for r in receivers:
                st = self._lookup_locked(r)
                if st is None:
                    unknown.append(r)
                    continue
                key = id(st)
                ent = by_conn.get(key)
                if ent is None:
                    ent = by_conn[key] = (st, [])
                    groups.append(ent)
                ent[1].append(r)
        return groups, unknown

    @staticmethod
    def _range_meta(cst, rids):
        """The compact mux-wrap target for a fully-covered single-range
        conn, or None.  A sync addressed to an edge's ENTIRE cohort is
        the common case, and naming 25k consecutive ids costs ~175 KB
        of outer-header JSON per copy — ``{"range": [lo, hi]}`` says
        the same thing in constant space.  Partial coverage (sampled
        participation) falls back to the explicit id list."""
        if (len(cst.ranges) == 1 and not cst.ids):
            lo, hi = cst.ranges[0]
            if len(rids) == hi - lo + 1:
                return {"range": [lo, hi]}
        return None

    def _forward(self, receiver: int, parts: Tuple, msg_type=None,
                 hdr=None, nbytes=None, rids=None, conn=None,
                 region=None) -> bool:
        """Enqueue one frame for ``receiver``; the sender pool writes
        it.  Untraced (``hdr=None``): ``parts`` is the COMPLETE frame
        (header line [+ payload]).  Traced: ``hdr`` is the parsed
        header dict (already ``hub_in``-stamped; shared across an
        mcast's receiver queues) — or a deferred ``(kind, meta, inner
        header)`` tuple — and ``parts`` holds only the payload tail:
        the sender worker re-encodes the header line at drain time.
        Unknown receivers and over-bound queues drop the frame —
        counted, by design (the round deadline treats the receiver as a
        straggler).  Returns False when the frame was dropped (mux-group
        callers count the co-located ids the same drop cost).

        ``conn`` pins the target connection (mcast group paths resolve
        it ONCE in ``_conn_groups``): re-resolving by id here could
        land a mux-wrapped copy on a connection that REBOUND the
        representative id in between — the wrong peer entirely.

        ``region`` is the slab pin backing a zero-copy laned payload:
        retained BEFORE the entry becomes drainable (a sender worker
        may pop and release it the instant the lock drops) and released
        back on the drop path — the entry's reference must exist
        exactly when the entry does."""
        if nbytes is None:
            nbytes = sum(len(p) for p in parts)
        wake = False
        dropped = False
        if region is not None:
            region.retain()
        with self._lock:
            st = (conn if conn is not None
                  else self._lookup_locked(receiver))
            if st is None or st.dead:
                dropped = True
            elif (len(st.frames) + len(st.heads) >= self._max_queue_frames
                    or st.nbytes + nbytes > self._max_queue_bytes):
                self.backpressure_drops += 1
                dropped = True
            else:
                st.frames.append((msg_type, parts, hdr, nbytes,
                                  tuple(rids) if rids else (receiver,),
                                  region))
                st.nbytes += nbytes
                if region is not None:
                    self.zero_copy_forwards += 1
                if not st.scheduled:
                    st.scheduled = True
                    wake = True
        if dropped:
            if region is not None:
                region.release()
            self._count_drop(receiver, msg_type)
            return False
        if region is not None:
            get_telemetry().inc("hub.zero_copy_forwards",
                                msg_type=msg_type or "?")
        if wake:
            self._wake(st, receiver)
        return True

    def _fan_out_striped(self, frame: dict, groups, mt,
                         payload, region=None) -> None:
        """Split one mcast payload into ``mcast_stripe`` frames and
        enqueue the stripe sequence to every receiver.

        Every stripe is self-describing (its own ``__binlen__`` + a
        crc32 of its chunk), so the receiver reassembles by simple
        concatenation and detects a lost/corrupted stripe without
        trusting stream position.  The chunk buffers are memoryviews
        over the ONE payload object shared by every receiver's queue —
        striping adds outer headers, never payload copies.

        Traced frames keep the per-receiver queue-wait measurement:
        stripe 0 carries the parsed inner header dict instead of bytes,
        and the sender worker re-encodes that line with a fresh
        ``hub_out`` stamp at drain time (crc computed then) — exactly
        the whole-frame traced contract, per copy.
        """
        sid = next(self._sid)
        hdr, tail = _split_traced_mcast(frame, payload)
        body = tail if hdr is not None else memoryview(payload)
        chunks = [body[i:i + self._stripe_bytes]
                  for i in range(0, len(body), self._stripe_bytes)]
        total = len(chunks) + (1 if hdr is not None else 0)
        base = 1 if hdr is not None else 0

        def chunk_entry(k: int) -> tuple:
            ch = chunks[k]
            h = {HUB_KEY: MCAST_STRIPE_KIND, "sid": sid, "i": base + k,
                 "n": total, "msg_type": mt, "crc": zlib.crc32(ch),
                 FRAME_BINLEN_KEY: len(ch)}
            outer = (json.dumps(h) + "\n").encode()
            return (mt, (outer, ch), None, len(outer) + len(ch))

        # tail entries are shared tuples across every connection's
        # queue (same immutable buffers); only stripe 0 is per-conn —
        # a muxed conn's carries the co-located ``nodes`` list the
        # demuxing backend fans the reassembled frame out to
        if hdr is not None:
            tails = [chunk_entry(k) for k in range(len(chunks))]
        else:
            tails = [chunk_entry(k) for k in range(1, len(chunks))]
        with self._lock:
            self.striped_mcasts += 1
        # head-start scheduling: EVERY connection's stripe 0 rides the
        # strict-priority head queue, so the pool drains all head
        # stripes (small) before any tail — every receiver starts
        # streaming within one head round (bcast_queue ≈ that round,
        # not K-1 whole-frame sends) — and then drains tails at
        # ``max_inflight_stripes`` locality, which staggers COMPLETION
        # times so receivers that finish early overlap their downstream
        # work with the rest of the fan-out (measured: full round-robin
        # equalizes completion and serializes the cohort's post-receive
        # compute AFTER the fan-out window; see PROFILE.md round-9).
        for cst, rids in groups:
            if hdr is not None:
                # deferred stripe 0: (kind, outer meta, inner header
                # dict) — the worker builds outer line + restamped
                # inner line at drain.  nbytes is the original line's
                # length (queue accounting only; the restamp grows it
                # by one hop).
                meta0 = {"sid": sid, "i": 0, "n": total, "msg_type": mt}
                if cst.mux:
                    meta0.update(self._range_meta(cst, rids)
                                 or {"nodes": rids})
                head_entry = (mt, (), (MCAST_STRIPE_KIND, meta0, hdr),
                              len(payload) - len(body) + 64)
            elif cst.mux:
                # untraced mux stripe 0 is ALSO deferred: the worker
                # re-encodes the small outer header at drain with the
                # ``nodes`` list filtered to the conn's live ids (a
                # rebind mid-queue-wait must drop the stolen id from
                # the local fan-out); the chunk rides as parts
                ch0 = chunks[0]
                meta0 = {"sid": sid, "i": 0, "n": total, "msg_type": mt,
                         "crc": zlib.crc32(ch0)}
                meta0.update(self._range_meta(cst, rids)
                             or {"nodes": rids})
                head_entry = (mt, (ch0,), (MCAST_STRIPE_KIND, meta0,
                                           None), len(ch0) + 96)
            else:
                head_entry = chunk_entry(0)
            self._forward_stripes(cst, rids, [head_entry], mt,
                                  head=True, region=region)
        for cst, rids in groups:
            self._forward_stripes(cst, rids, tails, mt, region=region)

    def _forward_stripes(self, conn: _Conn, receivers: List[int],
                         entries: List[tuple], msg_type,
                         head: bool = False, region=None) -> None:
        """Enqueue one segment of a logical frame's stripe sequence
        atomically (all or nothing) onto the PRE-RESOLVED connection
        ``receivers`` share: an over-bound queue drops the whole
        segment in one counted decision — the receiver then sees an
        index gap (tail dropped after its head) or nothing at all, and
        either way the logical frame dies with straggler semantics
        instead of wedging reassembly (a gap aborts the stream; a head
        with no tail is evicted by the bounded-stream cap).

        ``region``: the stripe chunks are views over ONE laned payload
        — each queued entry carries its own retain() (the drain
        releases per entry), so the slab bytes live exactly as long as
        the last undrained stripe anywhere."""
        nbytes = sum(e[3] for e in entries)
        wake = False
        dropped = False
        receiver = receivers[0]
        # tag the (shared-buffer) entries with this connection's target
        # ids — the drain's rebind re-check needs them; the buffers
        # themselves stay shared across connections
        rids = tuple(receivers)
        tagged = [(e[0], e[1], e[2], e[3], rids, region) for e in entries]
        if region is not None:
            for _ in entries:
                region.retain()
        with self._lock:
            st = conn
            if st.dead:
                dropped = True
            elif (len(st.frames) + len(st.heads) + len(entries)
                    > self._max_queue_frames
                    or st.nbytes + nbytes > self._max_queue_bytes):
                self.backpressure_drops += 1
                dropped = True
            else:
                (st.heads if head else st.frames).extend(tagged)
                st.nbytes += nbytes
                self.stripe_frames += len(entries)
                if not st.scheduled:
                    st.scheduled = True
                    wake = True
        if dropped:
            if region is not None:
                for _ in entries:
                    region.release()
            for r in receivers:
                self._count_drop(r, msg_type)
            return
        if wake:
            self._wake(st, receiver)

    def _sender_loop(self):
        """Sender-pool worker: drain the one connection handed to it.
        A worker only ever services the exact ``_Conn`` it was
        scheduled for (never a same-id replacement), so a reconnecting
        node can't end up with two drainers interleaving its stream.

        Pacing: after ``max_inflight_stripes`` frames to one conn the
        worker re-queues it at the BACK of the ready queue and moves
        on (``scheduled`` stays True, so there is still exactly one
        drainer).  Combined with the head-start enqueue order
        (``_fan_out_striped``) this bounds how long any receiver's
        stream can monopolize a worker: small pace = round-robin fair
        streaming (equal completion), large pace = locality draining
        (staggered completion, better when receivers share cores with
        the hub).
        """
        while True:
            item = self._ready.get()
            if item is None:
                return
            nid, st = item
            quantum = 0
            while True:
                requeue = False
                from_head = False
                stale_rids = False
                live_nodes = None  # filtered mux/stripe-0 target list
                stale_subset: Tuple = ()
                dead_leftovers = None
                region = None
                with self._lock:
                    if st.dead:
                        # replaced/deregistered: frames die with it —
                        # COUNTED, like the OSError path's leftovers
                        # (the rebind policy promises visible drops)
                        dead_leftovers = [(e[0], e[4], e[5])
                                          for e in st.heads]
                        dead_leftovers += [(e[0], e[4], e[5])
                                           for e in st.frames]
                        st.heads.clear()
                        st.frames.clear()
                        st.nbytes = 0
                    elif st.heads:
                        # strict priority, quantum-exempt: heads are
                        # small and the head-start contract wants all
                        # of them out before any conn's tail
                        msg_type, parts, hdr, nbytes, rids, region = \
                            st.heads.popleft()
                        st.nbytes -= nbytes
                        from_head = True
                    elif not st.frames:
                        st.scheduled = False
                        break
                    elif quantum >= self._pace:
                        requeue = True
                    else:
                        msg_type, parts, hdr, nbytes, rids, region = \
                            st.frames.popleft()
                        st.nbytes -= nbytes
                    if not requeue and dead_leftovers is None:
                        stale_rids, live_nodes, stale_subset = \
                            self._stale_check_locked(st, rids, hdr)
                if dead_leftovers is not None:
                    for mt_, rids_, reg_ in dead_leftovers:
                        for r in rids_ or ():
                            self._count_drop(r, mt_)
                        if reg_ is not None:
                            reg_.release()
                    break
                if requeue:
                    self._ready.put((nid, st))
                    break
                # a head send exhausts the visit's quantum: the worker
                # requeues before touching this conn's TAIL, so every
                # other conn's pending head drains first (the requeue
                # lands behind them in the FIFO ready queue)
                quantum = self._pace if from_head else quantum + 1
                if stale_rids:
                    # every id this entry addressed was rebound away
                    if region is not None:
                        region.release()
                    for r in rids:
                        self._count_drop(r, msg_type)
                    continue
                if live_nodes is not None:
                    # partially-rebound mux copy: the stolen ids lose
                    # this frame (counted), the live ones still get it
                    for r in stale_subset:
                        self._count_drop(r, msg_type)
                try:
                    hdr_dict, line, body = self._entry_wire(
                        parts, hdr, live_nodes)
                    self._conn_send(st, hdr_dict, line, body, msg_type)
                except OSError:
                    # dead receiver: count this frame + everything still
                    # queued, deregister (its reader thread finishes
                    # cleanup when it sees EOF)
                    if region is not None:
                        region.release()
                    self._count_drop(nid, msg_type)
                    with self._lock:
                        st.dead = True
                        for i in list(st.ids):
                            if self._conns.get(i) is st:
                                self._conns.pop(i, None)
                        leftovers = [(e[0], e[4], e[5])
                                     for e in st.heads]
                        leftovers += [(e[0], e[4], e[5])
                                      for e in st.frames]
                        st.heads.clear()
                        st.frames.clear()
                        st.nbytes = 0
                    for mt_, rids_, reg_ in leftovers:
                        for r in rids_ or (nid,):
                            self._count_drop(r, mt_)
                        if reg_ is not None:
                            reg_.release()
                    break
                except Exception:
                    # never lose a pool worker to an unexpected bug —
                    # the hub would silently shrink its send capacity.
                    # Count the frame lost and KEEP DRAINING: breaking
                    # here would leave st.scheduled=True with frames
                    # queued and no drainer — a permanently wedged
                    # receiver (worse than the bug being survived)
                    logging.exception("hub: sender worker error for "
                                      "node %s", nid)
                    if region is not None:
                        region.release()
                    self._count_drop(nid, msg_type)
                    continue
                if region is not None:
                    # sent: this entry's slab pin dies here — when the
                    # LAST queue's copy drains, the ring reclaims
                    region.release()

    def _stale_check_locked(self, st: _Conn, rids,
                            hdr):  # fedlint: holds=_lock
        """Drain-time rebind re-check, shared by both drain planes: any
        id a queued entry targets may have been claimed by a NEWER
        connection while the frame sat queued — the displaced owner
        must not deliver to it (straggler drop, exactly the policy's
        "old conn loses it").  Deferred mux/stripe-0 entries carry
        their target list in ``meta['nodes']`` and get it FILTERED to
        the live subset (the outer header is rebuilt at drain anyway);
        whole entries drop only when every target is gone.  Range-claim
        conns are exempt: their cohort is an atom (displacement kills
        the whole conn via ``st.dead``, never a single id), so
        ``st.ids`` being empty must not read as "everything stale".
        Returns ``(stale_rids, live_nodes, stale_subset)``."""
        assert_held(self._lock, "TcpHub._stale_check_locked")
        if not rids or st.ranges:
            return False, None, ()
        stale_subset = tuple(r for r in rids if r not in st.ids)
        if len(stale_subset) == len(rids):
            return True, None, stale_subset
        live_nodes = None
        if stale_subset and isinstance(hdr, tuple) \
                and hdr[1].get("nodes"):
            live_nodes = [r for r in rids if r in st.ids]
        return False, live_nodes, stale_subset

    @staticmethod
    def _entry_wire(parts, hdr, live_nodes):
        """Build ONE queue entry's wire form — a byte-identity
        chokepoint shared by both drain planes (the sender pool and the
        reactor build identical frames by construction).  Three entry
        shapes:

        - deferred tuple ``(kind, meta, inner_hdr)``: build the outer
          header at drain time — around the hub_out-restamped inner
          header line when traced (``inner_hdr`` set), around the raw
          body otherwise.  Two kinds: stripe 0 of a striped mcast and
          a mux wrap.  ``live_nodes`` (rebind filtering) replaces the
          meta's target list when set.
        - parsed dict: a traced frame — re-encode the (small) header
          line with THIS copy's hub_out stamp at drain time (hub_out -
          hub_in is this receiver's real queue wait); the payload tail
          stays the one shared immutable object.
        - ``None``: untraced complete frame(s) — split the header line
          off the first part so the payload tail is lane-eligible (a
          scan up to the first newline, never a payload copy).

        Returns ``(hdr_dict, line, body)`` for ``_prepare_send``:
        exactly one of ``hdr_dict``/``line`` is set."""
        if isinstance(hdr, tuple):
            kind, meta, inner_hdr = hdr
            if live_nodes is not None:
                meta = {**meta, "nodes": live_nodes}
            if inner_hdr is not None:
                line = trace_ctx.hub_out_line(inner_hdr)
                body = [line, *parts]
            else:
                line = None
                body = list(parts)
            if kind == MUX_KIND:
                out_hdr = {HUB_KEY: MUX_KIND, **meta,
                           FRAME_BINLEN_KEY: sum(len(p) for p in body)}
            else:
                out_hdr = {HUB_KEY: MCAST_STRIPE_KIND, **meta}
                if line is not None:
                    # traced stripe 0: the restamped line IS the
                    # chunk — crc what actually ships
                    out_hdr["crc"] = zlib.crc32(line)
                out_hdr[FRAME_BINLEN_KEY] = sum(len(p) for p in body)
            return out_hdr, None, body
        if hdr is not None:
            stamped = dict(hdr)
            trace_ctx.hub_stamp(stamped, "hub_out")
            return stamped, None, list(parts)
        first = parts[0]
        end = split_frame_line(first)
        if end <= 0 or end == len(first):
            # header-only first part (control frames, the
            # unicast-forward (line, payload) shape)
            return None, first, [p for p in parts[1:] if len(p)]
        # embedded header (whole-frame mcast copy): the tail view
        # shares the one payload object
        view = memoryview(first)
        return (None, bytes(view[:end]),
                [view[end:], *(p for p in parts[1:] if len(p))])

    def _prepare_send(self, st: _Conn, hdr_dict, line, body):
        """Lane-or-inline decision + final header encoding for one
        outbound frame — the second shared chokepoint: both drain
        planes ship exactly what this returns.  Exactly one of
        ``hdr_dict`` (still a dict — drain-built outer headers, traced
        restamps) and ``line`` (already-encoded bytes) is set on entry;
        ``body`` holds the payload parts.  Lane refusal (ring full,
        descriptor queue full, oversized) falls back to the inline
        write, per frame, counted — never an error and never a stall.

        Returns ``(socket_parts, lane_commit)``: ``lane_commit`` is
        ``(lane, pending, nbody)`` when the payload rode the shm ring —
        the caller commits it only AFTER the doorbell line is fully on
        the socket (a writer killed between the two leaves nothing
        deliverable; the descriptor is never announced) and then counts
        ``shm_frames``/``shm_bytes``."""
        lane = st.lane
        nbody = sum(len(p) for p in body) if body else 0
        if lane is not None and nbody >= self._shm_min and nbody:
            pending = lane.try_send(body, nbody)
            if pending is not None:
                if hdr_dict is None:
                    hdr_dict = json.loads(line)
                out = (json.dumps(
                    {**hdr_dict, SHM_SEQ_KEY: ShmLane.seq_of(pending)}
                ) + "\n").encode()
                return [out], (lane, pending, nbody)
            with self._lock:
                self.shm_fallbacks += 1
            get_telemetry().inc("comm.shm_fallbacks",
                                reason=lane.last_refusal)
        if hdr_dict is not None:
            line = (json.dumps(hdr_dict) + "\n").encode()
        return ([line, *body] if body else [line]), None

    def _conn_send(self, st: _Conn, hdr_dict, line, body, msg_type) -> None:
        """Threaded-plane frame write: blocking vectored send of
        whatever ``_prepare_send`` decided, then the lane commit.
        OSErrors propagate to the caller's dead-receiver handling."""
        parts, commit = self._prepare_send(st, hdr_dict, line, body)
        _sendall_parts(st.sock, parts)
        if commit is not None:
            lane, pending, nbody = commit
            lane.commit(pending)
            with self._lock:
                self.shm_frames += 1
                self.shm_bytes += nbody

    def _count_drop(self, receiver: int, msg_type) -> None:
        mt = msg_type or HUB_KEY
        with self._lock:
            self.dropped_frames[mt] = self.dropped_frames.get(mt, 0) + 1
        get_telemetry().inc("hub.dropped_frames", msg_type=mt)
        logging.debug("hub: dropped %s frame to unreachable node %s",
                      mt, receiver)

    def _counters_snapshot(self) -> dict:  # fedlint: holds=_lock
        assert_held(self._lock, "TcpHub._counters_snapshot")
        return {
            "dropped_frames": dict(self.dropped_frames),
            "backpressure_drops": self.backpressure_drops,
            "mcast_frames": self.mcast_frames,
            "mcast_copies": self.mcast_copies,
            "striped_mcasts": self.striped_mcasts,
            "stripe_frames": self.stripe_frames,
            "node_rebinds": self.node_rebinds,
            "shm_frames": self.shm_frames,
            "shm_bytes": self.shm_bytes,
            "shm_fallbacks": self.shm_fallbacks,
            "shm_hub_copies": self.shm_hub_copies,
            "zero_copy_forwards": self.zero_copy_forwards,
        }

    def stats(self) -> dict:
        """Hub-side fault + fan-out accounting (``run_hub`` prints this
        at shutdown so multi-process chaos drivers can collect it).
        ``nodes`` counts registered node ids, ``connections`` physical
        sockets — equal for v1 dialers, many-to-one under muxing."""
        with self._lock:
            snap = self._counters_snapshot()
            snap["nodes"] = len(self._conns) + sum(
                rc.claimed() for rc in self._range_conns)
            conns = set(map(id, self._conns.values()))
            conns.update(map(id, self._range_conns))
            snap["connections"] = len(conns)
            snap["shm_conns"] = len(
                {id(c) for c in self._conns.values()
                 if c.lane is not None}
            )
            snap["range_conns"] = len(self._range_conns)
            snap["mode"] = self._mode
            snap["threads"] = self._thread_count_locked()
            snap["open_fds"] = self._open_fds_locked()
        return snap

    def _thread_count_locked(self) -> int:  # fedlint: holds=_lock
        """Hub-owned thread count — the reactor's O(1)-in-connections
        claim as a measurement: 1 loop thread, vs the threaded plane's
        accept thread + sender pool + one reader per live conn."""
        assert_held(self._lock, "TcpHub._thread_count_locked")
        if self._mode == "reactor":
            return 1
        return 1 + len(self._senders) + self._reader_count

    def _open_fds_locked(self) -> int:  # fedlint: holds=_lock
        """Descriptors the hub holds open (reactor: everything the
        selector watches — server + wakeup pipe + conns; threaded:
        conn sockets + the server)."""
        assert_held(self._lock, "TcpHub._open_fds_locked")
        if self._mode == "reactor":
            try:
                fd_map = self._sel.get_map()
            except RuntimeError:
                fd_map = None
            # a closed selector returns None (3.11) or raises — either
            # way stop() raced the sample and the honest answer is 0
            return len(fd_map) if fd_map is not None else 0
        conns = set(map(id, self._conns.values()))
        conns.update(map(id, self._range_conns))
        return len(conns) + 1

    def sample_telemetry(self, telemetry=None) -> dict:
        """Snapshot ``stats()`` + per-CONNECTION send-queue depths into
        the telemetry registry: gauges for live introspection plus one
        ``hub_stats`` event per call.  Queue depth and backpressure are
        physical-connection properties (a muxer's 500 virtual node ids
        share ONE queue), so the series are keyed by connection id with
        a ``hub.conn_nodes`` gauge carrying each connection's node
        count and a ``hub.nodes`` total alongside — node-keyed copies
        of the same number would overstate a muxed hub's queue memory
        500x.  ``run_hub`` calls this on a timer and drains into
        ``metrics-hub.jsonl``, so a crashed or SIGKILLed hub still
        leaves queue-depth / backpressure evidence behind as a time
        series — and the per-sample ``t_m`` monotonic stamp lets
        ``tools/fed_timeline.py`` line queue depth up against the
        per-frame hop stamps, which share this clock."""
        t = telemetry or get_telemetry()
        with self._lock:
            depths = {}
            nodes_total = len(self._conns) + sum(
                rc.claimed() for rc in self._range_conns)
            shm_conns = 0
            for st in set(self._conns.values()) | set(self._range_conns):
                depths[st.cid] = (len(st.frames) + len(st.heads),
                                  st.nbytes, st.claimed())
                if st.lane is not None:
                    shm_conns += 1
            snap = self._counters_snapshot()
            hub_threads = self._thread_count_locked()
            open_fds = self._open_fds_locked()
        for cid, (nframes, nbytes, nids) in depths.items():
            t.gauge_set("hub.send_queue_frames", nframes, conn=cid)
            t.gauge_set("hub.send_queue_bytes", nbytes, conn=cid)
            t.gauge_set("hub.conn_nodes", nids, conn=cid)
        t.gauge_set("hub.connections", len(depths))
        t.gauge_set("hub.nodes", nodes_total)
        # _total suffix = cumulative monotonic counter exposed as a time
        # series (diff successive samples for a rate); un-suffixed hub
        # gauges (connections, nodes, send_queue_*) are instantaneous.
        # mcast copies lose their identity once queued, so no true
        # in-flight mcast count exists to report
        t.gauge_set("hub.backpressure_drops_total",
                    snap["backpressure_drops"])
        t.gauge_set("hub.mcast_frames_total", snap["mcast_frames"])
        t.gauge_set("hub.stripe_frames_total", snap["stripe_frames"])
        t.gauge_set("hub.node_rebinds_total", snap["node_rebinds"])
        t.gauge_set("hub.shm_conns", shm_conns)
        t.gauge_set("hub.shm_frames_total", snap["shm_frames"])
        t.gauge_set("hub.shm_bytes_total", snap["shm_bytes"])
        t.gauge_set("hub.shm_fallbacks_total", snap["shm_fallbacks"])
        # reactor-plane health: thread inventory (the O(1) claim as a
        # series) and watched-descriptor count; hub.loop_lag_s (the
        # per-batch histogram) is observed by the loop itself
        t.gauge_set("hub.threads", hub_threads)
        t.gauge_set("hub.open_fds", open_fds)
        t.event(
            "hub_stats", t_m=trace_ctx.now(),
            connections=sorted(depths),
            nodes=nodes_total,
            queue_frames={str(c): d[0] for c, d in depths.items()},
            queue_bytes={str(c): d[1] for c, d in depths.items()},
            conn_nodes={str(c): d[2] for c, d in depths.items()},
            **snap,
        )
        snap["nodes"] = nodes_total
        snap["connections"] = len(depths)
        return snap

    def stop(self):
        self._running = False
        if self._mode == "reactor":
            # wake the sleeping select; the loop's exit path tears every
            # conn down through _close_conn_r (pins flushed + released)
            self._tickle()
            self._loop_thread.join(timeout=10)
            if self._loop_thread.is_alive():
                # loop wedged (should not happen): at least release the
                # queued pins so a soak's leak accounting stays clean
                with self._lock:
                    states = (set(self._conns.values())
                              | set(self._range_conns))
                for st in states:
                    self._flush_conn_queues(st)
                    try:
                        st.sock.close()
                    except OSError:
                        pass
                try:
                    self._srv.close()
                except OSError:
                    pass
            return
        with self._lock:
            states = set(self._conns.values()) | set(self._range_conns)
        for st in states:
            try:
                st.sock.close()
            except OSError:
                pass
        for _ in self._senders:
            self._ready.put(None)
        # the workers exit via the sentinel WITHOUT visiting their
        # queues: release every still-queued payload pin here, or a
        # stop with in-flight laned frames leaks their slab/pool
        # references (the dead-receiver cleanup satellite's stop() leg)
        for st in states:
            self._flush_conn_queues(st)
        self._srv.close()


class TcpBackend(CommBackend):
    """One node's hub connection.

    ``auto_reconnect`` (attempts; 0 = off) makes ``run()`` survive a
    dropped hub connection: on EOF/reset the backend dials again,
    re-registers (the hub's identity guard swaps the live conn,
    ``TcpHub._serve_conn``), and resumes the read loop.  Frames routed
    while disconnected are lost — by design the round-deadline server
    (``fedavg_cross_device``) treats the node as a straggler for that
    round and it rejoins at the next sync.

    Striped multicast reassembly: a hub running striped fan-out delivers
    a broadcast as ``__hub__: mcast_stripe`` frames; this backend
    reassembles them per stripe-stream id into the original inner frame
    and delivers it like any whole frame (``_on_stripe``).  The buffer
    is bounded (streams + bytes) and a lost/corrupted stripe kills the
    WHOLE logical frame — counted, never a wedged reassembly: the round
    deadline treats the node as a straggler exactly as for a dropped
    whole frame.
    """

    # lock-discipline contract (fedlint): the reader thread owns
    # reassembly, but the chaos layer installs its stripe hook from the
    # construction thread — all reassembly state rides one lock
    _GUARDED_BY = {
        "_reasm": "_reasm_lock",
        "_reasm_bytes": "_reasm_lock",
        "_dead_sids": "_reasm_lock",
        "_stripe_fault_hook": "_reasm_lock",
    }

    # bounded reassembly: at most this many concurrent stripe streams
    # (two mcasts can interleave on one conn; more means lost finals
    # piling up) and this many buffered payload bytes (the hub refuses
    # to stripe frames over HALF this shared budget — see the module
    # constant — so a well-formed stream can never overflow it alone)
    _MAX_REASM_STREAMS = 8
    _MAX_REASM_BYTES = _MAX_REASM_BYTES

    def __init__(self, node_id: int, host: str, port: int,
                 timeout: float = 30.0, auto_reconnect: int = 0,
                 send_retries: int = 3, wire: int = 2,
                 lane: str = "tcp",
                 shm_data_bytes: int = DEFAULT_DATA_BYTES,
                 shm_slots: int = DEFAULT_SLOTS,
                 shm_min_bytes: int = DEFAULT_MIN_BYTES):
        super().__init__(node_id)
        self._host, self._port, self._timeout = host, port, timeout
        self.auto_reconnect = auto_reconnect
        # transport lane for payload bytes: "shm" creates a
        # shared-memory slab per dial and advertises it in the hello —
        # same-box hubs attach and both directions' payloads ride its
        # rings (headers stay on TCP); anything else (cross-host peer,
        # refused attach, full ring, oversized frame) falls back to
        # inline TCP per frame, counted.  "tcp" (default) is bitwise
        # the pre-lane transport.
        if lane not in ("tcp", "shm"):
            raise ValueError(f"unknown lane {lane!r} (tcp|shm)")
        self._lane_mode = lane
        self._shm_data = int(shm_data_bytes)
        self._shm_slots = int(shm_slots)
        self._shm_min = max(0, int(shm_min_bytes))
        self._lane: Optional[ShmLane] = None
        # wire generation for OUTBOUND frames: 2 = binary v2 frames
        # (Message.to_frame), 1 = legacy JSON lines (b64 arrays) — the
        # baseline arm of the compression measurement and the interop
        # test knob.  Inbound frames of either generation always decode.
        self.wire = int(wire)
        # bounded retry budget for send_message: a transient OSError
        # (hub restarting, conn mid-swap by the reconnect path) used to
        # be terminal for the SENDER even though the reader thread was
        # about to re-dial.  0 = fail fast (the pre-fault behavior).
        self.send_retries = max(0, int(send_retries))
        self._stopped = threading.Event()
        # the rebind path leaves its displaced socket/lane OPEN for the
        # hub to close (closing it ourselves would race the hub's
        # deferred registration of the new conn and turn the rebind
        # into a plain re-register); reaped at the next rebind or stop
        self._stale_conn = None  # (file, sock, lane) or None
        # serializes send_message against _dial's socket swap: without
        # it, a send between "socket connected" and "hello written"
        # lands BEFORE the registration line and the hub parses the
        # message frame as the hello (KeyError, conn dropped, frame lost)
        self._send_lock = make_lock("TcpBackend._send_lock")
        # striped-multicast reassembly (see class doc): sid -> entry
        self._reasm_lock = make_lock("TcpBackend._reasm_lock")
        self._reasm: Dict[int, dict] = {}
        self._reasm_bytes = 0
        self._dead_sids: deque = deque(maxlen=64)  # aborted stream ids
        self._stripe_fault_hook = None
        # latest hub conn_map reply ({cid: [node ids]}): written only by
        # the reader thread (atomic reference swap), read by the robust
        # aggregator's connection attribution — never mutated in place
        self._conn_map: Optional[dict] = None
        self._dial()

    def set_stripe_fault_hook(self, hook) -> None:
        """Install a per-stripe fault hook (chaos layer):
        ``hook(msg_type, sid, idx, chunk) -> chunk | None`` runs on
        every arriving stripe before crc verification — ``None``
        simulates a lost stripe, a mutated chunk a corrupted one (the
        crc then catches it).  The reassembly must degrade to a dropped
        logical frame either way."""
        with self._reasm_lock:
            self._stripe_fault_hook = hook

    def _hello_obj(self) -> dict:
        """Registration payload.  v1: one ``node_id``.  The muxed
        subclass overrides with the hello-v2 ``node_ids`` form
        (``comm/mux.py``); the hub accepts both on one port."""
        return {"node_id": self.node_id}

    def _hello_line(self, lane: Optional[ShmLane] = None) -> bytes:
        obj = self._hello_obj()
        if lane is not None:
            # shm capability: advertise the freshly created slab; the
            # hub attaches (same box) or ignores (cross-host / error)
            # and confirms in its ACK
            obj["shm"] = lane.describe()
        return (json.dumps(obj) + "\n").encode()

    def _dial(self, keep_stale: bool = False):
        with self._send_lock:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._timeout
            )
            _tune_socket(sock)
            # slab creation only AFTER the socket connected: the
            # startup/reconnect paths retry _dial in a loop, and a
            # pre-connect slab (2 rings, fully prefaulted) would leak
            # ~shm_data x2 of tmpfs per refused connection — the
            # cleanup below owns it from here on
            lane = None
            if self._lane_mode == "shm":
                try:
                    lane = ShmLane.create(self._shm_data, self._shm_slots)
                except Exception as e:
                    logging.warning(
                        "node %d: shm slab creation failed (%s: %s) — "
                        "dialing pure TCP", self.node_id,
                        type(e).__name__, e,
                    )
                    get_telemetry().inc("comm.shm_fallbacks",
                                        reason="create")
                    lane = None
            try:
                sock.sendall(self._hello_line(lane))
                f = sock.makefile("rb")
                # wait for the hub's registration ACK — guaranteed to be
                # the FIRST line on the conn (the hub ACKs before
                # registering, so no routed frame can precede or
                # interleave it); afterwards, any frame sent TO this
                # node can be delivered
                ack = f.readline()
                ack_obj = json.loads(ack) if ack else {}
                if ack_obj.get(HUB_KEY) != "ack":
                    raise ConnectionError(
                        f"node {self.node_id}: no hub ACK"
                    )
                if lane is not None and not ack_obj.get("shm"):
                    # hub could not (or would not) map the slab: stay
                    # pure TCP on this connection — the automatic
                    # cross-host / old-hub downgrade
                    lane.close(unlink=True)
                    lane = None
                    get_telemetry().inc("comm.shm_fallbacks",
                                        reason="attach")
                # handshake phase 2: the hub does NOT register this
                # conn until it reads ``ping_done`` (before that, its
                # reader thread can reply to clock-sync pings directly
                # with no sender-pool interleaving risk) — so EVERY
                # dialer must end the phase, even an untraced one that
                # sends no pings, or a receive-only node would never
                # register and every frame to it would drop
                if trace_ctx.enabled():
                    self._clock_sync(sock, f)  # ping burst + ping_done
                else:
                    sock.sendall(
                        (json.dumps({HUB_KEY: "ping_done"}) + "\n").encode()
                    )
            except BaseException:
                try:
                    sock.close()
                except OSError:
                    pass
                if lane is not None:
                    lane.close(unlink=True)
                raise
            sock.settimeout(None)
            if keep_stale:
                # rebind path: the OLD connection must stay registered
                # until the hub processes the new hello (that overlap
                # IS what makes it a counted rebind, and the hub then
                # closes the displaced conn itself) — park it for the
                # next rebind/stop to reap
                self._stale_conn = (getattr(self, "_file", None),
                                    getattr(self, "_sock", None),
                                    self._lane)
            else:
                # close the connection being replaced (reconnect path)
                # — without this every reconnect cycle leaks an fd (and
                # its slab: each dial advertises a FRESH lane, so the
                # old one is unlinked here)
                for stale in (getattr(self, "_file", None),
                              getattr(self, "_sock", None)):
                    if stale is not None:
                        try:
                            stale.close()
                        except OSError:
                            pass
                if self._lane is not None:
                    self._lane.close(unlink=True)
            self._sock, self._file = sock, f
            self._lane = lane

    def _clock_sync(self, sock: socket.socket, f, pings: int = 8) -> None:
        """NTP-style handshake ping burst (tracing on only): the hub is
        still in its pre-registration phase for this conn, so replies
        are written directly by its reader thread — the RTT is pure
        wire + scheduling, never sender-pool queue wait.  The min-RTT
        sample's midpoint estimates this process's monotonic-clock
        offset to the hub (``trace_ctx.estimate_offset``), recorded as
        a ``clock_sync`` telemetry event — what lets the timeline
        merger place every process on the hub's clock.  ``ping_done``
        ends the phase; only then does the hub register the conn."""
        samples = []
        for _ in range(pings):
            t0 = time.perf_counter()
            sock.sendall((json.dumps(
                {HUB_KEY: "ping", "t0": t0}
            ) + "\n").encode())
            line = f.readline()
            t1 = time.perf_counter()
            if not line:
                raise ConnectionError(
                    f"node {self.node_id}: hub closed during clock sync"
                )
            pong = json.loads(line)
            if pong.get(HUB_KEY) != "pong":
                raise ConnectionError(
                    f"node {self.node_id}: bad clock-sync reply {pong!r}"
                )
            samples.append((t0, pong.get("th"), t1))
        sock.sendall((json.dumps({HUB_KEY: "ping_done"}) + "\n").encode())
        offset, rtt = trace_ctx.estimate_offset(samples)
        trace_ctx.record_clock_sync(self.node_id, offset, rtt, len(samples))

    def _send_parts(self, parts: List, msg_type: str) -> None:
        """Bounded-retry vectored write of one complete frame.

        Exponential backoff + jitter: each attempt re-reads self._sock,
        so a reconnect (reader thread's _dial swapping the socket)
        between attempts is picked up.  A retry after a PARTIAL write
        hands the hub a garbled header line — the hub treats that as
        fatal for the CONNECTION (frames may carry binary payloads, so
        a garbled boundary cannot be resynchronized) and drops it; this
        node's reader then sees EOF and the auto_reconnect/round-
        deadline machinery covers the lost frame.  Never stream
        corruption, at worst one reconnect.  A backend killed by
        _kill_connection must not retry: the stream is desync-fatal by
        contract and callers expect OSError.  ``parts`` is immutable
        across attempts (and across broadcast receivers) — the frame is
        encoded exactly once however many times it is written.
        """
        if self._lane is not None and len(parts) > 1:
            body = parts[1:]
            nbody = sum(len(p) for p in body)
            # nbody > 0: a zero-byte body must ride inline — the
            # receiver's `binlen and sseq` gate never reads an empty
            # descriptor, so publishing one would desync the lane seq
            if (nbody and nbody >= self._shm_min
                    and self._send_parts_shm(parts, body, nbody,
                                             msg_type)):
                return
        delay = 0.05
        for attempt in range(self.send_retries + 1):
            try:
                with self._send_lock:
                    _sendall_parts(self._sock, parts)
                return
            except OSError:
                if self._stopped.is_set() or attempt >= self.send_retries:
                    raise
                get_telemetry().inc("comm.send_retries", msg_type=msg_type)
                time.sleep(delay * (1.0 + _retry_jitter(self.node_id,
                                                        attempt)))
                delay = min(delay * 2.0, 2.0)

    def _send_parts_shm(self, parts: List, body: List, nbody: int,
                        msg_type: str) -> bool:
        """Lane attempt for one frame: payload into the outbound ring,
        then the header line — with the doorbell key spliced in — over
        TCP.  Returns False (caller ships the whole frame inline) on
        any refusal: ring/descriptor-queue full, oversized payload, or
        a doorbell write error (the reserved ring space is simply never
        committed, so the rollback is free).  Counted either way."""
        tel = get_telemetry()
        with self._send_lock:
            lane = self._lane
            if lane is None:
                return False  # reconnect swapped the conn mid-call
            pending = lane.try_send(body, nbody)
            if pending is None:
                tel.inc("comm.shm_fallbacks", reason=lane.last_refusal)
                return False
            hdr = json.loads(parts[0])
            hdr[SHM_SEQ_KEY] = ShmLane.seq_of(pending)
            try:
                _sendall_parts(self._sock,
                               [(json.dumps(hdr) + "\n").encode()])
            except OSError:
                # a partial doorbell garbles the stream and the hub
                # drops the conn — same contract as any partial write;
                # the caller's retry path covers the frame
                tel.inc("comm.shm_fallbacks", reason="send_error")
                return False
            lane.commit(pending)
        tel.inc("comm.shm_frames", msg_type=msg_type)
        tel.inc("comm.shm_bytes", nbody, msg_type=msg_type)
        return True

    def send_message(self, msg: Message) -> None:
        self._send_message_as(msg, self.node_id)

    def _send_message_as(self, msg: Message, origin: int) -> None:
        """One frame onto the shared socket, trace-stamped as coming
        from ``origin`` — the node_id for a plain backend, the VIRTUAL
        node id when a muxed connection sends on a virtual client's
        behalf (``comm/mux.py``), so per-virtual-node hop chains stay
        distinguishable over one physical conn.

        v2: header line + raw buffer views (to_frame_parts, memoized
        on the message); v1: one JSON line (newlines escape inside
        JSON strings) — either way ONE complete frame, written
        atomically (vectored) under the send lock."""
        t0 = time.perf_counter()
        trace_ctx.ensure(msg, origin)
        if self.wire >= 2:
            # restamp_parts re-encodes ONLY the header line around the
            # memoized encoding (payload views shared by identity) — a
            # no-op returning the memoized list when untraced
            parts = trace_ctx.restamp_parts(
                msg, msg.to_frame_parts(), origin, "send"
            )
        else:
            trace_ctx.stamp_msg(msg, origin, "send")
            parts = [(msg.to_json() + "\n").encode()]
        self._send_parts(parts, msg.type)
        # exact wire bytes; latency covers serialize + socket write
        # (including any backoff — a retried send IS that slow)
        self._record_send(msg, sum(len(p) for p in parts),
                          time.perf_counter() - t0)

    def send_multicast(self, msg: Message, receivers) -> None:
        """Native hub fan-out: ONE ``__hub__: mcast`` control frame
        wrapping the encoded message (header line + raw buffers) plus a
        receiver list.  The hub enqueues the same immutable payload to
        every receiver, so the server→hub broadcast leg is O(model) per
        round instead of O(receivers · model) — the counter delta
        ``tools/federation_latency_run.py`` measures."""
        receivers = [int(r) for r in receivers]
        if not receivers:
            return
        if self.wire < 2:
            # v1 frames are legacy JSON lines with per-receiver
            # envelopes — keep the unicast loop (fallback matrix arm)
            super().send_multicast(msg, receivers)
            return
        t0 = time.perf_counter()
        trace_ctx.ensure(msg, self.node_id)
        inner = msg.to_frame_parts()  # encode ONCE for the whole cohort
        traced = trace_ctx.TRACE_KEY in msg.params
        if traced:
            # one 'send' stamp shared by the whole cohort (the frame IS
            # one object); per-copy divergence begins at the hub's
            # per-receiver hub_out restamp
            inner = trace_ctx.restamp_parts(msg, inner, self.node_id, "send")
        head = (json.dumps({
            HUB_KEY: "mcast",
            "receivers": receivers,
            "msg_type": msg.type,
            # binlen AFTER the restamp: the inner header line grew
            FRAME_BINLEN_KEY: sum(len(p) for p in inner),
            **({trace_ctx.TRACE_KEY: True} if traced else {}),
        }) + "\n").encode()
        parts = [head, *inner]
        self._send_parts(parts, msg.type)
        t = get_telemetry()
        t.inc("comm.mcast_sends", msg_type=msg.type)
        t.inc("comm.mcast_receivers", len(receivers), msg_type=msg.type)
        # ONE wire frame however many receivers — comm.sent_bytes for a
        # broadcast now counts the payload once
        self._record_send(msg, sum(len(p) for p in parts),
                          time.perf_counter() - t0)

    def request_conn_map(self) -> None:
        """Ask the hub for its node→connection grouping (replied
        asynchronously; the reader loop stores the latest map, read via
        ``conn_map``).  Best-effort: a failed request just leaves the
        previous map in place — connection caps then attribute against
        slightly stale grouping, which the rebind counters make
        visible."""
        line = (json.dumps({HUB_KEY: "conn_map"}) + "\n").encode()
        try:
            with self._send_lock:
                _sendall_parts(self._sock, [line])
        except OSError:
            logging.warning("node %d: conn_map request failed",
                            self.node_id)

    def conn_map(self) -> Optional[dict]:
        """Latest hub ``{cid: [node ids]}`` reply (None before the
        first one).  The returned object is never mutated — callers may
        identity-cache it."""
        return self._conn_map

    def _sync_hub_replies(self, kind: str, timeout: float, op: str):
        """Pre-``run()`` synchronous hub-RPC loop — the ONE
        implementation behind ``await_peers`` and ``fetch_conn_map``:
        send a ``{__hub__: kind}`` request, read frames off the shared
        socket, and yield each matching reply (the caller decides
        whether to return or poll again; resuming the generator
        re-sends the request).

        Frame discipline while waiting (the part that must not drift
        between callers): a read timing out MID-frame kills the
        connection (the buffered reader discarded partial bytes —
        frame alignment can't be trusted) and raises; other ``__hub__``
        frames are skipped (stale idempotent replies); an ORDINARY
        message frame is a genuine delivery — its binary payload is
        read and the message handed to the observers, NOT dropped (the
        stats plane's early digest frames queue here while the startup
        barrier waits on slow-importing clients; eating them would
        silently lose those intervals from the SLO rollup).  The
        generator raises ``TimeoutError`` when the budget runs dry."""
        import time as _time

        deadline = _time.monotonic() + timeout
        request = (json.dumps({HUB_KEY: kind}) + "\n").encode()
        try:
            while (remaining := deadline - _time.monotonic()) > 0:
                self._sock.settimeout(max(remaining, 0.05))
                try:
                    self._sock.sendall(request)
                except TimeoutError:
                    # a timed-out sendall may have written PART of the
                    # request line — the write side is no longer
                    # frame-aligned, so the socket must not be reused
                    # (same contract as the mid-frame read below)
                    self._kill_connection()
                    raise TimeoutError(
                        f"node {self.node_id}: hub write timed out mid-"
                        f"frame during {op}; connection closed"
                    ) from None
                except OSError as e:
                    raise ConnectionError(
                        f"node {self.node_id}: hub connection failed "
                        f"during {op}: {e}"
                    ) from e
                matched = False
                while not matched and \
                        (remaining := deadline - _time.monotonic()) > 0:
                    self._sock.settimeout(max(remaining, 0.05))
                    try:
                        line = self._file.readline()
                        frame = json.loads(line) if line else None
                        # a v2 frame announces its binary payload —
                        # consume it HERE or the next readline would
                        # parse payload bytes as headers (shm doorbells
                        # consume their slab descriptor the same way;
                        # the rare pre-run delivery takes the one-copy
                        # read — these are small early frames)
                        binlen = (frame.get(FRAME_BINLEN_KEY)
                                  if isinstance(frame, dict) else None)
                        sseq = (frame.pop(SHM_SEQ_KEY, None)
                                if isinstance(frame, dict) else None)
                        if binlen and sseq is not None:
                            if self._lane is None:
                                raise ShmLaneError("no lane attached")
                            payload = self._lane.read_copy(sseq, binlen)
                        else:
                            payload = (self._file.read(binlen)
                                       if binlen else b"")
                            if binlen and len(payload) < binlen:
                                line = b""  # torn frame == EOF
                    except TimeoutError:
                        # mid-frame timeout: the stream can no longer
                        # be trusted frame-aligned (ADVICE r2) — kill
                        # it so reuse fails loudly instead of corrupting
                        self._kill_connection()
                        raise TimeoutError(
                            f"node {self.node_id}: hub read timed out "
                            f"mid-frame during {op}; connection closed "
                            "(a resumed read could split a frame)"
                        ) from None
                    except OSError as e:
                        raise ConnectionError(
                            f"node {self.node_id}: hub connection failed "
                            f"during {op}: {e}"
                        ) from e
                    except ShmLaneError as e:
                        # lane bookkeeping untrustworthy: same contract
                        # as a mid-frame timeout — the stream dies
                        self._kill_connection()
                        raise ConnectionError(
                            f"node {self.node_id}: shm lane error during "
                            f"{op}: {e}"
                        ) from e
                    if not line:
                        raise ConnectionError(
                            f"node {self.node_id}: hub closed during {op}"
                        )
                    if frame.get(HUB_KEY) == kind:
                        matched = True
                        yield frame
                    elif HUB_KEY in frame:
                        continue  # stale idempotent reply of another kind
                    else:
                        # genuine early delivery (e.g. a digest frame):
                        # hand it to the observers on this thread — a
                        # handler error must not kill the RPC
                        try:
                            self._notify(Message.from_frame(frame, payload),
                                         nbytes=len(line) + len(payload))
                        except Exception:
                            logging.exception(
                                "node %d: early frame delivery failed "
                                "during %s", self.node_id, op,
                            )
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass  # _kill_connection already closed it

    def fetch_conn_map(self, timeout: float = 10.0) -> dict:
        """SYNCHRONOUS conn_map fetch — pre-``run()`` only (the
        ``_sync_hub_replies`` contract).  The robust aggregator calls
        this before the first broadcast so connection caps are armed
        from round 0 instead of racing the async reply; later topology
        changes ride the per-round ``request_conn_map`` refresh.
        Raises on timeout — a cap the operator asked for must fail
        loudly, never silently degrade to uncapped."""
        for reply in self._sync_hub_replies("conn_map", timeout,
                                            "fetch_conn_map"):
            self._conn_map = {
                int(c): [int(n) for n in nodes]
                for c, nodes in (reply.get("conns") or {}).items()
            }
            return self._conn_map
        raise TimeoutError(
            f"node {self.node_id}: no conn_map reply within {timeout}s"
        )

    def drop_connection(self) -> None:
        """Fault injection: sever the hub connection WITHOUT stopping
        the backend — ``run()`` sees EOF and, with ``auto_reconnect``,
        re-dials and re-registers exactly as for a real network drop."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _reap_stale_conn(self) -> None:
        stale = self._stale_conn
        if stale is None:
            return
        self._stale_conn = None
        f, sock, lane = stale
        for closer in (f, sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:
                    pass
        if lane is not None:
            lane.close(unlink=True)  # hub already unlinked: no-op

    def rebind_connection(self) -> None:
        """Churn injection: dial a FRESH connection (new hello, new
        slab) while the old one is STILL registered — the silent-death
        shape, where the hub learns about the replacement only from
        the new hello and counts a REBIND for every id (the
        ``hub.node_rebinds`` policy).  The displaced socket is left
        open deliberately: the hub's registration of the new conn is
        deferred to its ``ping_done``, so closing the old one here
        would race that registration and sometimes demote the rebind
        to a plain re-register.  The HUB closes the displaced conn
        once every id moved; we reap the dead fd/slab at the next
        rebind or stop.  The reader resumes on the swapped stream
        without spending a reconnect retry."""
        self._reap_stale_conn()
        # a failed re-dial needs no rollback: the reader tells a
        # displaced socket's EOF from a genuine one by comparing the
        # stream it was blocked on against the live self._file —
        # if the dial never swapped it, ordinary drop semantics (the
        # auto_reconnect budget) take over on their own
        self._dial(keep_stale=True)

    def await_peers(self, ids, timeout: float = 60.0) -> None:
        """Block until every node id in ``ids`` is registered at the hub.

        MUST be called before ``run()`` (it reads replies off the
        shared socket — the ``_sync_hub_replies`` contract; ordinary
        frames arriving early are DELIVERED to the observers, not
        dropped).  This is the startup barrier: the hub drops frames to
        unregistered receivers, so a coordinator that broadcasts before
        its cohort registered would hang the federation.  A budget
        spent between full reads leaves the stream frame-aligned and
        the backend reusable; only a mid-frame timeout kills the
        connection.
        """
        import time as _time

        want = set(int(i) for i in ids)
        for reply in self._sync_hub_replies("peers", timeout,
                                            "await_peers"):
            have = set(reply.get("ids", []))
            missing = want - have
            if missing:
                # range-claim cohorts (edge uplinks) are reported as
                # [lo, hi] spans — check the remainder against them
                # without materializing the span
                for lo, hi in reply.get("ranges", ()):
                    missing = {m for m in missing
                               if not (lo <= m <= hi)}
                    if not missing:
                        break
            if not missing:
                return
            _time.sleep(0.05)  # poll: resuming re-sends the request
        raise TimeoutError(
            f"node {self.node_id}: peers {sorted(want)} not all registered "
            f"within {timeout}s"
        )

    def _kill_connection(self) -> None:
        """Mark the backend unusable and close the socket (desync-fatal
        paths): later send_message/run calls get OSError immediately."""
        self._stopped.set()
        # shutdown() disables the connection immediately even though the
        # makefile() reader still holds a reference to the fd (a bare
        # close() is deferred by that refcount and sends would still work)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for closer in (self._file.close, self._sock.close):
            try:
                closer()
            except OSError:
                pass
        if self._lane is not None:
            self._lane.close(unlink=True)

    def run(self) -> None:
        retries = self.auto_reconnect
        lost_at = None  # perf_counter stamp of the FIRST EOF of an outage
        while not self._stopped.is_set():
            frame = None
            payload = b""
            region = None
            try:
                # pin the stream for this whole iteration: a rebind
                # (mux flush hook, churn soak) swaps self._file while
                # this thread may be blocked right here, and the EOF
                # check below tells the two apart by identity
                f = self._file
                line = f.readline()
                if line:
                    try:
                        frame = json.loads(line)
                    except json.JSONDecodeError:
                        # same contract as the hub: a garbled header on
                        # a stream that may carry binary payloads means
                        # the frame boundary is lost — treat as EOF so
                        # the reconnect path re-dials a frame-aligned
                        # stream instead of parsing payload bytes as
                        # headers
                        logging.warning(
                            "node %d: malformed frame header — "
                            "dropping connection", self.node_id,
                        )
                        line, frame = b"", None
                    binlen = (frame.get(FRAME_BINLEN_KEY)
                              if isinstance(frame, dict) else None)
                    sseq = (frame.pop(SHM_SEQ_KEY, None)
                            if isinstance(frame, dict) else None)
                    if binlen and sseq is not None:
                        # shm doorbell: the payload lives in the slab —
                        # map it zero-copy; any descriptor skew means
                        # the lane's bookkeeping is untrustworthy
                        # (torn writer) and the CONNECTION dies, same
                        # as a garbled stream
                        try:
                            if self._lane is None:
                                raise ShmLaneError("no lane attached")
                            region = self._lane.read(sseq, binlen)
                            payload = region.view
                        except ShmLaneError as e:
                            logging.warning(
                                "node %d: shm lane error (%s) — "
                                "dropping connection", self.node_id, e,
                            )
                            get_telemetry().inc("comm.shm_fallbacks",
                                                reason="torn")
                            line, frame = b"", None
                    elif binlen:
                        payload = f.read(binlen)
                        if len(payload) < binlen:
                            # torn frame: the hub died mid-payload — the
                            # stream can't be trusted, treat as EOF (the
                            # reconnect path re-dials a fresh one)
                            line, frame = b"", None
            except OSError:
                line = b""
            if not line:
                if f is not self._file:
                    # rebind_connection swapped in a live, registered
                    # stream while this thread was blocked on the OLD
                    # socket: this EOF is the displaced conn dying —
                    # keep reading, no retry spent.  Identity (not a
                    # flag): a rebind issued ON this thread (the mux
                    # flush hook) never blocks here, so a flag set for
                    # it would mis-absorb the NEXT genuine EOF.
                    continue
                if self._stopped.is_set() or retries <= 0:
                    return
                retries -= 1
                import time as _time

                if lost_at is None:
                    lost_at = _time.perf_counter()
                    # first EOF of an outage (not the retry loop): the
                    # dialer's black box captures its own view of the
                    # death — hub restarts attribute from BOTH sides
                    flight.note("events", "conn_death",
                                node=self.node_id)
                    flight.trigger(
                        "conn_death",
                        reason=f"node {self.node_id}: hub connection "
                               f"lost ({retries} retries left)",
                    )
                _time.sleep(0.2)
                try:
                    self._dial()  # re-register; hub swaps the live conn
                    # recovery span: total time this node was off the hub
                    # (first EOF -> re-registered), the number a chaos
                    # soak reads to bound reconnect impact
                    t = get_telemetry()
                    t.inc("comm.reconnects")
                    t.observe("span.reconnect_s",
                              _time.perf_counter() - lost_at)
                    lost_at = None
                    logging.warning(
                        "node %d: hub connection lost — reconnected "
                        "(%d retries left)", self.node_id, retries,
                    )
                    continue
                except (OSError, ConnectionError, json.JSONDecodeError):
                    # JSONDecodeError: a hub that died mid-ACK leaves a
                    # partial line — that's a failed dial, not a reason
                    # to kill the reader thread with retries remaining
                    logging.exception(
                        "node %d: reconnect failed", self.node_id
                    )
                    continue  # retry until the budget runs out
            try:
                keep_going = self._dispatch_frame(
                    frame, payload, len(line) + len(payload), region
                )
            finally:
                if region is not None:
                    # the reader's reference: consumers that handed the
                    # payload to another thread pinned their own
                    # (Message.pin_payload) — the ring reclaims the
                    # bytes once the LAST reference drops
                    region.release()
            if not keep_going:
                return

    def _dispatch_frame(self, frame: dict, payload, nbytes: int,
                        region=None) -> bool:
        """Route one complete inbound frame (payload possibly a slab
        memoryview — ``region`` then owns its reclamation).  Returns
        False only for the stop sentinel."""
        if frame.get(HUB_KEY) == "stop":
            return False
        if frame.get(HUB_KEY) == MCAST_STRIPE_KIND:
            if region is not None:
                # stripe chunks are BUFFERED across frames until the
                # stream completes — unbounded retention, so this one
                # consumer materializes (one copy) instead of pinning
                # slab space for a whole logical frame
                payload = bytes(payload)
            try:
                self._on_stripe(frame, payload, nbytes=nbytes)
            except Exception:
                # reassembly bugs must degrade to a dropped logical
                # frame (straggler semantics), never a dead reader
                logging.exception("node %d: stripe reassembly failed",
                                  self.node_id)
            return True
        if frame.get(HUB_KEY) == MUX_KIND:
            try:
                self._on_mux_frame(frame, payload, nbytes=nbytes,
                                   region=region)
            except Exception:
                # a demux bug must degrade to a dropped broadcast
                # copy, never a dead reader
                logging.exception("node %d: mux demux failed",
                                  self.node_id)
            return True
        if frame.get(HUB_KEY) == "conn_map":
            # hub introspection reply (request_conn_map): atomic
            # reference swap — readers (the robust aggregator's
            # connection attribution) always see a complete map
            try:
                self._conn_map = {
                    int(c): [int(n) for n in nodes]
                    for c, nodes in (frame.get("conns") or {}).items()
                }
            except (TypeError, ValueError):
                logging.warning("node %d: malformed conn_map reply",
                                self.node_id)
            return True
        try:
            # exact wire bytes: header line + binary payload
            msg = Message.from_frame(frame, payload)
            msg._region = region
            self._notify(msg, nbytes=nbytes)
        except Exception:
            # a handler error must not kill the reader thread — the
            # node would silently stop receiving and the federation
            # would hang with no attributable cause
            logging.exception("node %d: message handler failed",
                              self.node_id)
        return True

    def _on_stripe(self, frame: dict, chunk: bytes, nbytes: int) -> None:
        """One ``mcast_stripe`` continuation frame off the wire.

        Stripes of one logical frame arrive in order (per-conn FIFO +
        single drainer), so reassembly is append-only per stream id; an
        index gap means an upstream drop (hub backpressure, chaos) and
        kills the whole stream.  crc32 mismatch = corrupted stripe,
        same fate.  Completion hands the concatenated inner frame to
        ``_notify`` exactly like a whole frame — with a backdated
        ``reasm`` hop stamp (first-stripe arrival) so the timeline can
        split fan-out delivery from reassembly wait.
        """
        sid, idx = frame.get("sid"), frame.get("i")
        total = frame.get("n")
        mt = frame.get("msg_type") or "?"
        t_now = time.perf_counter()
        with self._reasm_lock:
            hook = self._stripe_fault_hook
        if hook is not None:
            chunk = hook(mt, sid, idx, chunk)
            if chunk is None:
                return  # injected loss: the gap aborts the stream later
        tel = get_telemetry()
        tel.inc("comm.stripe_frames", msg_type=mt)
        abort_reason = None
        done = None
        with self._reasm_lock:
            if sid in self._dead_sids:
                return  # already-aborted stream: ignore the tail
            ent = self._reasm.get(sid)
            if ent is None:
                if idx != 0:
                    abort_reason = "gap"  # head stripe was lost upstream
                elif len(self._reasm) >= self._MAX_REASM_STREAMS:
                    # evict the OLDEST stream (its final stripe is
                    # presumed lost) to admit the new one
                    oldest = None
                    for s, e in self._reasm.items():
                        if oldest is None or e["t0"] < oldest_t0:
                            oldest, oldest_t0 = s, e["t0"]
                    dead = self._reasm.pop(oldest)
                    self._reasm_bytes -= dead["blen"]
                    self._dead_sids.append(oldest)
                    tel.inc("comm.stripe_aborts", reason="stale",
                            msg_type=dead["mt"])
                ent = None
            if abort_reason is None:
                if ent is None:
                    # ``nodes`` rides stripe 0's outer header on a
                    # muxed connection: the co-located virtual node ids
                    # the reassembled frame fans out to locally
                    ent = {"chunks": [], "next": 0, "total": total,
                           "t0": t_now, "nbytes": 0, "blen": 0, "mt": mt,
                           "nodes": frame.get("nodes"),
                           "range": frame.get("range")}
                    self._reasm[sid] = ent
                if idx != ent["next"] or total != ent["total"]:
                    abort_reason = "gap"
                elif zlib.crc32(chunk) != frame.get("crc"):
                    abort_reason = "crc"
                else:
                    while (self._reasm_bytes + len(chunk)
                            > self._MAX_REASM_BYTES
                            and len(self._reasm) > 1):
                        # the budget is hogged by OLDER partial streams
                        # whose finals are presumed lost (e.g. a hub
                        # reconnect killed their tails mid-broadcast):
                        # evict oldest-first so stale bytes can never
                        # permanently starve every later broadcast —
                        # the live stream wins
                        oldest = None
                        for s, e in self._reasm.items():
                            if s != sid and (oldest is None
                                             or e["t0"] < oldest_t0):
                                oldest, oldest_t0 = s, e["t0"]
                        if oldest is None:
                            break
                        dead = self._reasm.pop(oldest)
                        self._reasm_bytes -= dead["blen"]
                        self._dead_sids.append(oldest)
                        tel.inc("comm.stripe_aborts", reason="stale",
                                msg_type=dead["mt"])
                    if (self._reasm_bytes + len(chunk)
                            > self._MAX_REASM_BYTES):
                        abort_reason = "overflow"
                if abort_reason is None:
                    ent["chunks"].append(chunk)
                    ent["next"] += 1
                    ent["nbytes"] += nbytes
                    ent["blen"] += len(chunk)
                    self._reasm_bytes += len(chunk)
                    if ent["next"] == ent["total"]:
                        del self._reasm[sid]
                        self._reasm_bytes -= ent["blen"]
                        done = ent
            if abort_reason is not None:
                dead = self._reasm.pop(sid, None)
                if dead is not None:
                    self._reasm_bytes -= dead["blen"]
                self._dead_sids.append(sid)
        if abort_reason is not None:
            tel.inc("comm.stripe_aborts", reason=abort_reason, msg_type=mt)
            logging.warning(
                "node %d: striped frame sid=%s (%s) aborted at stripe "
                "%s: %s — logical frame dropped", self.node_id, sid, mt,
                idx, abort_reason,
            )
            return
        if done is None:
            return
        try:
            msg = Message.from_frame_bytes(b"".join(done["chunks"]))
        except Exception:
            tel.inc("comm.stripe_aborts", reason="undecodable", msg_type=mt)
            logging.warning(
                "node %d: reassembled frame sid=%s (%s) undecodable — "
                "dropped", self.node_id, sid, mt,
            )
            return
        tel.inc("comm.stripe_reassemblies", msg_type=mt)
        self._deliver_reassembled(msg, done)

    def _deliver_reassembled(self, msg: Message, ent: dict) -> None:
        """Hand a reassembled logical frame to the observers.  The
        muxed backend overrides to fan out per co-located virtual node
        (``ent['nodes']``); here the backdated ``reasm`` hop marks
        first-stripe arrival — recv - reasm is the reassembly/streaming
        wait on this node."""
        trace_ctx.stamp_msg(msg, self.node_id, "reasm", t=ent["t0"])
        self._notify(msg, nbytes=ent["nbytes"])

    def _on_mux_frame(self, frame: dict, payload, nbytes: int,
                      region=None) -> None:
        """A ``__hub__: mux`` wrapped broadcast copy.  Only muxed
        backends (hello v2) are ever addressed with these; a plain
        backend receiving one is a hub bug — drop it loudly (straggler
        semantics: the round deadline covers the lost sync)."""
        logging.warning(
            "node %d: unexpected mux-wrapped frame (%s) on a non-muxed "
            "connection — dropped", self.node_id, frame.get("msg_type"),
        )

    def run_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.sendall((json.dumps(_SENTINEL) + "\n").encode())
            self._sock.close()
        except OSError:
            pass
        if self._lane is not None:
            # creator-side detach + unlink; a region still pinned by a
            # late consumer keeps its mapping alive until released (the
            # segment name just disappears, which is the point)
            self._lane.close(unlink=True)
        self._reap_stale_conn()
