"""Shared-memory ring lanes — zero-copy payloads for same-box TCP peers.

FEDSCALE_r10's 256-virtual-on-one-muxer point pushes 269 MB of uploads
per round through loopback TCP: every multi-MB frame crosses the kernel
twice (user→kernel on send, kernel→user on recv) plus the loopback
stack's queueing.  For peers that share a box — the muxer topology's
normal case — those copies buy nothing.  This module moves the PAYLOAD
bytes of a hub connection through a ``multiprocessing.shared_memory``
slab instead, while the TCP stream keeps carrying every frame HEADER:

- the sender copies the payload into a per-direction SPSC byte ring
  inside the slab, publishes a crc-guarded frame descriptor, and sends
  the ordinary JSON header line over TCP with one extra reserved key
  (``__shmseq__``, ``comm/message.py``) naming the descriptor;
- the receiver, on reading that header, maps the payload as a
  **memoryview into the slab** — no intermediate ``bytes`` — and feeds
  it to the existing frame decode (``Message.from_frame``) exactly as
  if it had been read off the socket.

Keeping the header (and therefore frame ORDER, control frames, and the
doorbell that wakes the blocking reader) on TCP is what makes the lane
compose with everything built on the stream: per-connection FIFO is the
socket's, chaos/trace/reconnect machinery sees ordinary frames, and
**fallback is per-frame** — a full ring, an oversized payload, or a
refused attach simply ships that payload inline (counted
``comm.shm_fallbacks{reason=}``), never an error.

Failure containment:

- **peer death** looks exactly like a dropped connection: doorbells
  stop (TCP EOF) and the conn dies through the existing cleanup; the
  slab stays valid while mapped (POSIX shm survives unlink), so no
  read can ever fault on a dead writer's memory;
- **torn writer** (peer killed mid-descriptor, or doorbell/descriptor
  skew): the descriptor's crc/seq/length validation fails and the read
  raises ``ShmLaneError`` — CONNECTION-fatal for the reader (the lane's
  bookkeeping can no longer be trusted), never a partial frame
  delivered;
- **reclamation** is explicit: reads hand out refcounted
  ``ShmRegion``s; the byte ring's read cursor only advances over fully
  released frames, so a consumer that pins a payload past the delivery
  scope (the server's decode pool, a chaos-delayed copy) blocks reuse
  of exactly its own bytes and nothing else.

Memory-ordering note: descriptor and payload writes are plain stores
into a shared mmap; the doorbell crosses a TCP syscall boundary (a full
kernel round trip) before the reader ever looks, which on every
platform this targets (Linux, CPython) is a far stronger ordering
barrier than the release/acquire pair a native SPSC ring would use.
"""

from __future__ import annotations

import logging
import struct
import zlib
from typing import Dict, List, Optional

from fedml_tpu.analysis.locks import make_lock
from fedml_tpu.obs import flight

_MAGIC = b"FEDSHM13"
_VERSION = 1
_HDR_SIZE = 64          # slab header
_RING_HDR_SIZE = 64     # per-direction cursor block
_DESC_FMT = "<QQQQI4x"  # seq, off, ln, end_total, crc, pad
_DESC_SIZE = struct.calcsize(_DESC_FMT)
_DESC_BODY = struct.Struct("<QQQQ")  # the crc-covered prefix

DEFAULT_DATA_BYTES = 64 << 20
DEFAULT_SLOTS = 256
# payloads below this ride inline TCP by default: the descriptor +
# doorbell overhead beats the copy savings only past ~1 KiB (policy,
# not a fallback — not counted)
DEFAULT_MIN_BYTES = 1024


class ShmLaneError(RuntimeError):
    """Lane bookkeeping can no longer be trusted (torn descriptor,
    doorbell/descriptor skew, bad geometry): connection-fatal by
    contract — the caller must treat it like a garbled stream."""


# names created by THIS process: an in-process attach (hub and backend
# in one test process) must not unregister the creator's tracker entry
_LOCAL_NAMES: set = set()


def _attach_untracked(name: str):
    """Attach to an existing segment WITHOUT the resource tracker
    claiming it: on 3.8-3.12 an attaching process registers the
    segment and unlinks it at exit, destroying it under the creator
    (bpo-38119).  The creator keeps sole unlink responsibility."""
    from multiprocessing import shared_memory

    seg = shared_memory.SharedMemory(name=name)
    if name not in _LOCAL_NAMES:
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:
            pass  # tracker layouts vary; worst case is a benign warning
    return seg


class ShmRegion:
    """One read frame's refcounted window into the slab.  Created with
    one reference (the reader's delivery scope); consumers that hand
    the payload to another thread ``retain()`` first and ``release()``
    when done — the ring reclaims the bytes only at zero."""

    __slots__ = ("_lane", "seq", "view", "_refs")

    def __init__(self, lane: "ShmLane", seq: int, view: memoryview):
        self._lane = lane
        self.seq = seq
        self.view = view
        self._refs = 1

    def retain(self) -> None:
        with self._lane._rlock:
            self._refs += 1

    def release(self) -> None:
        lane = self._lane
        with lane._rlock:
            self._refs -= 1
            if self._refs > 0:
                return
        try:
            self.view.release()
        except BufferError:
            # numpy views still alive: the memoryview object survives
            # via their references; reclamation is still safe because
            # every consumer that could outlive this scope holds a
            # retain() of its own — a released region's arrays are
            # dead by contract
            pass
        lane._release_seq(self.seq)


class _Ring:
    """One direction's SPSC frame ring: a descriptor array + a byte
    ring, each end owning its own cursor pair in the ring header.

    The WRITER keeps its cursors (wseq/wtotal) locally and mirrors them
    into the header for introspection; it reads the reader's released
    cursors to compute free space.  The READER validates descriptors
    against its own expected sequence — the doorbell stream on TCP is
    FIFO, so any skew means a torn/rolled-back writer."""

    def __init__(self, buf: memoryview, base: int, nslots: int,
                 data_bytes: int):
        self._buf = buf
        self._hdr = base
        self._desc = base + _RING_HDR_SIZE
        self._data = self._desc + nslots * _DESC_SIZE
        self.nslots = nslots
        self.data_bytes = data_bytes
        # writer-local state
        self._wseq = 0
        self._wtotal = 0
        # reader-local state
        self._expected = 0
        self._next_release = 0
        self._released: Dict[int, int] = {}  # seq -> end_total

    # -- cursor block: [0:8) rd_seq, [8:16) rd_total (reader-owned);
    #    [16:24) wr_seq, [24:32) wr_total (writer-owned, informational)
    def _read_u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._buf, self._hdr + off)[0]

    def _write_u64(self, off: int, value: int) -> None:
        struct.pack_into("<Q", self._buf, self._hdr + off, value)

    # -- writer side --------------------------------------------------------
    def try_write(self, parts, nbytes: int):
        """Reserve + copy one frame; returns ``(seq, end_total)`` or a
        refusal-reason string.  NOT committed yet: the caller sends the
        doorbell first and calls ``commit`` only on success, so a
        failed doorbell leaves the ring exactly as before (the next
        frame reuses the sequence number and the descriptor slot)."""
        if nbytes > self.data_bytes:
            return "too_big"
        rd_seq = self._read_u64(0)
        if self._wseq - rd_seq >= self.nslots:
            return "desc_full"
        rd_total = self._read_u64(8)
        head = self._wtotal % self.data_bytes
        skip = (self.data_bytes - head
                if head + nbytes > self.data_bytes else 0)
        if nbytes + skip > self.data_bytes - (self._wtotal - rd_total):
            return "ring_full"
        off = 0 if skip else head
        pos = self._data + off
        for p in parts:
            v = p if isinstance(p, memoryview) else memoryview(p)
            if v.format != "B" or v.ndim != 1:
                v = v.cast("B")
            self._buf[pos:pos + len(v)] = v
            pos += len(v)
        seq = self._wseq
        end_total = self._wtotal + skip + nbytes
        body = _DESC_BODY.pack(seq, off, nbytes, end_total)
        struct.pack_into(
            _DESC_FMT, self._buf,
            self._desc + (seq % self.nslots) * _DESC_SIZE,
            seq, off, nbytes, end_total, zlib.crc32(body),
        )
        return (seq, end_total)

    def commit(self, pending) -> None:
        seq, end_total = pending
        self._wseq = seq + 1
        self._wtotal = end_total
        self._write_u64(16, self._wseq)
        self._write_u64(24, self._wtotal)

    # -- reader side --------------------------------------------------------
    def read(self, seq: int, nbytes: int) -> memoryview:
        if seq != self._expected:
            raise ShmLaneError(
                f"doorbell/descriptor skew: expected seq {self._expected}, "
                f"got {seq}"
            )
        d_seq, off, ln, end_total, crc = struct.unpack_from(
            _DESC_FMT, self._buf,
            self._desc + (seq % self.nslots) * _DESC_SIZE,
        )
        body = _DESC_BODY.pack(d_seq, off, ln, end_total)
        if zlib.crc32(body) != crc or d_seq != seq or ln != nbytes:
            raise ShmLaneError(
                f"torn descriptor at seq {seq}: "
                f"(seq={d_seq}, ln={ln}, crc_ok={zlib.crc32(body) == crc}) "
                f"vs announced {nbytes} bytes"
            )
        self._expected = seq + 1
        start = self._data + off
        return self._buf[start:start + ln], end_total

    def release(self, seq: int, end_total: int) -> None:
        """Caller holds the lane's release lock."""
        self._released[seq] = end_total
        while self._next_release in self._released:
            total = self._released.pop(self._next_release)
            self._next_release += 1
            self._write_u64(0, self._next_release)
            self._write_u64(8, total)


class ShmLane:
    """One connection's slab: two ``_Ring``s (dialer→acceptor at
    direction 0, acceptor→dialer at 1).  The DIALER creates (and later
    unlinks) the segment and advertises it in the hello frame; the
    acceptor attaches by name — cross-host peers simply fail the attach
    and the connection stays pure TCP."""

    # release() runs on whatever thread drops the last reference (the
    # reader thread, a decode-pool worker, a chaos timer): the
    # released-frame map and region refcounts all ride one lock
    _GUARDED_BY = {
        "_released": "_rlock",
        "_outstanding": "_rlock",
    }

    def __init__(self, seg, *, creator: bool, nslots: int,
                 data_bytes: int):
        self._seg = seg
        self._creator = creator
        self.nslots = nslots
        self.data_bytes = data_bytes
        self._rlock = make_lock("ShmLane._rlock")
        self._outstanding: Dict[int, int] = {}  # seq -> end_total
        self.last_refusal = ""
        self._closed = False
        buf = seg.buf
        ring_size = _RING_HDR_SIZE + nslots * _DESC_SIZE + data_bytes
        r0 = _Ring(buf, _HDR_SIZE, nslots, data_bytes)
        r1 = _Ring(buf, _HDR_SIZE + ring_size, nslots, data_bytes)
        # creator (dialer) writes ring 0 / reads ring 1; acceptor inverse
        self._wring = r0 if creator else r1
        self._rring = r1 if creator else r0

    # -- construction -------------------------------------------------------
    @staticmethod
    def _prefault(seg, write: bool) -> None:
        """Touch every page of the slab once, up front.  Without this
        the byte ring advances through FRESH pages for every frame
        until its first wraparound, and the per-page first-touch
        faults cost more than the copies the lane saves (measured:
        ~0.7 ms/MB on this box — the lane benched SLOWER than loopback
        TCP until this landed).  The creator write-touches (allocates
        the tmpfs pages); attachers read-touch (populates their own
        PTEs against the already-allocated pages)."""
        import numpy as np

        a = np.frombuffer(seg.buf, dtype=np.uint8)
        if write:
            a[::4096] = 0  # fresh segments are zero-filled already
        else:
            int(a[::4096].sum())  # read faults map the shared pages

    @classmethod
    def create(cls, data_bytes: int = DEFAULT_DATA_BYTES,
               nslots: int = DEFAULT_SLOTS) -> "ShmLane":
        from multiprocessing import shared_memory

        ring_size = _RING_HDR_SIZE + nslots * _DESC_SIZE + data_bytes
        seg = shared_memory.SharedMemory(
            create=True, size=_HDR_SIZE + 2 * ring_size
        )
        _LOCAL_NAMES.add(seg.name)
        cls._prefault(seg, write=True)
        struct.pack_into("<8sII", seg.buf, 0, _MAGIC, _VERSION, nslots)
        struct.pack_into("<Q", seg.buf, 16, data_bytes)
        return cls(seg, creator=True, nslots=nslots, data_bytes=data_bytes)

    @classmethod
    def attach(cls, desc: dict) -> "ShmLane":
        """Acceptor-side attach from the hello capability dict
        (``describe()``'s output).  Raises on any mismatch — the caller
        downgrades the connection to pure TCP."""
        seg = _attach_untracked(str(desc["name"]))
        try:
            magic, version, nslots = struct.unpack_from("<8sII", seg.buf, 0)
            (data_bytes,) = struct.unpack_from("<Q", seg.buf, 16)
            if (magic != _MAGIC or version != _VERSION
                    or nslots != int(desc["slots"])
                    or data_bytes != int(desc["data"])):
                raise ShmLaneError(
                    f"slab geometry mismatch: {magic!r} v{version} "
                    f"{nslots}x{data_bytes} vs hello {desc}"
                )
            ring_size = _RING_HDR_SIZE + nslots * _DESC_SIZE + data_bytes
            if seg.size < _HDR_SIZE + 2 * ring_size:
                raise ShmLaneError(f"slab too small: {seg.size}")
            cls._prefault(seg, write=False)
        except Exception:
            try:
                seg.close()
            except (OSError, BufferError):
                pass
            raise
        return cls(seg, creator=False, nslots=nslots,
                   data_bytes=data_bytes)

    def describe(self) -> dict:
        return {"name": self._seg.name, "data": self.data_bytes,
                "slots": self.nslots}

    # -- write side ---------------------------------------------------------
    def try_send(self, parts, nbytes: int):
        """Copy one payload into the outbound ring.  Returns an opaque
        pending handle (pass to ``commit`` after the doorbell went out)
        or None with ``last_refusal`` set — the caller ships the
        payload inline instead."""
        if self._closed:
            self.last_refusal = "closed"
            return None
        try:
            out = self._wring.try_write(parts, nbytes)
        except ValueError:
            # close() released the base memoryview under us (stop vs
            # in-flight send race): behave like a full ring — the
            # caller ships inline and the connection dies on its own
            self.last_refusal = "closed"
            return None
        if isinstance(out, str):
            self.last_refusal = out
            # flight-recorder comm ring: each lane refusal with its
            # reason and size — the forensics evidence that separates
            # "ring sized too small" from "reader stopped draining"
            flight.note("comm", "shm_refusal", reason=out, nbytes=nbytes)
            return None
        return out

    def commit(self, pending) -> int:
        """Publish a reserved frame (doorbell already on the wire);
        returns its sequence number."""
        try:
            self._wring.commit(pending)
        except ValueError:
            pass  # closed under us: the conn is dying anyway
        return pending[0]

    @staticmethod
    def seq_of(pending) -> int:
        return pending[0]

    # -- read side ----------------------------------------------------------
    def read(self, seq: int, nbytes: int) -> ShmRegion:
        """Map one announced frame.  Raises ``ShmLaneError`` on any
        descriptor/sequence mismatch (connection-fatal)."""
        if self._closed:
            raise ShmLaneError("lane closed")
        try:
            view, end_total = self._rring.read(seq, nbytes)
        except ValueError as e:
            raise ShmLaneError(f"lane closed mid-read: {e}") from e
        with self._rlock:
            self._outstanding[seq] = end_total
        return ShmRegion(self, seq, view)

    def read_copy(self, seq: int, nbytes: int) -> bytes:
        """Materialized read for consumers with unbounded retention
        (stripe reassembly buffers, the hub's pin-pressure valve): one
        copy out of the slab, region released immediately."""
        region = self.read(seq, nbytes)
        try:
            return bytes(region.view)
        finally:
            region.release()

    def live_pins(self) -> int:
        """Inbound regions read but not yet released to zero — the
        leak-test observable: after a churn/rebind soak every queued
        pin must have been released (sent, dropped, or flushed by conn
        cleanup) and this must read 0.  Same protocol + meaning as
        ``reactor.BufPool.live`` for pooled TCP payload buffers."""
        with self._rlock:
            return len(self._outstanding)

    def inbound_backlog(self) -> int:
        """``live_pins()`` under its routing-decision name: ring
        reclamation is in-order, so ONE long-lived pin holds every
        later frame's bytes too — consumers with queue-length retention
        (the hub router) use this to decide pin vs materialize."""
        return self.live_pins()

    def _release_seq(self, seq: int) -> None:
        with self._rlock:
            end_total = self._outstanding.pop(seq, None)
            if end_total is None or self._closed:
                return
            try:
                self._rring.release(seq, end_total)
            except ValueError:
                pass  # closed under us: nothing left to reclaim into

    # -- lifecycle ----------------------------------------------------------
    def close(self, unlink: Optional[bool] = None) -> None:
        """Detach (and, for the creator, unlink) the slab.  Safe to
        call twice; a mapping still pinned by live numpy views is left
        to die with the process (close would raise BufferError)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._seg.close()
        except BufferError:
            # live numpy views still export the mapping: leave it to
            # die with the process, and disarm SharedMemory.__del__ so
            # it doesn't retry (and whine) at interpreter exit — the
            # exported memoryviews keep the mmap object alive
            logging.debug("shm lane: mapping still exported at close — "
                          "left to process exit")
            try:
                self._seg._mmap = None
                self._seg._buf = None
            except Exception:
                pass
        except OSError:
            pass
        if unlink if unlink is not None else self._creator:
            try:
                self._seg.unlink()
            except (OSError, FileNotFoundError):
                pass
        _LOCAL_NAMES.discard(self._seg.name)


def split_frame_line(data) -> int:
    """Offset just past the first newline of a frame held in memory
    (bytes OR a slab memoryview, searched chunk-wise so a multi-MB
    payload is never materialized); -1 if no header line."""
    if not isinstance(data, memoryview):
        nl = data.find(b"\n")
        return -1 if nl < 0 else nl + 1
    chunk = 8192
    off = 0
    n = len(data)
    while off < n:
        j = bytes(data[off:off + chunk]).find(b"\n")
        if j >= 0:
            return off + j + 1
        off += chunk
    return -1
