"""Virtual-client multiplexing — many node ids over ONE hub connection.

The reference's flagship distributed mode is one OS process per client
(``PROCESS_NUM = WORKER_NUM + 1``); on a small host that shape is the
measured scaling wall (PROFILE.md r9: 33 processes thrashing 2 cores
cost more than the wire ever did).  This module decouples *client
count* from *process count* on the wire side:

- ``TcpMuxBackend`` dials the hub once with a **hello v2** frame
  (``{"node_ids": [...]}``), registering every virtual client id on one
  socket.  Hub routing is id-keyed, so unicast frames to any virtual id
  arrive here; broadcast copies arrive once per CONNECTION as
  ``__hub__: mux`` wrapped frames naming the co-located target ids
  (``TcpHub``'s per-conn dedup), and this backend fans them out
  locally.
- ``VirtualNodeBackend`` is one virtual client's ``CommBackend``
  endpoint on the shared connection: it has its own node id, its own
  observers, its own telemetry/trace identity — so per-virtual-node
  handlers, chaos wrappers (``faults.ChaosBackend``), and trace hop
  chains behave exactly as they would on a dedicated process — while
  every byte rides the one muxed socket.

Wire-byte accounting stays honest: a wrapped broadcast's bytes are
counted ONCE (on the first local delivery); the per-virtual fan-out
increments message counts only (``comm.mux_deliveries``).  The local
clones share payload objects by identity — stamping is copy-on-write
(``obs.trace_ctx``), so per-virtual hop lists never alias.

The vmapped cohort engine that turns the co-located deliveries into ONE
jit step lives in ``fedml_tpu.algorithms.fedavg_mux``.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from fedml_tpu.comm.backend import CommBackend
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.tcp import TcpBackend
from fedml_tpu.obs import flight, trace_ctx
from fedml_tpu.obs.telemetry import get_telemetry


class VirtualNodeBackend(CommBackend):
    """One virtual client's endpoint on a shared muxed hub connection.

    Sends ride the mux's socket stamped with THIS node's identity;
    inbound frames are delivered by the mux's demux dispatch.  The
    lifetime of the physical connection is owned by the mux backend —
    ``stop()`` here is deliberately a no-op (a single virtual client
    finishing must not sever its 499 co-located peers), and ``run()``
    refuses: the muxer process drives exactly one reader loop, the
    mux's.
    """

    def __init__(self, mux: "TcpMuxBackend", node_id: int):
        super().__init__(node_id)
        self.mux = mux

    def send_message(self, msg: Message) -> None:
        self.mux._send_message_as(msg, self.node_id)

    def drop_connection(self) -> None:
        """Fault injection: a virtual client's 'disconnect' severs the
        SHARED connection — on a real muxer one flaky socket takes all
        co-located virtual clients off the hub at once, which is
        exactly the blast radius the chaos layer should exercise."""
        self.mux.drop_connection()

    def run(self) -> None:
        raise RuntimeError(
            f"virtual node {self.node_id}: run() belongs to the mux "
            "backend — a muxer process drives ONE reader loop"
        )

    def stop(self) -> None:
        """No-op by contract: the shared connection outlives any one
        virtual client (the cohort manager stops the mux backend once,
        at FINISH)."""

    def deliver(self, msg: Message, nbytes: Optional[int] = None) -> None:
        """Demux entry: hand one inbound frame to this node's
        observers (recv stamp + telemetry via the base ``_notify``)."""
        self._notify(msg, nbytes=nbytes)


class TcpMuxBackend(TcpBackend):
    """Hub connection multiplexing N virtual node ids (hello v2).

    Inbound dispatch:

    - ``__hub__: mux`` wrapped broadcast copy → the inner frame is
      parsed once and delivered to every named co-located virtual node
      as a shallow clone (payload objects shared, per-clone trace
      stamps);
    - striped broadcast → reassembled exactly as a plain backend, then
      fanned out to the stream's ``nodes`` (stripe 0 carried them);
    - unicast frame → routed to the virtual node matching its
      ``receiver``.

    ``add_flush_hook`` is the cohort-batching contract: hooks run on
    the reader thread after ALL local deliveries of one physical frame
    — the moment a vmapped cohort engine can train everyone the
    broadcast reached in ONE jit step.  ``in_dispatch()`` tells a
    handler whether such a flush is coming (chaos-delayed re-injections
    arrive on timer threads, where it is not).
    """

    def __init__(self, node_ids, host: str, port: int, **kw):
        ids = [int(i) for i in node_ids]
        if not ids:
            raise ValueError("TcpMuxBackend needs at least one node id")
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate virtual node ids: {ids}")
        self.node_ids = ids
        self._virtual: Dict[int, VirtualNodeBackend] = {}
        self._dispatch_flag = threading.local()
        self._flush_hooks: List[Callable[[], None]] = []
        super().__init__(ids[0], host, port, **kw)
        for i in ids:
            self._virtual[i] = VirtualNodeBackend(self, i)

    # -- registration -------------------------------------------------------
    def _hello_obj(self) -> dict:
        return {"node_ids": self.node_ids}

    # -- virtual endpoints --------------------------------------------------
    def virtual(self, node_id: int) -> VirtualNodeBackend:
        return self._virtual[int(node_id)]

    def virtuals(self) -> List[VirtualNodeBackend]:
        return [self._virtual[i] for i in self.node_ids]

    def add_flush_hook(self, fn: Callable[[], None]) -> None:
        """Register a post-dispatch hook (reader thread, after every
        local delivery of one physical frame has run its handler)."""
        self._flush_hooks.append(fn)

    def in_dispatch(self) -> bool:
        return bool(getattr(self._dispatch_flag, "active", False))

    # -- demux dispatch -----------------------------------------------------
    def _run_flush_hooks(self) -> None:
        for fn in list(self._flush_hooks):
            try:
                fn()
            except Exception:
                # a cohort-engine bug must not kill the reader thread —
                # the muxer would silently stop receiving
                logging.exception("node %d: mux flush hook failed",
                                  self.node_id)

    def _fan_out_local(self, msg: Message, nodes, nbytes: Optional[int],
                       reasm_t: Optional[float] = None) -> None:
        """Deliver one physical broadcast copy to every named
        co-located virtual node: shallow clones (payload shared by
        identity), per-clone recv/done trace stamps, wire bytes counted
        exactly once (the first delivery)."""
        tel = get_telemetry()
        tel.inc("comm.mux_frames", msg_type=msg.type)
        # one flight record per PHYSICAL frame (not per local delivery):
        # the black box keeps the fan-out shape without 500x ring churn
        flight.note("comm", "mux_fanout", msg_type=msg.type,
                    nbytes=nbytes or 0, n_nodes=len(nodes or ()))
        self._dispatch_flag.active = True
        try:
            first = True
            for n in nodes or ():
                vb = self._virtual.get(int(n))
                if vb is None:
                    logging.warning(
                        "node %d: mux frame names unknown virtual node "
                        "%s — that copy dropped", self.node_id, n,
                    )
                    continue
                clone = msg.clone_for(int(n))
                if reasm_t is not None:
                    # backdated reassembly hop, per virtual chain
                    trace_ctx.stamp_msg(clone, vb.node_id, "reasm",
                                        t=reasm_t)
                tel.inc("comm.mux_deliveries", msg_type=msg.type)
                vb.deliver(clone, nbytes=nbytes if first else None)
                first = False
        finally:
            self._dispatch_flag.active = False
        self._run_flush_hooks()

    def _on_mux_frame(self, frame: dict, payload, nbytes: int,
                      region=None) -> None:
        try:
            msg = Message.from_frame_bytes(payload)
        except Exception:
            logging.warning(
                "node %d: undecodable mux-wrapped frame (%s) — broadcast "
                "copy dropped", self.node_id, frame.get("msg_type"),
            )
            return
        # slab-backed payload: the clones the local fan-out hands each
        # virtual node share this residency, so a chaos-delayed copy's
        # timer path can pin the bytes past the dispatch scope
        msg._region = region
        self._fan_out_local(msg, frame.get("nodes"), nbytes)

    def _deliver_reassembled(self, msg: Message, ent: dict) -> None:
        nodes = ent.get("nodes")
        if nodes is None:
            # a striped frame without a nodes annotation: route like a
            # unicast by its receiver (defensive — the hub annotates
            # every stripe stream to a muxed conn)
            self._notify(msg, nbytes=ent["nbytes"])
            return
        self._fan_out_local(msg, nodes, ent["nbytes"], reasm_t=ent["t0"])

    def _notify(self, msg: Message, nbytes: Optional[int] = None) -> None:
        """Unicast (or nodes-less) inbound frame: route by receiver to
        the matching virtual node; anything else falls through to the
        mux's own observers (normally none — logged as unhandled by
        the base path)."""
        vb = self._virtual.get(msg.receiver)
        if vb is None:
            super()._notify(msg, nbytes=nbytes)
            return
        self._dispatch_flag.active = True
        try:
            vb.deliver(msg, nbytes=nbytes)
        finally:
            self._dispatch_flag.active = False
        self._run_flush_hooks()
