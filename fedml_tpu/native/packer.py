"""ctypes binding for the native row-gather packer (packer.cpp).

Lazy build-and-cache: the shared library is compiled with the system
``g++`` the first time it's needed and cached next to this file
(rebuilt when packer.cpp is newer).  If no compiler is present or the
build fails, ``gather_rows`` silently uses the numpy fallback — the
native path is an optimization, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "packer.cpp"
_LIB = _HERE / "_libpacker.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # compile to a process-unique temp path and rename into place:
    # concurrent processes (pytest-xdist, multi-process launches) must
    # never dlopen a partially-written .so
    tmp = _LIB.with_suffix(f".tmp.{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        tmp.unlink(missing_ok=True)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("FEDML_TPU_NO_NATIVE"):
            return None
        try:
            stale = (not _LIB.exists()) or (
                _SRC.stat().st_mtime > _LIB.stat().st_mtime
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(str(_LIB))
            lib.gather_rows.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int32,
            ]
            lib.gather_rows.restype = None
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def gather_rows(
    src: np.ndarray,
    idx: np.ndarray,
    out: Optional[np.ndarray] = None,
    *,
    n_threads: int = 0,
) -> np.ndarray:
    """out[i] = src[idx[i]] over leading-axis rows.

    src must be C-contiguous; idx is any integer array (flattened).
    out, if given, must be C-contiguous with shape
    (idx.size, *src.shape[1:]) and src's dtype.  n_threads=0 picks the
    hardware count.  Returns out.
    """
    if not src.flags.c_contiguous:
        src = np.ascontiguousarray(src)
    flat_idx = np.ascontiguousarray(idx, dtype=np.int64).ravel()
    out_shape = (flat_idx.size, *src.shape[1:])
    if out is None:
        out = np.empty(out_shape, dtype=src.dtype)
    else:
        if out.shape != out_shape or out.dtype != src.dtype:
            raise ValueError(
                f"out has shape {out.shape}/{out.dtype}, "
                f"need {out_shape}/{src.dtype}"
            )
        if not out.flags.c_contiguous:
            raise ValueError("out must be C-contiguous")

    lib = _load()
    if lib is None or src.size == 0 or flat_idx.size == 0:
        if flat_idx.size:
            np.take(src, np.clip(flat_idx, 0, src.shape[0] - 1),
                    axis=0, out=out)
        return out

    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        ctypes.c_int64(src.shape[0]),
        flat_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.c_char_p),
        ctypes.c_int64(flat_idx.size),
        ctypes.c_int64(row_bytes),
        ctypes.c_int32(n_threads),
    )
    return out
