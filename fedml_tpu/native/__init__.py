"""Native (C++) host-runtime components.

The compute path of the framework is JAX/XLA (compiled native code by
construction); these are the HOST-side pieces where Python/numpy is the
bottleneck — currently the per-round client-shard packer
(``gather_rows``).  Built on demand with the system ``g++`` via ctypes
(no pip/pybind dependency); every entry point has a pure-numpy fallback
so the framework works identically where no toolchain exists
(``FEDML_TPU_NO_NATIVE=1`` forces the fallback).
"""

from fedml_tpu.native.packer import gather_rows, native_available

__all__ = ["gather_rows", "native_available"]
