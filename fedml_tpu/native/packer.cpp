// Native host-side data packer: threaded row gather.
//
// The per-round host hot path of the framework is packing sampled
// clients' shards into the fixed-shape [K, steps, B, ...] block that
// the compiled round consumes (core/types.py pack_clients — the TPU
// replacement for the reference's per-client torch DataLoaders,
// SURVEY.md §7 "torch DataLoader dicts per client").  numpy fancy
// indexing does this single-threaded with an extra stack copy; this
// gather writes each row straight into the preallocated output block
// from multiple threads.
//
// Dtype-agnostic by treating rows as raw bytes.  Out-of-range indices
// are clamped defensively (callers validate; clamping turns a logic
// error into a visible wrong-sample, not a segfault).
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread packer.cpp

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Copy rows src[idx[i]] -> dst[i] for i in [0, n_rows).
// src: [src_rows, row_bytes] C-contiguous; dst: [n_rows, row_bytes].
void gather_rows(const char* src, int64_t src_rows, const int64_t* idx,
                 char* dst, int64_t n_rows, int64_t row_bytes,
                 int32_t n_threads) {
  if (n_rows <= 0 || row_bytes <= 0 || src_rows <= 0) return;
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  int threads = n_threads > 0 ? n_threads : hw;
  // don't spawn threads for tiny copies (< ~4 MiB total)
  int64_t total_bytes = n_rows * row_bytes;
  if (threads > 1 && total_bytes < (int64_t)4 << 20) threads = 1;
  threads = std::min<int64_t>(threads, n_rows);

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t j = idx[i];
      if (j < 0) j = 0;
      if (j >= src_rows) j = src_rows - 1;
      std::memcpy(dst + i * row_bytes, src + j * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };

  if (threads == 1) {
    worker(0, n_rows);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  int64_t chunk = (n_rows + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n_rows);
    if (lo >= hi) break;
    pool.emplace_back(worker, lo, hi);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
