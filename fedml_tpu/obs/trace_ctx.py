"""Federation-wide distributed trace context — per-hop stamps on the wire.

PR 5 left a question telemetry could not answer: at 32 clients the hub
multicast path wins 32x on bytes but LOSES ~12% p50 round wall, and the
per-process counters cannot say whether the time went to hub queue wait,
sender-pool drain, client compute, or the upload fold.  This module is
the cross-process layer that can: every traced message carries a small
context dict under the reserved ``__trace__`` param — run id, round,
origin node, a per-process sequence number, a copy counter (chaos
duplicates), and a list of ``[node, event, t_monotonic]`` hop stamps —
and each hop appends its own stamp:

    ``send``     — the sending backend, just before the socket write;
    ``hub_in``   — the hub reader thread, frame parsed off the stream;
    ``hub_out``  — the hub sender-pool worker, frame leaving its queue;
    ``recv``     — the receiving backend, frame delivered to observers;
    ``done``     — the receiver's handler completed (``NodeManager``).

The receiving node then emits the whole chain as one ``trace_hop``
telemetry event into its own ``metrics-node<id>.jsonl`` — so a merger
(``tools/fed_timeline.py``) holding every process's file can reconstruct
each message's full path, and ``hub_out - hub_in`` IS the hub queue
wait, measured, per frame.

Zero-copy contract (the PR-5 hot path must not regress): the context
lives in the frame's JSON **header line only**.  Stamping on the tcp
send path re-encodes just that line (``restamp_parts``) around the
message's memoized ``to_frame_parts()`` buffers — the multi-MB payload
memoryviews are reused by identity and the memoized list is never
mutated, so broadcast fan-out and retries still serialize the model
exactly once.  Each physical copy (per-receiver clone, chaos duplicate,
retry of a delayed frame) restamps from the same memoized base and
therefore gets its own distinct hop stamps.

Clocks: hop stamps are ``time.perf_counter()`` — monotonic, but with a
per-process arbitrary origin.  The hub is the reference clock; every
``TcpBackend`` measures its offset to it during the dial handshake
(NTP-style ping burst, min-RTT sample — ``estimate_offset``) and
records it as a ``clock_sync`` telemetry event, which is what lets the
merger place all processes on one timeline with ~RTT/2 uncertainty
(loopback: tens of microseconds).

Tracing is OFF by default and costs one dict lookup per message when
off.  Enable with ``FEDML_TPU_TRACE=1`` in the environment (the
``launch(trace=True)`` knob) or ``set_enabled(True)`` in-process.

Stdlib-only by design (like ``obs.telemetry``): the hub and the
timeline tools must import this without jax.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from fedml_tpu.obs.telemetry import get_telemetry

# reserved Message param / frame-header key (never carries arrays, so it
# always serializes into the header line, never the binary payload)
TRACE_KEY = "__trace__"
HUB_NODE = "hub"  # hop node label for the hub process (nodes are ints)

ENV_ENABLE = "FEDML_TPU_TRACE"
ENV_RUN_ID = "FEDML_TPU_RUN_ID"

_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()
_seq = itertools.count()


def enabled() -> bool:
    """Process-wide tracing switch (env ``FEDML_TPU_TRACE=1``), cached
    after first read; ``set_enabled`` overrides for in-process tests."""
    global _enabled
    if _enabled is None:
        with _enabled_lock:
            if _enabled is None:
                _enabled = os.environ.get(ENV_ENABLE, "") == "1"
    return _enabled


def set_enabled(flag: Optional[bool]) -> None:
    """Override the switch (True/False); ``None`` re-reads the env on
    next use — tests reset with this."""
    global _enabled
    with _enabled_lock:
        _enabled = flag


def run_id() -> str:
    return os.environ.get(ENV_RUN_ID) or f"pid{os.getpid()}"


def now() -> float:
    """The hop clock: monotonic, per-process origin (see module doc)."""
    return time.perf_counter()


# --- context construction / stamping ----------------------------------------

def new_ctx(origin: int, round_idx=None) -> dict:
    ctx = {
        "rid": run_id(),
        "org": int(origin),
        "seq": next(_seq),
        "copy": 0,
        # creation stamp, origin clock: ``ensure`` runs at send ENTRY,
        # before the frame encode, so ``send_hop.t - t0`` is the
        # serialize cost fed_timeline attributes to the origin
        "t0": now(),
        "hops": [],
    }
    if round_idx is not None:
        ctx["rnd"] = round_idx
    return ctx


def stamp_ctx(ctx: dict, node, event: str, t: Optional[float] = None) -> dict:
    """Copy-on-write: a NEW ctx dict with one more hop.  Never mutates
    ``ctx`` — on inproc the same params objects are shared between
    sender and receiver (and between chaos duplicate copies), so every
    stamp must fork the hop list instead of appending in place."""
    return {**ctx,
            "hops": list(ctx.get("hops") or ()) + [[node, event,
                                                    now() if t is None else t]]}


def ensure(msg, origin: int) -> None:
    """Attach a fresh context to ``msg`` if tracing is on and it has
    none.  Runs BEFORE the first encode, so the context lands in the
    memoized header; per-hop stamps are then header-only restamps."""
    if not enabled() or TRACE_KEY in msg.params:
        return
    msg.add_params(TRACE_KEY, new_ctx(origin, msg.get("round_idx")))


def stamp_msg(msg, node, event: str, t: Optional[float] = None) -> None:
    """Stamp a decoded/in-process message (inproc send, backend recv).
    Assigns directly into ``msg.params`` — deliberately NOT through
    ``add_params``: a hop stamp is header-only metadata and must not
    invalidate a memoized frame encoding (the tcp path restamps the
    header line instead of re-encoding the payload).  ``t`` backdates
    the stamp (stripe reassembly stamps first-stripe arrival when the
    frame finally decodes)."""
    ctx = msg.params.get(TRACE_KEY)
    if ctx is not None:
        msg.params[TRACE_KEY] = stamp_ctx(ctx, node, event, t)


def fork_copy(msg):
    """Per-copy identity for a chaos ``duplicate``: a shallow clone
    whose context carries ``copy + 1``, so the two deliveries are
    distinguishable in the merged timeline (their hop stamps are
    already distinct — each copy restamps from the shared base).
    Untraced messages pass through unchanged."""
    ctx = msg.params.get(TRACE_KEY)
    if ctx is None:
        return msg
    twin = msg.clone_for(msg.receiver)
    twin.params[TRACE_KEY] = {**ctx, "copy": int(ctx.get("copy", 0)) + 1}
    return twin


# --- zero-copy header-line restamping (tcp frame path) ----------------------

def restamp_parts(msg, parts: Sequence, node, event: str) -> List:
    """A NEW parts list whose header line carries one more hop stamp.

    ``parts`` is (typically) the message's memoized ``to_frame_parts()``
    encoding: element 0 is the JSON header line, the rest are payload
    buffers.  The returned list re-encodes ONLY the header; payload
    elements are the same objects by identity and the input list is
    never mutated — the encode-once contract survives stamping.  The
    header's ``__binlen__``/``__ndbuf__`` bookkeeping is payload-
    relative, so a header that grows by one hop stays self-consistent.
    Untraced messages return ``parts`` unchanged (no JSON work).
    """
    if TRACE_KEY not in msg.params:
        return list(parts) if not isinstance(parts, list) else parts
    hdr = json.loads(parts[0])
    ctx = hdr.get(TRACE_KEY)
    if ctx is None:
        return list(parts) if not isinstance(parts, list) else parts
    hdr[TRACE_KEY] = stamp_ctx(ctx, node, event)
    return [(json.dumps(hdr) + "\n").encode(), *parts[1:]]


def hub_stamp(hdr: dict, event: str) -> None:
    """Stamp a hub-side parsed header dict in place (the dict is
    reader-thread-local at ``hub_in`` time; the value swap is still COW
    so an mcast header shared across receiver queues never aliases hop
    lists)."""
    ctx = hdr.get(TRACE_KEY)
    if ctx is not None:
        hdr[TRACE_KEY] = stamp_ctx(ctx, HUB_NODE, event)


def hub_out_line(hdr: dict) -> bytes:
    """Encode a queued header dict as its wire line with a fresh
    ``hub_out`` stamp — called by the sender-pool worker at drain time,
    once per receiver, so every fan-out copy records its own queue
    wait.  ``hdr`` itself is never mutated (shared across an mcast's
    receiver queues)."""
    ctx = hdr.get(TRACE_KEY)
    if ctx is None:
        return (json.dumps(hdr) + "\n").encode()
    stamped = {**hdr, TRACE_KEY: stamp_ctx(ctx, HUB_NODE, "hub_out")}
    return (json.dumps(stamped) + "\n").encode()


# --- receive-side completion ------------------------------------------------

def on_recv(msg, node) -> None:
    """Transport delivery stamp (``CommBackend._notify``)."""
    if TRACE_KEY in msg.params:
        stamp_msg(msg, node, "recv")


def on_handled(msg, node, telemetry=None) -> None:
    """Handler-completion stamp + emission: the full hop chain becomes
    one ``trace_hop`` telemetry event on the RECEIVER's registry, which
    ``MetricsLogger.log_telemetry`` drains into that process's metrics
    file.  The trace never travels back over the wire."""
    ctx = msg.params.get(TRACE_KEY)
    if ctx is None:
        return
    ctx = stamp_ctx(ctx, node, "done")
    msg.params[TRACE_KEY] = ctx
    (telemetry or get_telemetry()).event(
        "trace_hop",
        rid=ctx.get("rid"),
        seq=ctx.get("seq"),
        copy=ctx.get("copy", 0),
        org=ctx.get("org"),
        round=ctx.get("rnd"),
        msg_type=msg.type,
        node=node,
        t0=ctx.get("t0"),  # send-entry stamp, ORIGIN clock (serialize)
        hops=ctx["hops"],
    )


# --- clock alignment --------------------------------------------------------

def estimate_offset(
    samples: Sequence[Tuple[float, float, float]],
) -> Tuple[Optional[float], Optional[float]]:
    """NTP-style offset from ``(t0, th, t1)`` ping samples: ``t0``/``t1``
    are the LOCAL monotonic clock around the round trip, ``th`` the
    hub's monotonic clock at reply.  The minimum-RTT sample bounds the
    asymmetry best, so its midpoint estimate wins:

        hub_clock  ~=  local_clock + offset,   |error| <= rtt / 2

    Returns ``(offset_s, rtt_s)``, or ``(None, None)`` with no usable
    sample.  Pure function — the synthetic-skew unit test's surface.
    """
    best = None
    for t0, th, t1 in samples:
        if th is None:
            continue
        rtt = t1 - t0
        if rtt < 0:
            continue
        offset = th - (t0 + t1) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    if best is None:
        return None, None
    return best[1], best[0]


def record_clock_sync(node: int, offset_s: Optional[float],
                      rtt_s: Optional[float], samples: int,
                      telemetry=None) -> None:
    """Publish a handshake's offset estimate: a gauge pair (live
    introspection) plus a ``clock_sync`` event the metrics file keeps —
    what ``fed_timeline`` reads to map this node onto the hub clock."""
    if offset_s is None:
        return
    t = telemetry or get_telemetry()
    t.gauge_set("clock.hub_offset_s", offset_s, node=node)
    t.gauge_set("clock.hub_rtt_s", rtt_s, node=node)
    t.event("clock_sync", node=node, offset_s=offset_s, rtt_s=rtt_s,
            samples=samples)
