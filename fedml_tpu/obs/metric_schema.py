"""The checked-in telemetry naming registry — fedlint's source of truth.

Every counter/gauge/histogram series and every telemetry-event kind the
package emits is declared here, with its label set and one line of
meaning.  The ``metric-name`` linter (``fedml_tpu/analysis``) verifies
at CI time that every literal (and dynamic-pattern) name in code exists
here with the matching type — so a typo'd series fails the lint instead
of silently never aggregating — and PROFILE.md's metrics appendix cites
THIS module instead of maintaining a hand-copied table that drifts.

Conventions (``obs/telemetry.py``): rendered keys are
``name{label=value,...}`` with sorted labels; histograms are
log2-bucketed; names are ``<namespace>.<metric>``, cumulative gauges
carry a ``_total`` suffix, histogram names end in their unit (``_s``,
``_bytes``).

Stdlib-only, pure literals: the lint CI executes this file on a bare
interpreter, and the analysis fixtures exec it directly.
"""

from __future__ import annotations

# --- counters (monotonic; Telemetry.inc) ------------------------------------
COUNTERS = {
    "comm.sent_msgs": "messages sent {msg_type=}",
    "comm.sent_bytes": "exact wire bytes sent (tcp) / estimator (inproc) {msg_type=}",
    "comm.recv_msgs": "messages delivered to observers {msg_type=}",
    "comm.recv_bytes": "exact wire bytes received {msg_type=}",
    "comm.raw_bytes": "logical fp32 bytes of codec-encoded payloads {msg_type=}",
    "comm.compressed_bytes": "encoded bytes actually shipped {msg_type=}",
    "comm.unhandled_msgs": "frames with no registered handler {msg_type=}",
    "comm.send_retries": "bounded send retries after transient OSError {msg_type=}",
    "comm.send_failed": "sends abandoned after the retry budget {msg_type=}",
    "comm.reconnects": "hub re-dials by the auto-reconnect path",
    "comm.mcast_sends": "native multicast frames sent {msg_type=}",
    "comm.mcast_receivers": "receivers addressed by multicast frames {msg_type=}",
    "comm.stripe_frames": "mcast_stripe continuation frames received {msg_type=}",
    "comm.stripe_reassemblies": "striped logical frames reassembled + delivered {msg_type=}",
    "comm.stripe_aborts": "striped logical frames killed (gap/crc/overflow/stale/undecodable) {reason=,msg_type=}",
    "comm.mux_frames": "muxed broadcast copies received on a shared connection {msg_type=}",
    "comm.mux_deliveries": "local fan-out deliveries to co-located virtual nodes {msg_type=}",
    "comm.shm_frames": "frames whose payload rode the shared-memory lane {msg_type=}",
    "comm.shm_bytes": "payload bytes carried through shm ring slabs {msg_type=}",
    "comm.shm_fallbacks": "lane-eligible payloads shipped inline TCP instead {reason=}",
    "comm.delta_bcast_bytes": "encoded bytes of delta-mode broadcast payloads",
    "comm.delta_full_fallbacks": "delta-mode broadcasts that shipped the full model {reason=}",
    "comm.delta_resyncs": "full-resync requests after an inapplicable delta sync",
    "comm.shm_hub_copies": "laned inbound payloads the hub materialized instead of pinning {reason=}",
    "edge.folded_uploads": "uploads an edge hub folded into its partial aggregate",
    "edge.uplink_bytes": "wire bytes of E2S_PARTIAL frames an edge hub sent upstream",
    "edge.uplink_frames": "E2S_PARTIAL frames an edge hub sent upstream {reason=}",
    "edge.flat_fallbacks": "uploads an edge hub forwarded upstream raw instead of folding {reason=}",
    "edge.partials_folded": "E2S_PARTIAL frames the root folded into the round accumulator",
    "hub.mcast_frames": "mcast control frames fanned out by the hub {msg_type=}",
    "hub.dropped_frames": "frames to unregistered/dead/over-bound receivers {msg_type=}",
    "hub.node_rebinds": "node ids re-claimed by a newer connection (new conn wins)",
    "digest.sent": "telemetry digest frames emitted by this process's reporter",
    "digest.frames": "digest frames accepted + merged by the rollup",
    "digest.rejected": "digest frames rejected pre-merge {reason=}",
    "digest.dup_frames": "digest frames skipped: per-source seq did not advance",
    "slo.violations": "SLO objective violations {objective=}",
    "slo.evaluations": "SLO evaluation passes (one per closed round)",
    "faults.injected": "chaos-layer injections {action=,msg_type=}",
    "faults.observed": "tolerance-layer observations {kind=,msg_type=}",
    "robust.clipped_uploads": "uploads clipped against the broadcast base (norm bound or DP clip)",
    "robust.dp_noised_uploads": "uploads given client-level DP clip+noise",
    "robust.capped_conns": "connections rescaled by the contribution cap",
    "robust.cap_infeasible": "rounds where the conn cap was unsatisfiable (left unapplied, loudly)",
    "rounds.degraded": "rounds closed under the aggregation target",
    "async.cut_rounds": "async-mode round cuts (K arrivals or the cut deadline)",
    "async.stale_weighted_uploads": "in-window stale uploads folded at discounted weight",
    "async.discarded_weight": "sample weight removed by staleness discounts (sum (1-w)·n)",
    "async.folded_weight": "sample weight folded into async cuts (sum w·n)",
    "traffic.offline_rounds": "node-rounds skipped by traffic-model churn draws",
    "traffic.delayed_uploads": "uploads deferred by a traffic-model delay draw",
    "traffic.rebinds": "connection flaps (drop+redial) drawn by the traffic model",
    "traffic.straggler_draws": "heavy-tailed straggler delays drawn by the traffic model",
    "flight.dumps": "flight-recorder bundles written {trigger=}",
    "flight.dumps_suppressed": "dumps skipped by the per-trigger rate limit or a dump already in flight {trigger=}",
    "flight.dump_errors": "bundle writes that failed (fs errors; recording continues)",
    "hub.zero_copy_forwards": "laned frames enqueued as refcounted slab pins (no materialize copy) {msg_type=}",
    "shard.cohort_fallbacks": "muxed cohorts trained on the unsharded path {reason=}",
    "jax.compiles": "jit compilations per instrumented fn {fn=}",
    "jax.backend_compile_events": "runtime jax.monitoring compile events {event=}",
}

# --- gauges (instantaneous, or cumulative with _total; gauge_set/max) --------
GAUGES = {
    "hub.tier": "aggregation-tree tier of this process's hub (0=root, 1=edge)",
    "hub.connections": "physical hub connections (== nodes for v1 dialers)",
    "hub.nodes": "registered node ids (>= connections under muxing)",
    "hub.send_queue_frames": "per-connection outbound queue depth {conn=}",
    "hub.send_queue_bytes": "per-connection outbound queue bytes {conn=}",
    "hub.conn_nodes": "node ids registered on a connection {conn=}",
    "hub.node_rebinds_total": "cumulative id rebinds (time series form)",
    "hub.backpressure_drops_total": "cumulative over-bound queue drops",
    "hub.shm_conns": "connections with an attached shared-memory lane",
    "hub.shm_frames_total": "cumulative frames the hub moved via shm lanes",
    "hub.shm_bytes_total": "cumulative payload bytes via shm lanes",
    "hub.shm_fallbacks_total": "cumulative hub-side lane fallbacks to inline TCP",
    "hub.mcast_frames_total": "cumulative mcast frames (time series form)",
    "hub.stripe_frames_total": "cumulative enqueued mcast stripes (time series form)",
    "hub.threads": "hub-owned OS threads (reactor: 1; threaded: accept + senders + readers)",
    "hub.open_fds": "descriptors the hub holds open (reactor: selector map size)",
    "jax.device_mem_bytes": "device memory in use {device=}",
    "jax.device_mem_peak_bytes": "high-water device memory {device=}",
    "digest.streams": "distinct digest source streams the rollup has seen",
    "clock.hub_offset_s": "estimated monotonic-clock offset to the hub {node=}",
    "clock.hub_rtt_s": "min round-trip of the clock-sync burst {node=}",
    "shard.mesh_dp": "dp (cohort) axis width of the partition-rule mesh",
    "shard.mesh_mp": "mp (model) axis width of the partition-rule mesh",
}

# --- histograms (log2-bucketed; Telemetry.observe) ---------------------------
HISTOGRAMS = {
    "comm.send_latency_s": "time inside send_message (serialize + write) {msg_type=}",
    "comm.handle_latency_s": "NodeManager handler time {msg_type=}",
    "span.agg_fold_s": "per-arrival streaming-aggregation fold",
    "span.decode_wait_s": "upload decode queue wait, reader submit -> pool pickup",
    "span.decode_s": "upload decode + finite-firewall scan (off the reader thread)",
    "span.encode_overlap_s": "next broadcast's off-thread encode+send span",
    "span.agg_s": "close-time aggregation (buffered mode / normalize)",
    "span.server_round_s": "server round wall time, open to close",
    "span.reconnect_s": "outage span, first EOF to re-registered",
    "robust.upload_norm": "L2 norm of each decoded upload's delta vs the broadcast base",
    "span.traced_round_s": "per-round synced seconds under trace_rounds",
    "slo.round_wall_s": "server round wall (open->close) — the SLO percentile source",
    "slo.round_bytes": "server-visible comm bytes folded per round (sent+recv delta)",
    "async.upload_staleness": "round gap r-b of each accepted async upload (0 = current)",
    "traffic.upload_delay_s": "per-upload delay the traffic model imposed",
    "jax.compile_s": "wall time of compile-triggering calls {fn=}",
    "jax.backend_compile_s": "runtime-reported compile durations {event=}",
    "flight.dump_write_s": "atomic flight-bundle write (snapshot + json + replace)",
    "lock.wait_s": "CheckedLock acquire block time past the flight threshold {lock=}",
    "hub.loop_lag_s": "reactor event-loop batch service time (time away from select)",
}

# --- dynamic-name patterns ---------------------------------------------------
# MetricsLogger.span(name) emits f"span.{name}_s" for driver-defined
# span names (sample/pack/round/eval/...): any span.*_s is a histogram.
METRIC_PATTERNS = {
    "span.*_s": "histogram",
}

# --- telemetry event kinds (Telemetry.event + MetricsLogger records) ---------
EVENTS = {
    "compile": "one jit compilation {fn, signature, seconds}",
    "trace": "profiler trace written {trace_dir}",
    "trace_rounds": "profiler round bracketing {trace_dir, per-round seconds}",
    "config": "the full experiment dataclass (MetricsLogger record)",
    "telemetry": "registry snapshot record (MetricsLogger.log_telemetry)",
    "resume": "checkpoint resume {round}",
    "degraded_round": "round closed under target {round, arrived, dropped}",
    "round_close": "round boundary {round, participants, t_open_m, t_close_m}",
    "hub_stats": "hub queue-depth/backpressure snapshot (1 s timer)",
    "clock_sync": "dial-handshake offset estimate {node, offset_s, rtt_s}",
    "trace_hop": "full per-message hop chain (receiver-side emission)",
    "mux_members": "muxer membership {muxer, nodes} — timeline track grouping",
    "slo_violation": "one failed SLO objective {round, objective, observed, threshold}",
    "flight_dump": "flight-recorder bundle written {trigger, reason, round, path, write_s}",
}

# flat view used by the linter and by tools that just need existence
METRICS = {
    **{name: "counter" for name in COUNTERS},
    **{name: "gauge" for name in GAUGES},
    **{name: "histogram" for name in HISTOGRAMS},
}


def metric_type(name: str) -> str:
    """'counter' | 'gauge' | 'histogram' | '' for a series name (exact
    match first, then the dynamic patterns)."""
    kind = METRICS.get(name)
    if kind:
        return kind
    import fnmatch

    for pat, ptype in METRIC_PATTERNS.items():
        if fnmatch.fnmatchcase(name, pat):
            return ptype
    return ""
