"""Process-wide telemetry registry: counters, gauges, histograms, events.

The reference scatters manual wall-clock logging through aggregator /
server manager / trainer (SURVEY.md §5.1/§5.5) and counts nothing else;
here one registry owns every host-side counter so any layer (comm
backends, the round drivers, the jit compile tracker) can report without
plumbing a handle through the call stack.  Everything is plain Python on
the HOST side — nothing here may ever run inside jit-traced code.

Naming convention (used by ``tools/trace_summary.py`` to group series):

    <namespace>.<metric>{label=value,label2=value2}

e.g. ``comm.sent_bytes{msg_type=S2C_SYNC_MODEL}``,
``jax.compiles{fn=round_fn}``, ``span.round_s``.  Labels are sorted, so
a (name, labels) pair always renders to the same key.

Histograms are log-scale bucketed (powers of two): an observation ``v``
lands in the bucket with upper bound ``2**ceil(log2(v))``; ``v == 0``
gets its own ``0`` bucket.  NaN/inf/negative observations are rejected
(``ValueError``) — a NaN folded into ``sum`` would silently poison every
later mean.

Stdlib-only by design: ``core.metrics`` and ``comm.backend`` import this
module at top level, so it must not import jax, numpy, or anything from
``fedml_tpu``.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


def metric_key(name: str, labels: Optional[Dict[str, Any]] = None) -> str:
    """``name{k=v,...}`` with sorted labels (stable across call sites)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_metric_key(key: str):
    """Inverse of ``metric_key``: ``(name, labels dict)``."""
    if not key.endswith("}") or "{" not in key:
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for part in inner[:-1].split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


class Histogram:
    """Log2-bucketed histogram: O(1) memory per decade of dynamic range."""

    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[float, int] = {}  # upper bound -> count

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v) or math.isinf(v):
            raise ValueError(f"histogram rejects non-finite observation: {v}")
        if v < 0:
            raise ValueError(f"histogram rejects negative observation: {v}")
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        le = 0.0 if v == 0.0 else 2.0 ** math.ceil(math.log2(v))
        self.buckets[le] = self.buckets.get(le, 0) + 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "mean": self.mean,
            # string keys: JSON objects can't have float keys
            "buckets": {repr(le): n for le, n in sorted(self.buckets.items())},
        }


class Telemetry:
    """Thread-safe registry (comm backends report from reader threads).

    The lock stays a plain ``threading.Lock`` (this module is stdlib-
    only and import-leaf by contract — see the module docstring — so it
    cannot use ``analysis.locks.make_lock``); the fedlint
    lock-discipline rule still enforces the ``_GUARDED_BY`` contract
    statically, and the lock is leaf-level (never held across another
    acquire), so it cannot participate in an order cycle."""

    _GUARDED_BY = {
        "counters": "_lock",
        "gauges": "_lock",
        "hists": "_lock",
        "_events": "_lock",
    }

    def __init__(self, max_events: int = 4096):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.hists: Dict[str, Histogram] = {}
        self._events: deque = deque(maxlen=max_events)
        # taps: single callbacks invoked OUTSIDE the lock after an
        # event/observation lands — the flight recorder's feed
        # (``obs/flight.py``).  Plain attribute swap (atomic ref), never
        # guarded: readers see either the old tap or the new one.
        self._event_tap = None
        self._observe_tap = None

    # -- taps ---------------------------------------------------------------
    def set_event_tap(self, fn) -> None:
        """Install the single event tap (``fn(record_dict)``), called
        after every ``event()`` append, outside the registry lock.  A
        tap exception is swallowed — observation must never break the
        emitter.  ``None`` uninstalls."""
        self._event_tap = fn

    def set_observe_tap(self, fn) -> None:
        """Install the single histogram tap (``fn(name, value, labels)``),
        called after every accepted ``observe()``.  Same contract as the
        event tap: outside the lock, exceptions swallowed."""
        self._observe_tap = fn

    # -- counters -----------------------------------------------------------
    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = metric_key(name, labels)
        with self._lock:
            self.counters[key] = self.counters.get(key, 0.0) + value

    # -- gauges -------------------------------------------------------------
    def gauge_set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self.gauges[metric_key(name, labels)] = float(value)

    def gauge_max(self, name: str, value: float, **labels) -> None:
        """High-water gauge: keeps the max ever seen (device peak bytes)."""
        key = metric_key(name, labels)
        with self._lock:
            self.gauges[key] = max(self.gauges.get(key, -math.inf), float(value))

    # -- histograms ---------------------------------------------------------
    def observe(self, name: str, value: float, **labels) -> None:
        key = metric_key(name, labels)
        with self._lock:
            h = self.hists.get(key)
            if h is None:
                h = self.hists[key] = Histogram()
            h.observe(value)
        tap = self._observe_tap
        if tap is not None:
            try:
                tap(name, value, labels)
            except Exception:
                pass  # the tap must never break the emitter

    # -- events -------------------------------------------------------------
    def event(self, kind: str, **fields) -> dict:
        """Append a timestamped event (compile, trace, ...) to the bounded
        ring; ``MetricsLogger.log_telemetry`` drains these into the
        metrics.jsonl record stream."""
        rec = {"kind": kind, "ts": time.time(), **fields}
        with self._lock:
            self._events.append(rec)
        tap = self._event_tap
        if tap is not None:
            try:
                tap(rec)
            except Exception:
                pass  # the tap must never break the emitter
        return rec

    def drain_events(self) -> List[dict]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    # -- export -------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSONL-able point-in-time copy of every series."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "hists": {k: h.snapshot() for k, h in self.hists.items()},
            }

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self.counters.get(metric_key(name, labels), 0.0)

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.hists.clear()
            self._events.clear()


_GLOBAL = Telemetry()


def get_telemetry() -> Telemetry:
    """The process-wide registry (one per process, like the root logger)."""
    return _GLOBAL
