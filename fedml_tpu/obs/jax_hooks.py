"""JAX runtime hooks: compile tracking, memory gauges, profiler traces.

Three observability gaps this closes (ISSUE 1):

- **Recompile storms are invisible.**  ``instrument_jit`` wraps a jitted
  callable and tracks the abstract signature (treedef + shape/dtype per
  leaf, python scalars by weak type) of every call; the first call under
  a new signature is counted as a compile event with its wall seconds.
  A round driver that accidentally varies a shape per round shows up as
  ``jax.compiles{fn=round_fn}`` climbing with the round index instead of
  sitting at 1-2.  All bookkeeping is host-side dict lookups — nothing
  is added inside the traced function.
- **Device memory pressure is invisible.**  ``record_device_memory``
  snapshots ``Device.memory_stats()`` (None-guarded: CPU backends may
  not implement it) into high-water gauges.
- **Profiler bracketing is manual.**  ``trace_rounds`` wraps N fully
  synced rounds (``utils/timing.sync_round`` — block AND scalar
  readback, the axon-tunnel lesson) in a ``jax.profiler`` trace.

``install_jax_monitoring`` additionally subscribes to
``jax.monitoring`` duration events (event names containing "compile"),
which yields the backend's OWN compile seconds where available —
``instrument_jit``'s triggering-call wall time is an upper bound that
includes dispatch.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

from fedml_tpu.obs.telemetry import Telemetry, get_telemetry

_MONITORING_INSTALLED = False


def abstract_signature(args, kwargs=None) -> Optional[Tuple]:
    """Hashable jit-specialization key: treedef + per-leaf (shape, dtype)
    for arrays; python scalars by TYPE only — jit weak-types a plain
    int/float to one dtype regardless of value, so keying on the value
    would report a fake compile on every varying-scalar call (the exact
    false recompile-storm this layer exists to detect)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        elif isinstance(leaf, (bool, int, float, complex)):
            sig.append((type(leaf).__name__,))
        else:
            try:
                hash(leaf)
            except TypeError:
                return None  # unhashable static leaf: skip tracking this call
            sig.append((type(leaf).__name__, leaf))
    return (treedef, tuple(sig))


def instrument_jit(fn: Callable, name: str,
                   telemetry: Optional[Telemetry] = None) -> Callable:
    """Wrap a jitted callable with host-side compile-event tracking.

    Per NEW signature: ``jax.compiles{fn=name}`` += 1, the triggering
    call's wall seconds land in ``jax.compile_s{fn=name}`` and a
    ``compile`` event (drained into metrics.jsonl by
    ``MetricsLogger.log_telemetry``).  Warm calls pay one tree_flatten +
    dict probe (~µs) — never anything inside the traced code.
    """
    seen: dict = {}

    def wrapped(*args, **kwargs):
        t = telemetry or get_telemetry()
        sig = abstract_signature(args, kwargs)
        if sig is None or sig in seen:
            return fn(*args, **kwargs)
        seen[sig] = len(seen)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        t.inc("jax.compiles", 1, fn=name)
        t.observe("jax.compile_s", dt, fn=name)
        t.event("compile", fn=name, signature=seen[sig],
                n_signatures=len(seen), seconds=round(dt, 6))
        return out

    wrapped.__name__ = f"instrumented[{name}]"
    wrapped.__wrapped__ = fn
    return wrapped


def install_jax_monitoring(telemetry: Optional[Telemetry] = None) -> bool:
    """Subscribe compile-duration events from ``jax.monitoring`` into the
    registry (idempotent; returns False if the API is unavailable)."""
    global _MONITORING_INSTALLED
    if _MONITORING_INSTALLED:
        return True
    try:
        from jax import monitoring

        def _on_duration(event, duration, **kw):
            if "compile" not in event:
                return
            t = telemetry or get_telemetry()
            t.inc("jax.backend_compile_events", 1, event=event)
            try:
                t.observe("jax.backend_compile_s", float(duration), event=event)
            except ValueError:
                pass  # non-finite duration from the runtime: drop, don't raise

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _MONITORING_INSTALLED = True
    return True


def record_device_memory(telemetry: Optional[Telemetry] = None) -> dict:
    """Snapshot per-device memory into gauges; returns {device: stats}.

    ``jax.device_mem_peak_bytes{device=...}`` is a high-water gauge
    (max over all snapshots this process); ``jax.device_mem_bytes`` is
    the point-in-time residency.  Backends without ``memory_stats``
    (CPU) contribute nothing.
    """
    import jax

    t = telemetry or get_telemetry()
    out = {}
    for d in jax.local_devices():
        stats_fn = getattr(d, "memory_stats", None)
        stats = stats_fn() if callable(stats_fn) else None
        if not stats:
            continue
        out[d.id] = stats
        peak = stats.get("peak_bytes_in_use")
        in_use = stats.get("bytes_in_use")
        if peak is not None:
            t.gauge_max("jax.device_mem_peak_bytes", peak, device=d.id)
        if in_use is not None:
            t.gauge_set("jax.device_mem_bytes", in_use, device=d.id)
    return out


def trace_rounds(
    round_fn: Callable,
    state: Any,
    args: Tuple,
    rounds: int = 2,
    *,
    log_dir: Optional[str] = None,
    logger=None,
    telemetry: Optional[Telemetry] = None,
) -> Tuple[Any, list]:
    """Bracket N fully-synced rounds in a ``jax.profiler`` trace.

    Every round is synced with ``utils/timing.sync_round`` (block AND
    scalar readback — ``block_until_ready`` alone can return early on
    the axon tunnel), so the trace spans real device work, not enqueues.
    ``log_dir`` defaults through ``core.metrics.trace`` to the logger's
    ``run_dir``; the trace path and per-round seconds are logged into
    the metrics stream.  Returns ``(final_state, per_round_seconds)``.
    """
    from fedml_tpu.core.metrics import trace
    from fedml_tpu.utils.timing import sync_round

    t = telemetry or get_telemetry()
    times = []
    with trace(log_dir, logger=logger) as tdir:
        for _ in range(rounds):
            t0 = time.perf_counter()
            state, metrics = round_fn(state, *args)
            sync_round(state, metrics)
            dt = time.perf_counter() - t0
            times.append(dt)
            t.observe("span.traced_round_s", dt)
    # exactly one trace_rounds record: straight into the logger's stream
    # when one is given, else into the event ring for a later
    # log_telemetry drain (both would double it up)
    rec = {"trace_dir": tdir, "rounds": rounds,
           "round_s": [round(x, 6) for x in times]}
    if logger is not None:
        logger.log({"kind": "trace_rounds", **rec})
    else:
        t.event("trace_rounds", **rec)
    return state, times
