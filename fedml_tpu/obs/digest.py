"""Mergeable telemetry digests — the in-band stats plane's algebra.

Every observability surface before this module was post-hoc: per-process
``metrics-*.jsonl`` files merged after the run by ``tools/fed_timeline``.
That model is O(events x clients) on disk and invisible until the run
ends — exactly wrong for the 100k-1M-virtual-client direction.  This
module makes a ``Telemetry`` registry SHIPPABLE while the run is live:

- a **digest** is a plain JSON-able dict capturing counter deltas,
  last-write gauges, and the log2-histogram buckets of one registry over
  one report interval, plus a per-source ``(seq, t)`` liveness stamp;
- ``merge`` is **associative and order-insensitive** (counters and
  histogram buckets add, gauges and source stamps last-write-win by a
  total order), so muxer-side pre-merge, the hub/server rollup, and a
  future edge-hub tier all compose EXACTLY — the same argument as the
  streaming aggregation's num/den fold;
- ``serialize`` is canonical (sorted keys, minimal separators): equal
  digests are byte-identical, which is what lets tests pin
  muxer-pre-merged == flat per-client merge the way PR 10 pinned
  muxed-vs-per-process uploads.

Cost model: one digest frame per report interval per CONNECTION — a
muxer's 2500 virtual clients share one process registry and therefore
one digest stream, so 10k virtual clients cost the hub 4 digest streams,
not 10k.

Stdlib-only at import time by design (mirrors ``obs/telemetry.py``): the
rollup runs inside the server process, but ``tools/fed_slo.py`` and the
hub must be able to read digests without jax; ``comm`` imports are lazy
(inside the send path only).
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from typing import Dict, List, Optional

from fedml_tpu.obs.telemetry import Telemetry, get_telemetry

# Reserved frame key carrying the digest payload on a C2S_TELEMETRY
# frame (fedlint wire-schema rule: this is the single canonical
# definition; every other module must reference the constant).
DIGEST_KEY = "__digest__"

DIGEST_VERSION = 1

# a reporter stream with no frame for this many seconds is STALE by
# default (the SLO engine's telemetry-coverage objective); the server
# resolves the effective threshold as max(this, 5 x report interval) —
# a long interval must not flag live streams stale between frames
DEFAULT_STALE_AFTER_S = 10.0


# --- digest algebra ----------------------------------------------------------


def empty_digest() -> dict:
    """The merge identity: ``merge(d, empty_digest()) == d``."""
    return {
        "v": DIGEST_VERSION,
        "counters": {},
        "gauges": {},   # key -> [t, value]  (last-write-wins by (t, value))
        "hists": {},    # key -> {count, sum, min, max, buckets{le: n}}
        "nodes": [],    # node ids this digest's sources cover
        "sources": {},  # str(origin node) -> {"seq": int, "t": wall}
    }


def _merge_hist(a: Optional[dict], b: dict) -> dict:
    if a is None:
        return {
            "count": b.get("count", 0),
            "sum": b.get("sum", 0.0),
            "min": b.get("min"),
            "max": b.get("max"),
            "buckets": dict(b.get("buckets") or {}),
        }
    buckets = dict(a.get("buckets") or {})
    for le, n in (b.get("buckets") or {}).items():
        buckets[le] = buckets.get(le, 0) + n
    mins = [m for m in (a.get("min"), b.get("min")) if m is not None]
    maxs = [m for m in (a.get("max"), b.get("max")) if m is not None]
    return {
        "count": a.get("count", 0) + b.get("count", 0),
        "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "buckets": buckets,
    }


def merge(a: dict, b: dict) -> dict:
    """Combine two digests into a new one (inputs untouched).

    Associative and commutative by construction: counters and histogram
    buckets ADD (integer-exact for counts; float sums associate to ulp),
    gauges and source stamps keep the entry with the larger ``(t,
    value)`` / ``(seq, t)`` tuple — a TOTAL order, so ties at equal
    stamps still resolve identically whatever the merge order.  This is
    what lets muxer pre-merge, hub rollup, and an edge-hub tier compose
    without caring who folded first.

    ONE implementation of the algebra: this is ``merge_into`` twice
    into a fresh identity plus the canonical-``nodes`` normalization —
    the pure form and the rollup's in-place hot path cannot drift.
    """
    out = merge_into(merge_into(empty_digest(), a), b)
    out["nodes"] = sorted(out["nodes"])
    return out


def merge_all(digests) -> dict:
    out = empty_digest()
    for d in digests:
        out = merge(out, d)
    return out


def merge_into(acc: dict, b: dict) -> dict:
    """In-place fold of ``b`` into ``acc`` — the rollup's hot path.

    Same algebra as ``merge`` but O(frame) instead of O(accumulated
    series): at one digest per connection per second, rebuilding the
    whole accumulator per frame would make the reader thread's cost
    grow with run length × fleet size.  ``acc`` is mutated (its
    ``nodes`` may become a set for O(1) union); ``merge``/``snapshot``
    handle either form, so callers that need a frozen copy take one
    via ``merge(acc, empty_digest())``."""
    c = acc["counters"]
    for k, v in (b.get("counters") or {}).items():
        c[k] = c.get(k, 0.0) + v
    g = acc["gauges"]
    for k, tv in (b.get("gauges") or {}).items():
        have = g.get(k)
        if have is None or tuple(tv) > tuple(have):
            g[k] = list(tv)
    hists = acc["hists"]
    for k, h in (b.get("hists") or {}).items():
        hists[k] = _merge_hist(hists.get(k), h)
    if not isinstance(acc.get("nodes"), set):
        acc["nodes"] = set(acc.get("nodes") or ())
    acc["nodes"].update(int(n) for n in (b.get("nodes") or ()))
    srcs = acc["sources"]
    for k, st in (b.get("sources") or {}).items():
        have = srcs.get(k)
        if have is None or ((st.get("seq", 0), st.get("t", 0.0))
                            > (have.get("seq", 0), have.get("t", 0.0))):
            srcs[k] = dict(st)
    return acc


def serialize(digest: dict) -> bytes:
    """Canonical wire form: sorted keys + minimal separators, so equal
    digests are byte-identical (the reproducibility pin tests compare
    these bytes, not dict equality)."""
    return json.dumps(digest, sort_keys=True,
                      separators=(",", ":")).encode()


def deserialize(data) -> dict:
    if isinstance(data, (bytes, bytearray, memoryview)):
        data = bytes(data).decode()
    return json.loads(data)


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def validate(digest) -> None:
    """Structural + finiteness check; raises ``ValueError`` on anything
    a merge could be poisoned by.  A NaN counter folded into the rollup
    would silently corrupt every later snapshot — corrupted digest
    frames must die HERE, counted, never wedge the rollup."""
    if not isinstance(digest, dict):
        raise ValueError(f"digest is not a dict: {type(digest).__name__}")
    if digest.get("v") != DIGEST_VERSION:
        raise ValueError(f"unsupported digest version: {digest.get('v')!r}")
    for k, v in (digest.get("counters") or {}).items():
        if not _finite(v):
            raise ValueError(f"non-finite counter {k!r}: {v!r}")
    for k, tv in (digest.get("gauges") or {}).items():
        if (not isinstance(tv, (list, tuple)) or len(tv) != 2
                or not _finite(tv[0]) or not _finite(tv[1])):
            raise ValueError(f"malformed gauge {k!r}: {tv!r}")
    for k, h in (digest.get("hists") or {}).items():
        if not isinstance(h, dict) or not _finite(h.get("count", 0)) \
                or not _finite(h.get("sum", 0.0)):
            raise ValueError(f"malformed histogram {k!r}")
        for le, n in (h.get("buckets") or {}).items():
            # bucket bounds are repr'd floats — and must be FINITE,
            # non-negative: a 'nan' bound merges fine and then poisons
            # every quantile downstream ('nan > threshold' is False, so
            # the gate silently stops gating)
            if not math.isfinite(float(le)) or float(le) < 0:
                raise ValueError(f"bad bucket bound {k!r}[{le!r}]")
            if not _finite(n) or n < 0:
                raise ValueError(f"bad bucket count {k!r}[{le!r}]: {n!r}")
    for k, st in (digest.get("sources") or {}).items():
        if not isinstance(st, dict) or not _finite(st.get("seq", 0)) \
                or not _finite(st.get("t", 0.0)):
            raise ValueError(f"malformed source stamp {k!r}: {st!r}")


def _hist_snapshot_digestable(h: dict) -> dict:
    """Registry ``Histogram.snapshot()`` -> digest hist form (drop the
    derived mean; keep the cumulative min/max — deltas can't know the
    interval's own extrema, and carrying the cumulative ones still
    merges to the correct global extrema)."""
    return {
        "count": h["count"],
        "sum": h["sum"],
        "min": h["min"],
        "max": h["max"],
        "buckets": dict(h["buckets"]),
    }


def registry_digest(telemetry: Optional[Telemetry] = None, *,
                    node: Optional[int] = None, nodes=None,
                    seq: int = 0, t: Optional[float] = None) -> dict:
    """Full snapshot of a registry as a digest (no delta) — what a
    fresh reporter's first frame carries, and the test fixture for the
    algebra pins."""
    snap = (telemetry or get_telemetry()).snapshot()
    if t is None:
        t = time.time()
    d = empty_digest()
    d["counters"] = {k: v for k, v in snap["counters"].items() if v}
    d["gauges"] = {k: [t, v] for k, v in snap["gauges"].items()}
    d["hists"] = {k: _hist_snapshot_digestable(h)
                  for k, h in snap["hists"].items() if h["count"]}
    if node is not None:
        d["sources"] = {str(int(node)): {"seq": int(seq), "t": t}}
    d["nodes"] = sorted(int(n) for n in (nodes or ()))
    if node is not None and int(node) not in d["nodes"]:
        d["nodes"] = sorted(d["nodes"] + [int(node)])
    return d


class DigestSource:
    """Delta emitter over one registry: each ``next()`` returns a digest
    of everything observed since the previous call (counter deltas,
    histogram bucket deltas, changed gauges) stamped with this source's
    monotonically-increasing ``seq`` — so merging every emitted delta
    reconstructs the full registry state, and the rollup can detect
    duplicated / lost frames per source.
    """

    _GUARDED_BY = {
        "_prev_counters": "_lock",
        "_prev_hists": "_lock",
        "_prev_gauges": "_lock",
        "_seq": "_lock",
    }

    def __init__(self, node: int, *, nodes=None,
                 telemetry: Optional[Telemetry] = None):
        self.node = int(node)
        self.nodes = sorted(int(n) for n in (nodes or (node,)))
        self.telemetry = telemetry or get_telemetry()
        # plain threading.Lock on purpose: this module is import-leaf
        # for tools (same stance as obs/telemetry.py); the fedlint
        # lock-discipline rule still checks the _GUARDED_BY contract
        self._lock = threading.Lock()
        self._prev_counters: Dict[str, float] = {}
        self._prev_hists: Dict[str, dict] = {}
        self._prev_gauges: Dict[str, float] = {}
        self._seq = 0

    def next(self, t: Optional[float] = None) -> dict:
        """The delta digest since the last call (always emitted, even
        when empty — the frame IS the liveness heartbeat)."""
        if t is None:
            t = time.time()
        d = empty_digest()
        d["nodes"] = list(self.nodes)
        with self._lock:
            # snapshot INSIDE the lock: two concurrent next() calls
            # must not diff an older snapshot against a newer prev
            # state (negative / duplicated deltas); the telemetry lock
            # nests leaf-level under this one
            snap = self.telemetry.snapshot()
            self._seq += 1
            d["sources"] = {str(self.node): {"seq": self._seq, "t": t}}
            for k, v in snap["counters"].items():
                dv = v - self._prev_counters.get(k, 0.0)
                if dv:
                    d["counters"][k] = dv
            self._prev_counters = dict(snap["counters"])
            for k, v in snap["gauges"].items():
                if self._prev_gauges.get(k) != v:
                    d["gauges"][k] = [t, v]
            self._prev_gauges = dict(snap["gauges"])
            for k, h in snap["hists"].items():
                prev = self._prev_hists.get(k)
                if prev is not None and prev["count"] == h["count"]:
                    continue
                buckets = dict(h["buckets"])
                if prev is not None:
                    for le, n in prev["buckets"].items():
                        left = buckets.get(le, 0) - n
                        if left:
                            buckets[le] = left
                        else:
                            buckets.pop(le, None)
                d["hists"][k] = {
                    "count": h["count"] - (prev["count"] if prev else 0),
                    "sum": h["sum"] - (prev["sum"] if prev else 0.0),
                    # cumulative extrema (see _hist_snapshot_digestable)
                    "min": h["min"],
                    "max": h["max"],
                    "buckets": buckets,
                }
            self._prev_hists = {k: _hist_snapshot_digestable(h)
                                for k, h in snap["hists"].items()}
        return d


class DigestRollup:
    """Server-side accumulator: ingests digest frames, tracks
    per-source liveness, and never lets a bad frame past validation.

    Ingest contract (the chaos ``telemetry_loss`` scenario's promise):
    a dropped frame just ages its source toward staleness; a corrupted
    or garbled frame is rejected + counted; a duplicated/stale frame
    (per-source ``seq`` not advancing) is skipped + counted.  Nothing a
    peer sends can raise out of ``ingest`` or corrupt the accumulator.
    """

    _GUARDED_BY = {
        "_acc": "_lock",
        "_seen": "_lock",
        "frames": "_lock",
        "rejected": "_lock",
        "duplicates": "_lock",
    }

    def __init__(self, telemetry: Optional[Telemetry] = None):
        self.telemetry = telemetry or get_telemetry()
        self._lock = threading.Lock()  # see DigestSource note
        self._acc = empty_digest()
        # str(source) -> {"seq", "t", "nodes", "frames", "lost"}
        self._seen: Dict[str, dict] = {}
        self.frames = 0
        self.rejected = 0
        self.duplicates = 0

    def ingest(self, digest, t: Optional[float] = None) -> bool:
        """Merge one digest frame; returns False when it was rejected
        or skipped (counted either way, never raised)."""
        if t is None:
            t = time.time()
        try:
            if isinstance(digest, (bytes, bytearray, memoryview, str)):
                digest = deserialize(digest)
            validate(digest)
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            with self._lock:
                self.rejected += 1
            self.telemetry.inc("digest.rejected", reason="malformed")
            logging.warning("digest rollup: rejected frame (%s)", e)
            return False
        sources = digest.get("sources") or {}
        with self._lock:
            for src, st in sources.items():
                have = self._seen.get(src)
                if have is not None and st.get("seq", 0) <= have["seq"]:
                    # per-source seq did not advance: a chaos duplicate
                    # or an out-of-order redelivery — merging it would
                    # double-add its counters
                    self.duplicates += 1
                    dup = True
                    break
            else:
                dup = False
            if not dup:
                for src, st in sources.items():
                    have = self._seen.get(src)
                    lost = 0
                    if have is not None:
                        lost = have["lost"] + max(
                            0, int(st.get("seq", 0)) - have["seq"] - 1)
                    elif st.get("seq", 0) > 1:
                        lost = int(st.get("seq", 0)) - 1
                    self._seen[src] = {
                        "seq": int(st.get("seq", 0)),
                        "t": float(st.get("t", t)),
                        "t_ingest": t,
                        "nodes": list(digest.get("nodes") or ()),
                        "frames": (have["frames"] + 1) if have else 1,
                        "lost": lost,
                    }
                merge_into(self._acc, digest)
                self.frames += 1
                nstreams = len(self._seen)
        if dup:
            self.telemetry.inc("digest.dup_frames")
            return False
        self.telemetry.inc("digest.frames")
        self.telemetry.gauge_set("digest.streams", nstreams)
        return True

    def snapshot(self) -> dict:
        """The merged digest (a fresh copy — callers may mutate)."""
        with self._lock:
            return merge(self._acc, empty_digest())

    def sources(self, now: Optional[float] = None,
                stale_after: float = DEFAULT_STALE_AFTER_S) -> dict:
        """Per-stream liveness: ``{source: {seq, age_s, stale, nodes,
        frames, lost}}`` — what the SLO report's telemetry-coverage
        objective and the live status view read."""
        if now is None:
            now = time.time()
        out: Dict[str, dict] = {}
        with self._lock:
            seen = {k: dict(v) for k, v in self._seen.items()}
        for src, st in seen.items():
            age = max(0.0, now - st["t_ingest"])
            out[src] = {
                "seq": st["seq"],
                "age_s": round(age, 3),
                "stale": age > stale_after,
                "nodes": len(st["nodes"]),
                "frames": st["frames"],
                "lost_frames": st["lost"],
            }
        return out

    def covered_nodes(self) -> List[int]:
        with self._lock:
            return sorted(int(n) for n in (self._acc.get("nodes") or ()))

    def stats(self) -> dict:
        with self._lock:
            return {
                "frames": self.frames,
                "rejected": self.rejected,
                "duplicates": self.duplicates,
                "streams": len(self._seen),
            }


def send_digest(backend, digest: dict, server: int = 0) -> None:
    """Ship one digest frame to the server over the existing comm plane
    (a plain ``C2S_TELEMETRY`` message — it rides ChaosBackend, the hub,
    and muxed connections like every other frame)."""
    from fedml_tpu.comm.message import MSG_TYPE_C2S_TELEMETRY, Message

    m = Message(MSG_TYPE_C2S_TELEMETRY, backend.node_id, server)
    m.add_params(DIGEST_KEY, digest)
    backend.send_message(m)


class DigestReporter:
    """Client/muxer-side reporter thread: every ``interval`` seconds,
    emit this process registry's delta digest to the server.  A muxer's
    single reporter IS the pre-merge: all its virtual clients share the
    process registry, so one frame per interval covers the whole
    co-located cohort (``nodes``) — the hub ingests one stream per
    connection, not per client.

    Best-effort by contract: a send that fails (hub mid-restart, socket
    mid-reconnect) is logged and the delta is RE-FOLDED into the next
    frame (the source keeps cumulative state, so nothing is lost — the
    next successful frame carries the catch-up delta).
    """

    def __init__(self, backend, *, server: int = 0, interval: float = 1.0,
                 nodes=None, telemetry: Optional[Telemetry] = None):
        self.backend = backend
        self.server = int(server)
        self.interval = max(0.05, float(interval))
        self.telemetry = telemetry or get_telemetry()
        self.source = DigestSource(backend.node_id, nodes=nodes,
                                   telemetry=self.telemetry)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # delta consumed from the source but not yet delivered (a send
        # that failed): merged into the next frame so no interval's
        # counters ever vanish from the rollup — the seq gap the rollup
        # reports for the failed frame is honest (THAT frame never
        # arrived; its data rides the catch-up one)
        self._backlog: Optional[dict] = None

    def _tick(self) -> None:
        digest = self.source.next()
        if self._backlog is not None:
            digest = merge(self._backlog, digest)
        try:
            send_digest(self.backend, digest, self.server)
            self._backlog = None
            self.telemetry.inc("digest.sent")
        except Exception:
            # telemetry is best-effort: a lost digest frame must never
            # take the round path down with it.  The consumed delta is
            # kept as backlog — the next successful frame carries the
            # catch-up; only the liveness heartbeat is late.
            self._backlog = digest
            logging.debug("digest reporter: send failed (will retry "
                          "with the next interval)", exc_info=True)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._tick()

    def start(self) -> "DigestReporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"digest-reporter-{self.backend.node_id}",
            )
            self._thread.start()
        return self

    def stop(self, final_flush: bool = True) -> None:
        """Idempotent; with ``final_flush`` a last delta frame is sent
        so the rollup sees everything up to FINISH.  The flush only
        runs once the reporter thread has actually exited — a thread
        wedged inside a blocking send past the join budget must not
        race a second ``_tick`` over the unsynchronized backlog."""
        if self._stop.is_set():
            return
        self._stop.set()
        joined = True
        if self._thread is not None:
            self._thread.join(timeout=5)
            joined = not self._thread.is_alive()
        if final_flush and joined:
            self._tick()
