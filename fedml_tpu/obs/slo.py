"""Federation SLO engine — declarative objectives evaluated live.

ROADMAP item 2 asks for "SLO-style p99 round latency ... under load";
until this module nothing in the repo computed, declared, or gated an
objective while the federation was RUNNING.  The engine closes that
gap on top of the in-band stats plane (``obs/digest.py``):

- ``SloSpec`` is the declarative objective set (JSON / dataclass):
  p50/p99 round wall, bytes per round, participation, stale/corrupt
  upload budgets, degraded-round budget, telemetry-coverage budget;
- ``SloEngine`` is evaluated once per closed round against the merged
  rollup: percentiles come from the merged **log2 histograms**
  (``hist_quantile`` — bucket upper bound, the same estimator family
  ``tools/trace_summary`` uses), violations emit ``slo_violation``
  events + ``slo.violations{objective=}`` counters, and the final
  machine-readable ``slo_report.json`` lands in run_dir;
- ``build_status``/``write_json_atomic`` produce the live
  ``status.json`` snapshot (rollup + SLO state + per-stream liveness)
  that ``tools/fed_slo.py`` renders — a killed or wedged run leaves
  evidence mid-flight instead of nothing.

Stdlib-only (same contract as ``obs/digest.py``): the tools read these
artifacts on a bare interpreter.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional

from fedml_tpu.obs.telemetry import Telemetry, get_telemetry
from fedml_tpu.obs import digest as digestlib


def hist_quantile(hist: Optional[dict], q: float) -> Optional[float]:
    """Quantile upper bound from a digest histogram's log2 buckets.

    Nearest-rank over bucket counts, answering with the bucket's upper
    bound — so the true quantile lies within ONE log2 bucket below the
    returned value (the acceptance contract the health campaign checks
    against ``fed_timeline``'s exact post-hoc numbers)."""
    if not hist:
        return None
    count = hist.get("count", 0)
    if not count:
        return None
    target = max(1, int(math.ceil(q * count)))
    acc = 0
    for le, n in sorted((float(k), v)
                        for k, v in (hist.get("buckets") or {}).items()):
        acc += n
        if acc >= target:
            return le
    return hist.get("max")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """Declarative federation objectives; ``None`` = not gated (the
    engine still REPORTS every observed number, so an empty spec is a
    pure health report).  Budgets are per run, percentile/participation
    objectives per evaluation."""

    p50_round_wall_s: Optional[float] = None   # slo.round_wall_s p50 <=
    p99_round_wall_s: Optional[float] = None   # slo.round_wall_s p99 <=
    round_bytes_p50: Optional[float] = None    # slo.round_bytes p50 <=
    min_participation: Optional[float] = None  # last round's arrived/target >=
    max_stale_uploads: Optional[int] = None    # cumulative stale rejects <=
    max_corrupt_uploads: Optional[int] = None  # cumulative corrupt rejects <=
    # robust-aggregation budget: outlier-score rejects (the streaming
    # defense's norm-space firewall) — a quiet ongoing attack then
    # shows up as an SLO VIOLATION, not just a counter someone has to
    # go looking for.  Counted per DELIVERED copy, like every
    # faults.observed kind (a chaos-duplicated hostile upload is two
    # hostile frames observed) — size the budget accordingly.
    max_outlier_uploads: Optional[int] = None  # cumulative outlier rejects <=
    max_degraded_rounds: Optional[int] = None  # cumulative degraded rounds <=
    max_stale_streams: Optional[int] = None    # silent/missing reporters <=
    # async buffered-round objectives (--round-mode async), evaluated at
    # each round CUT like everything else: the p99 of accepted uploads'
    # round gaps (how far behind the population is running), and the
    # fraction of arrived sample weight the staleness discount removed
    # (discarded / (discarded + folded) — a model-quality budget: high
    # discard means the cut cadence outruns the devices)
    p99_upload_staleness: Optional[float] = None   # async.upload_staleness p99 <=
    max_discarded_weight_frac: Optional[float] = None  # discarded weight frac <=
    # staleness threshold for reporter streams; None = derive it from
    # the report interval at engine construction (the server resolves
    # max(10 s, 5 x interval) — a 30 s interval must not flag every
    # live stream stale between frames)
    stale_after_s: Optional[float] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_obj(cls, obj: dict) -> "SloSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(obj) - known
        if unknown:
            raise ValueError(f"unknown SLO spec fields: {sorted(unknown)} "
                             f"(known: {sorted(known)})")
        for k, v in obj.items():
            # fail FAST on a non-numeric threshold (e.g. "5" from shell
            # templating): a TypeError inside evaluate() would be
            # swallowed by the round path's best-effort guard and the
            # final report would read ok=true with the gate silently dead
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))):
                raise ValueError(
                    f"SLO spec field {k!r} must be a number or null, "
                    f"got {v!r}")
        stale = obj.get("stale_after_s")
        if stale is not None and stale <= 0:
            raise ValueError(
                f"stale_after_s must be positive (or null = derived "
                f"from the report interval), got {stale!r}")
        return cls(**obj)

    @classmethod
    def from_arg(cls, arg: str) -> "SloSpec":
        """CLI form: inline JSON (``{"p99_round_wall_s": 5}``) or a
        path to a JSON file."""
        text = arg.strip()
        if not text.startswith("{") and os.path.exists(text):
            with open(text) as fh:
                text = fh.read()
        return cls.from_obj(json.loads(text))


class SloEngine:
    """Per-round objective evaluation over the merged rollup.

    ``observe_round`` feeds the two SLO histograms (round wall, bytes
    per round) into the LOCAL registry — they then travel the same
    digest plane as everything else, so an upstream tier evaluating the
    merged rollup computes the identical percentiles (the digest
    algebra's whole point).  ``evaluate`` compares spec thresholds to
    the rollup's current merged state and records violations.
    """

    _GUARDED_BY = {
        "violations": "_lock",
        "rounds_evaluated": "_lock",
        "_participation": "_lock",
    }

    def __init__(self, spec: Optional[SloSpec] = None,
                 telemetry: Optional[Telemetry] = None):
        spec = spec or SloSpec()
        if spec.stale_after_s is None:
            # unresolved staleness threshold: fall back to the module
            # default (entry points resolve it from the report interval
            # BEFORE constructing the engine) — inside the engine it is
            # always a concrete positive number
            spec = dataclasses.replace(
                spec, stale_after_s=digestlib.DEFAULT_STALE_AFTER_S)
        self.spec = spec
        self.telemetry = telemetry or get_telemetry()
        self._lock = threading.Lock()  # stdlib-leaf, see obs/digest.py
        self.violations: List[dict] = []
        self.rounds_evaluated = 0
        self._participation: List[float] = []
        # telemetry-coverage grace: a round that closes before the
        # first report interval could even elapse must not flag every
        # expected node missing (the spurious-violation class) — the
        # stale_streams objective arms only once the plane has been up
        # for one staleness threshold
        self._t_up = time.time()

    # -- per-round inputs ---------------------------------------------------
    def observe_round(self, round_idx: int, *, wall_s: float,
                      round_bytes: float, participants: int,
                      target: int) -> None:
        if wall_s >= 0 and math.isfinite(wall_s):
            self.telemetry.observe("slo.round_wall_s", wall_s)
        if round_bytes >= 0 and math.isfinite(round_bytes):
            self.telemetry.observe("slo.round_bytes", round_bytes)
        frac = participants / target if target else 1.0
        with self._lock:
            self._participation.append(frac)

    # -- evaluation ---------------------------------------------------------
    def _counter_sum(self, rollup_digest: dict, prefix: str) -> float:
        return sum(v for k, v in (rollup_digest.get("counters") or {}).items()
                   if k.startswith(prefix))

    def evaluate(self, round_idx: int, rollup_digest: dict,
                 sources: dict, expected_nodes=None) -> List[dict]:
        """One evaluation pass (called at each round close).  Returns
        the NEW violations; cumulative state rides the engine."""
        spec = self.spec
        hists = rollup_digest.get("hists") or {}
        found: List[dict] = []

        def check(objective: str, observed, threshold, *, at_most=True):
            if threshold is None or observed is None:
                return
            bad = observed > threshold if at_most else observed < threshold
            if bad:
                found.append({"round": round_idx, "objective": objective,
                              "observed": observed, "threshold": threshold})

        check("round_wall_p50",
              hist_quantile(hists.get("slo.round_wall_s"), 0.5),
              spec.p50_round_wall_s)
        check("round_wall_p99",
              hist_quantile(hists.get("slo.round_wall_s"), 0.99),
              spec.p99_round_wall_s)
        check("round_bytes_p50",
              hist_quantile(hists.get("slo.round_bytes"), 0.5),
              spec.round_bytes_p50)
        with self._lock:
            last_part = (self._participation[-1]
                         if self._participation else None)
        check("participation", last_part, spec.min_participation,
              at_most=False)
        check("stale_uploads",
              self._counter_sum(rollup_digest,
                                "faults.observed{kind=stale_upload"),
              spec.max_stale_uploads)
        check("corrupt_uploads",
              self._counter_sum(rollup_digest,
                                "faults.observed{kind=corrupt_upload"),
              spec.max_corrupt_uploads)
        check("outlier_uploads",
              self._counter_sum(rollup_digest,
                                "faults.observed{kind=outlier_upload"),
              spec.max_outlier_uploads)
        check("degraded_rounds",
              self._counter_sum(rollup_digest, "rounds.degraded"),
              spec.max_degraded_rounds)
        check("upload_staleness_p99",
              hist_quantile(hists.get("async.upload_staleness"), 0.99),
              spec.p99_upload_staleness)
        check("discarded_weight_frac",
              self._discarded_frac(rollup_digest),
              spec.max_discarded_weight_frac)
        stale, missing = self.coverage(rollup_digest, sources,
                                       expected_nodes)
        # silent streams AND never-covered nodes both count, each —
        # collapsing missing coverage to a boolean would let any
        # threshold >= 1 pass however many nodes went dark.  Armed only
        # after one staleness threshold of uptime: before the first
        # report interval has even elapsed, "everyone is missing" is
        # startup, not an outage.
        if time.time() - self._t_up >= self.spec.stale_after_s:
            check("stale_streams", len(stale) + len(missing),
                  spec.max_stale_streams)
        for v in found:
            self.telemetry.inc("slo.violations", objective=v["objective"])
            self.telemetry.event("slo_violation", **v)
            logging.warning(
                "SLO violation at round %s: %s observed=%s threshold=%s",
                round_idx, v["objective"], v["observed"], v["threshold"],
            )
        self.telemetry.inc("slo.evaluations")
        with self._lock:
            self.rounds_evaluated += 1
            self.violations.extend(found)
        if found:
            # black-box trigger: the evaluation that flagged the round
            # is the moment the evidence is still in the rings
            from fedml_tpu.obs import flight

            flight.trigger(
                "slo_violation", round_idx=round_idx,
                reason=",".join(v["objective"] for v in found),
            )
        return found

    def _discarded_frac(self, rollup_digest: dict) -> Optional[float]:
        """Fraction of arrived async sample weight the staleness
        discount removed: ``discarded / (discarded + folded)``.  None
        (not 0) when nothing has folded yet, so the objective cannot
        spuriously pass/fail before the first cut."""
        discarded = self._counter_sum(rollup_digest, "async.discarded_weight")
        folded = self._counter_sum(rollup_digest, "async.folded_weight")
        total = discarded + folded
        return discarded / total if total > 0 else None

    def coverage(self, rollup_digest: dict, sources: dict,
                 expected_nodes=None):
        """(stale stream ids, missing node ids): streams silent past the
        spec's staleness threshold, and expected nodes NO stream has
        ever covered — the ``telemetry_loss`` chaos scenario's flagging
        contract (counted + named, never silent)."""
        stale = sorted(s for s, st in (sources or {}).items()
                       if st.get("stale"))
        missing: List[int] = []
        if expected_nodes:
            covered = set(int(n)
                          for n in (rollup_digest.get("nodes") or ()))
            for s in (sources or {}):
                # a stream that reports at all covers at least its own
                # node id, even if its digests declare no nodes list
                try:
                    covered.add(int(s))
                except (TypeError, ValueError):
                    pass
            missing = sorted(int(n) for n in expected_nodes
                             if int(n) not in covered)
        return stale, missing

    def violation_state(self):
        """(total violations, last 10) under the engine's own lock —
        what the live status builder embeds."""
        with self._lock:
            return len(self.violations), list(self.violations[-10:])

    # -- artifacts ----------------------------------------------------------
    def report(self, rollup_digest: dict, sources: dict,
               expected_nodes=None, extra: Optional[dict] = None) -> dict:
        """The ``slo_report.json`` document (machine-readable verdict)."""
        hists = rollup_digest.get("hists") or {}
        wall = hists.get("slo.round_wall_s") or {}
        rbytes = hists.get("slo.round_bytes") or {}
        staleness = hists.get("async.upload_staleness") or {}
        stale, missing = self.coverage(rollup_digest, sources,
                                       expected_nodes)
        with self._lock:
            violations = list(self.violations)
            rounds_evaluated = self.rounds_evaluated
            participation = list(self._participation)
        by_objective: Dict[str, int] = {}
        for v in violations:
            by_objective[v["objective"]] = \
                by_objective.get(v["objective"], 0) + 1
        doc = {
            "v": 1,
            "spec": self.spec.to_dict(),
            "rounds_evaluated": rounds_evaluated,
            "ok": not violations,
            "violations_total": len(violations),
            "by_objective": by_objective,
            "violations": violations[:200],
            "observed": {
                "round_wall_s": {
                    "p50": hist_quantile(wall, 0.5),
                    "p99": hist_quantile(wall, 0.99),
                    "count": wall.get("count", 0),
                    "min": wall.get("min"),
                    "max": wall.get("max"),
                    "mean": (wall.get("sum", 0.0) / wall["count"]
                             if wall.get("count") else None),
                },
                "round_bytes": {
                    "p50": hist_quantile(rbytes, 0.5),
                    "p99": hist_quantile(rbytes, 0.99),
                    "count": rbytes.get("count", 0),
                    "max": rbytes.get("max"),
                },
                "participation": {
                    "last": participation[-1] if participation else None,
                    "min": min(participation) if participation else None,
                },
                "stale_uploads": self._counter_sum(
                    rollup_digest, "faults.observed{kind=stale_upload"),
                "corrupt_uploads": self._counter_sum(
                    rollup_digest, "faults.observed{kind=corrupt_upload"),
                "outlier_uploads": self._counter_sum(
                    rollup_digest, "faults.observed{kind=outlier_upload"),
                "degraded_rounds": self._counter_sum(
                    rollup_digest, "rounds.degraded"),
                "upload_staleness": {
                    "p99": hist_quantile(staleness, 0.99),
                    "count": staleness.get("count", 0),
                    "max": staleness.get("max"),
                },
                "discarded_weight_frac": self._discarded_frac(
                    rollup_digest),
            },
            "stats_plane": {
                "streams": len(sources or {}),
                "stale_streams": stale,
                "missing_nodes": missing[:50],
                "missing_nodes_total": len(missing),
                "nodes_covered": len(rollup_digest.get("nodes") or ()),
                "sources": sources,
            },
        }
        if extra:
            doc.update(extra)
        return doc


def write_json_atomic(path: str, obj: dict) -> None:
    """tmp-file + ``os.replace``: a reader (``fed_slo --watch``, a CI
    artifact grab mid-kill) never sees a torn document — the same
    atomicity contract as the checkpoint writer.  The tmp name comes
    from ``mkstemp`` (unique per CALL, not per process): the status
    thread and a round-close write can land concurrently, and a shared
    pid-keyed tmp would let one writer truncate the other's file
    mid-write."""
    import tempfile

    dirname = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.", dir=dirname)
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(obj, fh, indent=1, default=float)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def build_status(engine: SloEngine, rollup, *, round_idx: int,
                 rounds_total: int, expected_nodes=None,
                 finished: bool = False,
                 now: Optional[float] = None) -> dict:
    """The live ``status.json`` snapshot: merged rollup + SLO state +
    per-stream liveness.  Written atomically each report interval AND
    at every round close, so a killed or wedged run leaves a current
    picture behind mid-flight."""
    if now is None:
        now = time.time()
    snap = rollup.snapshot()
    sources = rollup.sources(now=now,
                             stale_after=engine.spec.stale_after_s)
    hists = snap.get("hists") or {}
    wall = hists.get("slo.round_wall_s") or {}
    stale, missing = engine.coverage(snap, sources, expected_nodes)
    violations_total, recent = engine.violation_state()
    return {
        "v": 1,
        "t": now,
        "finished": finished,
        "round": round_idx,
        "rounds_total": rounds_total,
        "slo": {
            "ok": violations_total == 0,
            "violations_total": violations_total,
            "recent_violations": recent,
        },
        "round_wall_s": {
            "p50": hist_quantile(wall, 0.5),
            "p99": hist_quantile(wall, 0.99),
            "count": wall.get("count", 0),
            "max": wall.get("max"),
        },
        "stats_plane": {
            **rollup.stats(),
            "stale_streams": stale,
            "missing_nodes_total": len(missing),
            "nodes_covered": len(snap.get("nodes") or ()),
        },
        "sources": sources,
        "rollup": {
            "counters": snap.get("counters") or {},
            "gauges": {k: v[1] for k, v in (snap.get("gauges") or {}).items()},
        },
    }
