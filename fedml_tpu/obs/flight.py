"""Flight recorder — the federation's black box.

ROADMAP regimes (100k–1M virtual clients, open-loop production traffic)
fail hours into unattended runs, long after the 4096-event telemetry
ring has evicted the evidence (PR 6's flusher thread exists precisely
because of that eviction — but the flusher needs a live metrics file
and a healthy process).  This module keeps the last seconds of
everything that matters in bounded per-category ring buffers inside
EVERY process — hub, server, clients, muxers — and dumps them
atomically to ``flight-<node>.json`` in the run_dir the moment
something goes wrong, so the postmortem (``tools/fed_forensics.py``)
works from what the process itself saw, with no re-run and no watcher.

Categories (ring per category, ``DEPTHS`` bounds memory):

- ``events`` — telemetry events (``round_close``, ``hub_stats``,
  ``degraded_round``, ``slo_violation``, ...), fed by the registry's
  event tap;
- ``hops`` — ``trace_hop`` per-message chains (same tap, own ring so a
  traced run cannot evict round boundaries);
- ``spans`` — every ``span.*_s`` histogram observation with its wall
  of occurrence (the registry's observe tap): per-arrival decode
  waits, fold stalls, round walls — the raw material for the
  forensics round diff;
- ``comm`` — per-frame send/recv metadata (msg_type, bytes) from
  ``obs/comm_obs.py``, covering every transport (tcp, shm lane, mux,
  inproc) since all of them report through that module;
- ``faults`` — chaos-layer decisions and injections
  (``faults/chaos.py``) plus tolerance-layer observations;
- ``locks`` — ``CheckedLock`` acquisitions (``analysis/locks.py``),
  populated only when lock checking is on;
- ``notes`` — anything else a subsystem wants on the record.

Recording is LOCK-LIGHT by design: the hot path is one
``deque.append`` of a small tuple (appends are atomic under the GIL —
no lock, no allocation beyond the tuple/dict).  Only ``dump()`` takes
a lock, and only against other dumpers; it snapshots rings with a
retry loop instead of freezing writers.

Dump triggers (each rate-limited per kind so a fault storm cannot
turn the recorder into the outage): SLO violation, round-deadline
overrun, non-finite/outlier upload reject, connection death, chaos
fault observed, unhandled exception (sys/threading excepthooks),
SIGUSR2 (operator-requested snapshot of a live process), and
``faulthandler``-style crash (enabled into
``faulthandler-<node>.log`` next to the bundle).  A process with no
configured run_dir still records — triggers then only mark history —
so library users pay nothing and lose nothing.

Bundle schema (v1) — see PROFILE.md's r16 appendix:

    {"schema": 1, "node": ..., "pid": ..., "window_s": ...,
     "trigger": {"kind", "reason", "round", "t_m", "t_wall"},
     "history": [trigger records, oldest first],
     "clock_sync": <the process's dial-time offset event or null>,
     "t_m_dump": ..., "t_wall_dump": ...,
     "telemetry": <full registry snapshot>,
     "rings": {category: [{"t_m", "kind", ...fields}, ...]}}

``clock_sync`` carries the same ``offset_s`` estimate
``tools/fed_timeline.py`` uses, so forensics can merge bundles from
every process onto the hub clock exactly like the timeline merges
metrics files.

Stdlib-only by contract: ``obs/comm_obs.py`` and ``analysis/locks.py``
feed this module, and both must keep importable on the lint CI's bare
interpreter.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from fedml_tpu.obs.telemetry import get_telemetry

SCHEMA = 1

ENV_DISABLE = "FEDML_TPU_FLIGHT"          # "0" switches recording off
ENV_WINDOW = "FEDML_TPU_FLIGHT_WINDOW_S"  # dump window override
ENV_LOCK_WAIT = "FEDML_TPU_FLIGHT_LOCK_WAIT_S"  # lock-ring wait threshold

DEFAULT_WINDOW_S = 60.0
DEFAULT_MIN_INTERVAL_S = 1.0  # per-trigger-kind dump rate limit
# lock acquires below this measured block time never reach the ring or
# the histogram: an uncontended CheckedLock acquire still takes ~1 us,
# and a 1024-deep ring of those evicts the contended rows forensics
# actually ranks.  1 ms keeps scheduler-quantum-scale contention and
# drops lock-free chatter.
DEFAULT_LOCK_WAIT_S = 1e-3

# ring depths: sized so the busiest category (per-frame comm metadata)
# holds several rounds of a large federation while the whole recorder
# stays a few MB of small tuples
DEPTHS = {
    "events": 2048,
    "hops": 2048,
    "spans": 4096,
    "comm": 4096,
    "faults": 2048,
    "locks": 1024,
    "notes": 512,
}

TRIGGERS = (
    "slo_violation", "deadline_overrun", "reject", "conn_death",
    "chaos_fault", "exception", "sigusr2", "crash", "manual",
)


def _snap_ring(ring: deque) -> list:
    """Copy a ring that other threads keep appending to.  ``list(deque)``
    raises RuntimeError if the deque mutates mid-iteration; retrying a
    few times is cheaper (and lock-free) than making every recording
    site take a lock for the rare dump."""
    for _ in range(8):
        try:
            return list(ring)
        except RuntimeError:
            continue
    return []


class FlightRecorder:
    """One per process (``get_recorder()``), always on unless
    ``FEDML_TPU_FLIGHT=0``.  ``record`` is the lock-free hot path;
    ``dump`` is the cold path that writes the bundle."""

    def __init__(self, window_s: Optional[float] = None,
                 depths: Optional[Dict[str, int]] = None):
        env_off = os.environ.get(ENV_DISABLE, "") == "0"
        self.enabled = not env_off
        if window_s is None:
            try:
                window_s = float(os.environ.get(ENV_WINDOW, DEFAULT_WINDOW_S))
            except ValueError:
                window_s = DEFAULT_WINDOW_S
        self.window_s = window_s
        try:
            self.lock_wait_s = float(
                os.environ.get(ENV_LOCK_WAIT, DEFAULT_LOCK_WAIT_S))
        except ValueError:
            self.lock_wait_s = DEFAULT_LOCK_WAIT_S
        d = dict(DEPTHS)
        d.update(depths or {})
        self._rings: Dict[str, deque] = {c: deque(maxlen=n)
                                         for c, n in d.items()}
        self.node: Optional[str] = None
        self.run_dir: Optional[str] = None
        self._clock_sync: Optional[dict] = None
        self._history: deque = deque(maxlen=128)  # trigger records
        self._last_dump: Dict[str, float] = {}    # trigger kind -> t_m
        self._dump_lock = threading.Lock()
        self._prev_excepthook = None
        self._prev_threading_hook = None
        self._faulthandler_fh = None
        self._atexit_installed = False

    # -- hot path -----------------------------------------------------------
    def record(self, category: str, kind: str, **fields) -> None:
        ring = self._rings.get(category)
        if ring is None:
            ring = self._rings.setdefault(category, deque(maxlen=512))
        ring.append((time.perf_counter(), kind, fields))

    # -- telemetry taps -----------------------------------------------------
    def _on_event(self, rec: dict) -> None:
        kind = rec.get("kind", "?")
        if kind == "clock_sync":
            # the one event forensics cannot live without: stamped at
            # dial time, far outside any last-seconds window — pin it
            # in its own slot so ring rotation can never evict it
            self._clock_sync = dict(rec)
            self.record("events", kind, **{k: v for k, v in rec.items()
                                           if k != "kind"})
            return
        ring = "hops" if kind == "trace_hop" else "events"
        self.record(ring, kind, **{k: v for k, v in rec.items()
                                   if k != "kind"})

    def _on_observe(self, name: str, value: float, labels: dict) -> None:
        if name.startswith("span."):
            if labels:
                self.record("spans", name, v=value, **labels)
            else:
                self.record("spans", name, v=value)

    def _on_lock(self, name: str, depth: int,
                 wait_s: float = 0.0) -> None:
        # ``wait_s`` is the CheckedLock tap's measured block time.  The
        # ring is a CONTENTION profile (fed_forensics ranks locks by
        # total/max wait), so only waits past the threshold
        # (``FEDML_TPU_FLIGHT_LOCK_WAIT_S``, default 1 ms) are kept —
        # uncontended acquires would evict the rows that matter.  Each
        # kept wait also lands in the ``lock.wait_s`` histogram so the
        # telemetry digest carries the contention shape even when no
        # dump ever fires.
        if wait_s < self.lock_wait_s:
            return
        self.record("locks", "acquire", lock=name, depth=depth,
                    wait_s=wait_s)
        try:
            get_telemetry().observe("lock.wait_s", wait_s, lock=name)
        except Exception:
            pass

    # -- configuration ------------------------------------------------------
    def configure(self, run_dir: Optional[str], node: str) -> None:
        """Point the recorder at its dump destination.  Tag convention
        follows the per-process metrics files: ``hub``, ``node0`` (the
        server), ``node<id>``, ``mux<id>``."""
        self.run_dir = run_dir
        self.node = str(node)

    def install_excepthooks(self) -> None:
        """Chain ``sys.excepthook`` + ``threading.excepthook`` so any
        unhandled exception dumps a bundle before the usual traceback."""
        if self._prev_excepthook is None:
            self._prev_excepthook = sys.excepthook

            def _hook(exc_type, exc, tb):
                self.dump("exception",
                          reason=f"{exc_type.__name__}: {exc}", force=True)
                self._prev_excepthook(exc_type, exc, tb)

            sys.excepthook = _hook
        if self._prev_threading_hook is None:
            self._prev_threading_hook = threading.excepthook

            def _thook(args):
                self.dump(
                    "exception", force=True,
                    reason=f"{args.exc_type.__name__}: {args.exc_value} "
                           f"(thread {getattr(args.thread, 'name', '?')})",
                )
                self._prev_threading_hook(args)

            threading.excepthook = _thook

    def install_signal_handlers(self) -> None:
        """SIGUSR2 → dump (operator snapshot of a live, healthy-looking
        process).  Main-thread only; silently skipped elsewhere."""
        import signal as _signal

        try:
            _signal.signal(
                _signal.SIGUSR2,
                lambda signum, frame: self.dump("sigusr2", force=True),
            )
        except (ValueError, OSError, AttributeError):
            pass  # not the main thread, or platform without SIGUSR2

    def enable_faulthandler(self) -> None:
        """Hard-crash evidence (segfault, deadlock dump via SIGABRT):
        ``faulthandler`` tracebacks into ``faulthandler-<node>.log``
        next to the bundle.  The recorder itself cannot run Python in a
        segfaulting process — this file is the crash half of the black
        box."""
        if self.run_dir is None or self._faulthandler_fh is not None:
            return
        import faulthandler

        try:
            path = os.path.join(self.run_dir,
                                f"faulthandler-{self.node or 'proc'}.log")
            self._faulthandler_fh = open(path, "w")
            faulthandler.enable(self._faulthandler_fh)
        except (OSError, ValueError):
            self._faulthandler_fh = None

    # -- dump ---------------------------------------------------------------
    def dump(self, trigger: str, reason: str = "",
             round_idx: Optional[int] = None, force: bool = False,
             min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
             **fields) -> Optional[str]:
        """Write the bundle.  Returns the path, or None when recording
        is off, no run_dir is configured (the trigger still lands in
        history), the per-kind rate limit suppressed it, or another
        dump is in flight (non-blocking acquire: a dump requested from
        a signal handler must never deadlock against the main thread's
        own dump)."""
        if not self.enabled:
            return None
        t_m = time.perf_counter()
        rec = {"kind": trigger, "reason": reason, "round": round_idx,
               "t_m": t_m, "t_wall": time.time(), **fields}
        self._history.append(rec)
        tel = get_telemetry()
        if self.run_dir is None:
            return None
        last = self._last_dump.get(trigger)
        if not force and last is not None and t_m - last < min_interval_s:
            tel.inc("flight.dumps_suppressed", trigger=trigger)
            return None
        if not self._dump_lock.acquire(blocking=False):
            tel.inc("flight.dumps_suppressed", trigger=trigger)
            return None
        try:
            self._last_dump[trigger] = t_m
            t0 = time.perf_counter()
            bundle = self._build_bundle(rec)
            path = os.path.join(self.run_dir, f"flight-{self.node}.json")
            fd, tmp = tempfile.mkstemp(dir=self.run_dir,
                                       prefix=".flight-", suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as fh:
                    json.dump(bundle, fh, separators=(",", ":"),
                              default=str)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            dt = time.perf_counter() - t0
        except Exception:
            tel.inc("flight.dump_errors")
            return None
        finally:
            self._dump_lock.release()
        tel.inc("flight.dumps", trigger=trigger)
        tel.observe("flight.dump_write_s", dt)
        tel.event("flight_dump", trigger=trigger, reason=reason,
                  round=round_idx, path=path, write_s=dt)
        return path

    def _build_bundle(self, trigger_rec: dict) -> dict:
        t_m = time.perf_counter()
        horizon = t_m - self.window_s
        rings: Dict[str, List[dict]] = {}
        for cat, ring in self._rings.items():
            rows = _snap_ring(ring)
            # dict(f, ...) second: a recording site's stray "t_m"/"kind"
            # field can never mask the row's own stamp and kind
            rings[cat] = [dict(f, t_m=t, kind=k)
                          for (t, k, f) in rows if t >= horizon]
        return {
            "schema": SCHEMA,
            "node": self.node,
            "pid": os.getpid(),
            "window_s": self.window_s,
            "trigger": trigger_rec,
            "history": list(self._history),
            "clock_sync": self._clock_sync,
            "t_m_dump": t_m,
            "t_wall_dump": time.time(),
            "telemetry": get_telemetry().snapshot(),
            "rings": rings,
        }


_GLOBAL = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process-wide recorder (one per process, like the telemetry
    registry)."""
    return _GLOBAL


def note(category: str, kind: str, **fields) -> None:
    """Module-level hot-path record on the process recorder — the form
    every instrumentation site uses (one attribute check when off)."""
    r = _GLOBAL
    if r.enabled:
        r.record(category, kind, **fields)


def trigger(kind: str, reason: str = "", round_idx: Optional[int] = None,
            **kw) -> Optional[str]:
    """Request a dump on the process recorder (rate-limited per kind)."""
    return _GLOBAL.dump(kind, reason=reason, round_idx=round_idx, **kw)


def install(run_dir: Optional[str], node: str,
            signals: bool = True) -> FlightRecorder:
    """Full per-process wiring, called by every federation entry point
    (``experiments/distributed_fedavg.py`` roles): dump destination,
    excepthooks, SIGUSR2, faulthandler, and the CheckedLock tap.  The
    telemetry taps are wired at import (below) so recording is always
    on even in library use."""
    r = _GLOBAL
    r.configure(run_dir, node)
    r.install_excepthooks()
    if signals:
        r.install_signal_handlers()
    r.enable_faulthandler()
    if run_dir and not r._atexit_installed:
        # final-state bundle on CLEAN exit too: a healthy run (or one
        # whose only anomaly never trips a trigger — every-frame shm
        # fallback, say) still leaves its black box behind, so the
        # forensics baseline and the fault-free verdict work from the
        # same evidence as the faulted arms
        import atexit

        atexit.register(
            lambda: r.dump("manual", reason="shutdown", force=True))
        r._atexit_installed = True
    try:
        from fedml_tpu.analysis import locks as _locks

        _locks.set_acquire_tap(r._on_lock)
    except Exception:
        pass
    return r


def _autowire() -> None:
    # always-on contract: any process that imports the obs layer feeds
    # its event stream and span observations into the rings, whether or
    # not an entry point ever calls install()
    tel = get_telemetry()
    tel.set_event_tap(_GLOBAL._on_event)
    tel.set_observe_tap(_GLOBAL._on_observe)


_autowire()
