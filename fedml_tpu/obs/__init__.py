"""Observability layer: process-wide telemetry + JAX/comm hooks.

Modules (kept import-light on purpose — ``telemetry`` is pure stdlib so
core/comm can depend on it without cycles or jax import cost):

- ``telemetry``  — counters / gauges / log-bucketed histograms with a
  JSONL-able snapshot, the process-wide registry every layer reports to;
- ``comm_obs``   — per-message-type send/recv counters for the comm
  backends (wired into ``CommBackend``, so transports and algorithms
  need no changes to be measured);
- ``jax_hooks``  — compile-event tracking per jit signature, device
  memory high-water gauges, ``trace_rounds`` profiler bracketing;
- ``digest``     — mergeable registry digests (the in-band stats plane:
  associative ``merge``, delta sources, the server-side rollup);
- ``slo``        — the declarative federation SLO engine + the atomic
  ``status.json`` / ``slo_report.json`` writers.

NOTE: do not import ``jax_hooks`` here — ``core.metrics`` imports
``obs.telemetry`` (which executes this file), and ``jax_hooks`` imports
``core.metrics`` lazily; a top-level import would close that cycle.
"""

from fedml_tpu.obs.telemetry import (
    Telemetry,
    get_telemetry,
    metric_key,
    parse_metric_key,
)

__all__ = ["Telemetry", "get_telemetry", "metric_key", "parse_metric_key"]
