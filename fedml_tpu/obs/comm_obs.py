"""Comm-layer instrumentation: per-message-type counters + latency.

Wired into ``CommBackend``/``NodeManager`` (``fedml_tpu/comm/backend.py``)
as observer-compatible middleware: the recv side records inside the base
class's ``_notify`` (before observers run) and the send side inside each
transport's ``send_message``, so server/client managers and algorithms
need no changes to be measured.

Series (naming convention in ``obs/telemetry.py``):

- ``comm.sent_msgs{msg_type=...}`` / ``comm.recv_msgs{msg_type=...}``
- ``comm.sent_bytes{msg_type=...}`` / ``comm.recv_bytes{msg_type=...}``
  — serialized wire bytes (TCP: exact frame length; inproc: the
  estimator below, since the deterministic bus never serializes)
- ``comm.send_latency_s{msg_type=...}`` — histogram of time spent in
  ``send_message`` (serialize + enqueue/socket write)
- ``comm.handle_latency_s{msg_type=...}`` — histogram of handler time in
  ``NodeManager.receive_message`` (the server's aggregate, the client's
  local train)
"""

from __future__ import annotations

from typing import Optional

from fedml_tpu.comm.message import (
    FRAME_NDBUF_KEY,
    NDARRAY_KEY,
    WIRETREE_KEY,
)
from fedml_tpu.obs import flight
from fedml_tpu.obs.telemetry import Telemetry, get_telemetry

# base64 expansion of binary buffers on the wire — applies ONLY to
# legacy wiretree-v1 values (b64 leaf dicts, or raw arrays serialized
# through the v1 JSON line).  The default wire since the compression
# subsystem is the v2 binary frame codec, whose raw-array accounting is
# EXACT (length-prefixed buffers + the ~48-byte __ndbuf__ header entry);
# ``message_nbytes`` estimates that framing unless asked for version=1.
_B64_FACTOR = 4.0 / 3.0


def record_send(msg_type: str, nbytes: Optional[int], seconds: Optional[float],
                telemetry: Optional[Telemetry] = None) -> None:
    t = telemetry or get_telemetry()
    t.inc("comm.sent_msgs", 1, msg_type=msg_type)
    if nbytes:
        t.inc("comm.sent_bytes", nbytes, msg_type=msg_type)
    if seconds is not None and seconds >= 0:
        t.observe("comm.send_latency_s", seconds, msg_type=msg_type)
    # per-frame metadata for the flight recorder's comm ring — every
    # transport (tcp/shm/mux/inproc) reports through here, so the black
    # box sees each frame once regardless of how it traveled
    flight.note("comm", "send", msg_type=msg_type, nbytes=nbytes or 0)


def record_recv(msg_type: str, nbytes: Optional[int] = None,
                telemetry: Optional[Telemetry] = None) -> None:
    t = telemetry or get_telemetry()
    t.inc("comm.recv_msgs", 1, msg_type=msg_type)
    if nbytes:
        t.inc("comm.recv_bytes", nbytes, msg_type=msg_type)
    flight.note("comm", "recv", msg_type=msg_type, nbytes=nbytes or 0)


def record_handle(msg_type: str, seconds: float,
                  telemetry: Optional[Telemetry] = None) -> None:
    t = telemetry or get_telemetry()
    if seconds >= 0:
        t.observe("comm.handle_latency_s", seconds, msg_type=msg_type)


def record_compression(msg_type: str, raw_nbytes: float,
                       compressed_nbytes: float,
                       telemetry: Optional[Telemetry] = None) -> None:
    """A model payload was codec-encoded before send: ``raw_bytes`` is
    the logical fp32 size, ``compressed_bytes`` the encoded payload
    actually shipped — one counter pair, so a run's compression ratio
    is a single division in ``tools/trace_summary.py``."""
    t = telemetry or get_telemetry()
    t.inc("comm.raw_bytes", raw_nbytes, msg_type=msg_type)
    t.inc("comm.compressed_bytes", compressed_nbytes, msg_type=msg_type)


def record_unhandled(msg_type: str,
                     telemetry: Optional[Telemetry] = None) -> None:
    """A frame arrived for a message type the node has no handler for —
    a late/stray/duplicate frame, expected under faults.  Counted on the
    same registry chaos runs read (``faults.observed`` naming), so
    injected drops/delays can be reconciled against what nodes saw."""
    t = telemetry or get_telemetry()
    t.inc("comm.unhandled_msgs", 1, msg_type=msg_type)
    t.inc("faults.observed", 1, kind="unhandled_msg", msg_type=msg_type)
    flight.note("faults", "observed", what="unhandled_msg",
                msg_type=msg_type)


def _value_nbytes(v, binary: bool = True) -> float:
    """Approximate serialized size of one params value (see message.py
    codecs) WITHOUT encoding it — inproc skips serialization entirely,
    so its byte accounting must not pay a full ``to_json`` per message.

    ``binary`` (the default — v2 binary framing is the wire default):
    raw arrays ship as exact length-prefixed buffers
    (``Message.to_frame``), so their accounting is EXACT (nbytes + the
    ~48-byte ``__ndbuf__`` header entry).  ``binary=False`` models the
    legacy v1 JSON line, where raw arrays b64-inflate by ``_B64_FACTOR``
    — the only path the factor still applies to (already-b64
    ``__ndarray__`` dicts are length-counted directly either way)."""
    if isinstance(v, dict):
        if NDARRAY_KEY in v:  # already-encoded array: b64 string length
            return len(v[NDARRAY_KEY]) + 48
        if FRAME_NDBUF_KEY in v:  # binary buffer reference: exact
            return float(v[FRAME_NDBUF_KEY][1]) + 48
        if WIRETREE_KEY in v:  # wire pytree: sum its encoded leaves
            # a v2 tree's raw leaves are only exact when the FRAME is
            # binary too; through a v1 JSON line they b64-encode like
            # any array (the interop contract in message.py)
            exact = v.get(WIRETREE_KEY) == 2 and binary
            return sum(_value_nbytes(l, binary=exact)
                       for l in v.get("leaves", ())) + 32
        return sum(len(str(k)) + 4 + _value_nbytes(x, binary)
                   for k, x in v.items()) + 2
    if isinstance(v, (list, tuple)):
        return sum(_value_nbytes(x, binary) for x in v) + 2
    if isinstance(v, str):
        return len(v) + 2
    if isinstance(v, bool) or v is None:
        return 5
    if isinstance(v, (int, float)):
        return 12
    nbytes = getattr(v, "nbytes", None)  # numpy / jax array
    if nbytes is not None:
        if binary:  # v2 frame: raw bytes + the __ndbuf__ header entry
            return float(nbytes) + 48
        return float(nbytes) * _B64_FACTOR + 48
    return len(str(v))


def message_nbytes(msg, version: int = 2) -> int:
    """Estimated wire size of a ``Message`` envelope without
    serializing it.  ``version=2`` (default): the binary frame codec —
    raw arrays counted exactly.  ``version=1``: the legacy JSON line,
    raw arrays inflated by the b64 factor."""
    binary = version >= 2
    return int(sum(len(k) + 4 + _value_nbytes(v, binary)
                   for k, v in msg.params.items()) + 2)
