"""VGG-11/13/16/19 (plain and BatchNorm variants).

Architecture parity with the reference ``fedml_api/model/cv/vgg.py``:
torchvision-style config strings (``vgg.py:73-78`` cfgs A/B/D/E), a
7×7 adaptive average pool, and the 4096-4096 dropout classifier head
(``vgg.py:22-31``).  Factories ``vgg11..vgg19`` / ``*_bn``
(``vgg.py:82-160``).

TPU-first choices: NHWC, a statically-unrolled adaptive pool (bins are
computed at trace time — no dynamic shapes under jit), dropout driven by
an explicit rng.
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle

# torchvision layer configs: reference vgg.py:73-78
CFGS = {
    "A": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "B": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "D": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"),
    "E": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
          512, "M", 512, 512, 512, 512, "M"),
}


def adaptive_avg_pool(x: jnp.ndarray, out_hw: int) -> jnp.ndarray:
    """torch AdaptiveAvgPool2d semantics on NHWC, unrolled statically.

    Bin i covers [floor(i*H/out), ceil((i+1)*H/out)); shapes are static
    under jit so the 49-slice unroll traces once and XLA fuses it.
    """
    h, w = x.shape[1], x.shape[2]
    if h == out_hw and w == out_hw:
        return x
    rows = []
    for i in range(out_hw):
        h0, h1 = (i * h) // out_hw, -(-((i + 1) * h) // out_hw)
        cols = []
        for j in range(out_hw):
            w0, w1 = (j * w) // out_hw, -(-((j + 1) * w) // out_hw)
            cols.append(x[:, h0:h1, w0:w1, :].mean(axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    batch_norm: bool = False
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding=1)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(
                        use_running_average=not train, momentum=0.9, epsilon=1e-5
                    )(x)
                x = nn.relu(x)
        x = adaptive_avg_pool(x, 7)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def _bundle(cfg_key, batch_norm, num_classes, image_size):
    return ModelBundle(
        module=VGG(cfg=CFGS[cfg_key], batch_norm=batch_norm,
                   num_classes=num_classes),
        input_shape=(image_size, image_size, 3),
        needs_dropout_rng=True,
    )


def vgg11(num_classes=1000, image_size=224):
    return _bundle("A", False, num_classes, image_size)


def vgg11_bn(num_classes=1000, image_size=224):
    return _bundle("A", True, num_classes, image_size)


def vgg13(num_classes=1000, image_size=224):
    return _bundle("B", False, num_classes, image_size)


def vgg13_bn(num_classes=1000, image_size=224):
    return _bundle("B", True, num_classes, image_size)


def vgg16(num_classes=1000, image_size=224):
    return _bundle("D", False, num_classes, image_size)


def vgg16_bn(num_classes=1000, image_size=224):
    return _bundle("D", True, num_classes, image_size)


def vgg19(num_classes=1000, image_size=224):
    return _bundle("E", False, num_classes, image_size)


def vgg19_bn(num_classes=1000, image_size=224):
    return _bundle("E", True, num_classes, image_size)
