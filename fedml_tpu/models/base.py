"""ModelBundle — the framework's model operator contract.

The reference abstracts models behind the ``ModelTrainer`` ABC
(``fedml_core/trainer/model_trainer.py:4-32``) whose docstring promises
framework-agnosticism.  Here the contract is functional: a flax module
plus pure ``init`` / ``apply_train`` / ``apply_eval`` closures over an
explicit ``variables`` pytree (``{'params': ..., 'batch_stats': ...}``).
Mutable BatchNorm statistics — the awkward hidden state of the torch
version (naively averaged by FedAvg, skipped by robust vectorization,
``robust_aggregation.py:28-29``) — are explicit leaves of the same tree,
so aggregation policy over them is a visible choice, not an accident.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

PyTree = Any


@dataclasses.dataclass
class ModelBundle:
    """A flax module + its input spec, wrapped as pure functions."""

    module: nn.Module
    input_shape: Sequence[int]  # one example's shape, no batch dim
    input_dtype: Any = jnp.float32
    needs_dropout_rng: bool = False

    def init(self, rng: jax.Array) -> PyTree:
        dummy = jnp.zeros((1, *self.input_shape), self.input_dtype)
        rngs = {"params": rng}
        if self.needs_dropout_rng:
            rngs["dropout"] = jax.random.fold_in(rng, 1)
        return self.module.init(rngs, dummy, train=False)

    def apply_train(
        self, variables: PyTree, x: jax.Array, rng: Optional[jax.Array] = None
    ) -> Tuple[jax.Array, PyTree]:
        """Forward in train mode; returns (logits, updated variables)."""
        rngs = {"dropout": rng} if (self.needs_dropout_rng and rng is not None) else None
        if "batch_stats" in variables:
            logits, mutated = self.module.apply(
                variables, x, train=True, mutable=["batch_stats"], rngs=rngs
            )
            return logits, {**variables, "batch_stats": mutated["batch_stats"]}
        logits = self.module.apply(variables, x, train=True, rngs=rngs)
        return logits, variables

    def apply_eval(self, variables: PyTree, x: jax.Array) -> jax.Array:
        return self.module.apply(variables, x, train=False)
