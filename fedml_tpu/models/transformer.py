"""Transformer LM with pluggable attention — the long-context model
family.

The reference's NLP ceiling is a 2-layer LSTM over 80-char windows
(``model/nlp/rnn.py``, SURVEY.md §5.7).  This decoder-only transformer
is the rebuild's long-context extension: its attention is an injected
function, so the SAME module runs

- single-device exact blockwise attention (O(L) memory), or
- ring attention over a sequence-sharded mesh axis
  (``parallel.ring_attention.ring_attention`` under ``shard_map``),

with no model code changes.  Pre-LN blocks, learned positional
embeddings, weight-tied output head.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle
from fedml_tpu.parallel.ring_attention import blockwise_attention

# (q, k, v, causal) over [L, H, D] per example
AttnFn = Callable


def _default_attn(q, k, v, causal):
    """Single-device attention policy:

    - L >= 2048 on an accelerator: the pallas flash kernel with its
      custom O(L)-memory backward.  Measured on one v5e chip: >= parity
      with the lax blockwise scan at 4k and ~2.4x on the fwd at 8k — and
      at 8k the blockwise TRAINING path does not fit at all (its scan
      vjp stacks per-block residuals; observed 17.6 GB > 15.75 GB HBM
      for a 4x8192 batch, while flash trains the same batch in ~365 ms).
    - shorter sequences, non-TPU backends (the kernel is Mosaic/TPU;
      GPU would fail to compile it, CPU runs it only in interpret mode),
      lengths that no >=512 block divides (smaller pallas blocks measured
      4-8x SLOWER than the blockwise scan): the lax blockwise path.
    """
    from fedml_tpu.ops.flash_attention import flash_attention, pick_block

    L = q.shape[0]
    block = pick_block(L)
    if block >= 512 and L >= 2048 and jax.default_backend() == "tpu":
        return flash_attention(
            q, k, v, causal=causal, block_q=block, block_k=block
        )
    return blockwise_attention(q, k, v, causal=causal, block_size=512)


class MultiHeadAttention(nn.Module):
    num_heads: int
    attn_fn: Optional[AttnFn] = None
    causal: bool = True

    @nn.compact
    def __call__(self, x):
        B, L, E = x.shape
        H = self.num_heads
        D = E // H
        qkv = nn.Dense(3 * E, use_bias=False)(x)
        q, k, v = jnp.split(qkv.reshape(B, L, 3 * H, D), 3, axis=2)
        attn = self.attn_fn or _default_attn
        out = jax.vmap(lambda a, b, c: attn(a, b, c, self.causal))(q, k, v)
        return nn.Dense(E, use_bias=False)(out.reshape(B, L, E))


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        E = x.shape[-1]
        x = x + MultiHeadAttention(self.num_heads, self.attn_fn)(
            nn.LayerNorm()(x)
        )
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.mlp_ratio * E)(h)
        h = nn.gelu(h)
        return x + nn.Dense(E)(h)


class TransformerLM(nn.Module):
    vocab_size: int = 256
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 2048
    attn_fn: Optional[AttnFn] = None
    pos_offset_fn: Optional[Callable] = None  # (local_len) -> global offset
    # gradient rematerialization: checkpoint each Block's activations
    # and recompute them in the backward pass — trades ~1/3 more FLOPs
    # for O(layers) less live-activation HBM, the standard lever that
    # lets one v5e chip train widths past GPT-2-Large (width 1536 OOMs
    # at batch 8x1024 without it — PROFILE.md).  Parameter tree is
    # unchanged (nn.remat is a lifted transform), pinned by
    # tests/test_models.py::test_transformer_remat_same_function
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, L = x.shape
        tok = nn.Embed(self.vocab_size, self.embed_dim, name="wte")
        h = tok(x.astype(jnp.int32))
        pos0 = self.pos_offset_fn(L) if self.pos_offset_fn else 0
        if isinstance(pos0, int) and pos0 + L > self.max_len:
            raise ValueError(
                f"sequence length {L} exceeds max_len {self.max_len}"
            )
        wpe = nn.Embed(self.max_len, self.embed_dim, name="wpe")
        h = h + wpe(pos0 + jnp.arange(L))[None]
        # explicit names keep the parameter tree identical with and
        # without remat (nn.remat would auto-name "CheckpointBlock_i")
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            h = block_cls(self.num_heads, attn_fn=self.attn_fn,
                          name=f"Block_{i}")(h)
        # named so partition-rule tables (parallel/partition.py) can
        # address the final norm distinctly from the blocks' auto-named
        # LayerNorm_{0,1} — the GPT convention
        h = nn.LayerNorm(name="ln_f")(h)
        # weight-tied head
        return tok.attend(h)


def transformer_lm(
    vocab_size=256, embed_dim=128, num_heads=4, num_layers=2, seq_len=256,
    attn_fn: Optional[AttnFn] = None, max_len: Optional[int] = None,
    remat: bool = False,
) -> ModelBundle:
    return ModelBundle(
        module=TransformerLM(
            vocab_size=vocab_size, embed_dim=embed_dim, num_heads=num_heads,
            num_layers=num_layers, max_len=max_len or seq_len,
            attn_fn=attn_fn, remat=remat,
        ),
        input_shape=(seq_len,),
        input_dtype=jnp.int32,
    )
