"""TPU-retiled execution variants of the CIFAR ResNet (same math).

The north-star workload (ResNet-56, ``models/resnet.py``, reference
``fedml_api/model/cv/resnet.py:202-209``) runs its convs at channel
widths 16/32/64 — a 128-lane MXU executes them at 12.5/25/50% output
-lane occupancy, which PROFILE.md's accounting identifies as the
structural MFU ceiling.  This module implements the two classic TPU
countermeasures as EXECUTION variants that compute the *identical
function* with the *identical parameter tree* as the baseline module
(pinned by ``tests/test_resnet_tpu.py`` — baseline-initialized
variables apply directly):

- **Space-to-depth** (``s2d_stages=k``): stages 1..k run on
  half-resolution tensors whose 2×2 spatial blocks are folded into
  channels (32×32×C → 16×16×4C, the MLPerf ResNet trick generalized
  to stride-1 stages).  Every conv kernel is re-scattered at trace
  time into its S2D-space equivalent (a stride-1 3×3 C→C' conv
  becomes a 3×3 4C→4C' conv with structural zeros; 1×1 becomes
  block-diagonal; the stage-transition stride-2 convs *consume* the
  S2D layout directly, so un-folding is free).  The transform trades
  4× nominal MACs for 4× wider MXU lanes and 4× larger K-tiles —
  net MXU-tile count DROPS for every conv in the stage.
- **Lane padding** (``pad_stage1_to=p``): stage-1's 16-wide internal
  bottleneck convs execute at width p with zero-padded kernels; the
  padded channels provably stay zero through conv→BN→relu, so the
  function is unchanged.
- **Pallas implicit GEMM** (``conv_variant="pallas"``): every 3×3 conv
  runs through ``ops/conv_mxu.conv3x3`` — patches gathered in VMEM
  into an [N·Ho·Wo, 9·Cin] matrix and multiplied in ONE MXU matmul,
  attacking M/K packing instead of the lane axis the dense retilings
  above inflated (r5 measured them all negative).  In train mode the
  kernel additionally emits the per-channel activation moments, so the
  following BatchNorm's batch statistics cost no separate full-tensor
  ``reduce_sum`` pass.  1×1 convs stay on XLA (they already lower to
  dense GEMMs; there is no patch axis to pack).

All transforms are parameter-preserving: the variables are created
with the baseline's exact names and shapes (``Conv_i/kernel`` etc.),
and kernels are expanded inside the forward, so gradients flow to the
original parameters and FedAvg aggregation/checkpoints are unchanged.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


def space_to_depth(x: jax.Array) -> jax.Array:
    """(B, H, W, C) → (B, H/2, W/2, 4C); channel layout (ry, rx, c)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)


def depth_to_space(x: jax.Array) -> jax.Array:
    b, h, w, c4 = x.shape
    c = c4 // 4
    x = x.reshape(b, h, w, 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, 2 * h, 2 * w, c)


def s2d_kernel_stride1(w: jax.Array) -> jax.Array:
    """Kernel of a stride-1 SAME conv, re-scattered so that
    ``conv(s2d(x), W') == s2d(conv(x, w))``.

    Output pixel (2i+dy, 2j+dx) reads input (2i+dy+t-p, ...); writing
    a = dy+t-p, the source lands in S2D block offset floor(a/2) at
    sub-row a mod 2 — so each tap of ``w`` occupies exactly one cell
    of a (4Cin → 4Cout) kernel over S2D blocks.  SAME padding in S2D
    space supplies original rows −2..−1 while the scatter only ever
    references row −1: the structural zeros keep the extra padded row
    inert, so no custom padding is needed.
    """
    k = w.shape[0]
    p = k // 2
    ci, co = w.shape[2], w.shape[3]
    bos = sorted({(d + t - p) // 2 for d in range(2) for t in range(k)})
    nk = bos[-1] - bos[0] + 1
    out = jnp.zeros((nk, nk, 4 * ci, 4 * co), w.dtype)
    for dy in range(2):
        for ty in range(k):
            ay = dy + ty - p
            by, ry = ay // 2 - bos[0], ay % 2
            for dx in range(2):
                for tx in range(k):
                    ax = dx + tx - p
                    bx, rx = ax // 2 - bos[0], ax % 2
                    out = out.at[
                        by, bx,
                        (ry * 2 + rx) * ci:(ry * 2 + rx + 1) * ci,
                        (dy * 2 + dx) * co:(dy * 2 + dx + 1) * co,
                    ].set(w[ty, tx])
    return out


def s2d_kernel_stride2(w: jax.Array) -> jax.Array:
    """Stride-2 3×3 SAME conv consuming an S2D input and emitting the
    NORMAL-space half-resolution output: out[i] reads original rows
    2i−1..2i+1 = S2D blocks {i−1 (sub-row 1), i (sub-rows 0, 1)} — a
    2×2 kernel over S2D blocks, stride 1, pad (1, 0) each spatial dim."""
    ci, co = w.shape[2], w.shape[3]
    out = jnp.zeros((2, 2, 4 * ci, co), w.dtype)
    for ty in range(3):
        ay = ty - 1
        by, ry = ay // 2 + 1, ay % 2
        for tx in range(3):
            ax = tx - 1
            bx, rx = ax // 2 + 1, ax % 2
            out = out.at[
                by, bx, (ry * 2 + rx) * ci:(ry * 2 + rx + 1) * ci, :
            ].set(w[ty, tx])
    return out


def s2d_kernel_stride2_1x1(w: jax.Array) -> jax.Array:
    """Stride-2 1×1 conv on an S2D input: out[i] = w·in[2i] — the
    (0, 0) sub-position, i.e. the first Cin channel block."""
    ci, co = w.shape[2], w.shape[3]
    out = jnp.zeros((1, 1, 4 * ci, co), w.dtype)
    return out.at[0, 0, :ci, :].set(w[0, 0])


_DN = ("NHWC", "HWIO", "NHWC")


class _XConv(nn.Module):
    """Conv whose PARAMETER keeps the baseline shape while the compute
    runs in a transformed space.  ``in_space``/``out_space`` ∈
    {"n", "s"} (normal / space-to-depth); ``pad_to`` zero-pads the
    compute width (lane padding) — ``pad_in`` declares how many of the
    input's trailing channels are structural zeros (so the kernel rows
    feeding them can be zero).  ``conv_variant="pallas"`` routes 3×3
    normal-space convs through the implicit-GEMM Pallas kernel
    (``ops/conv_mxu``); with ``emit_moments`` the call returns
    ``(y, (sum, sumsq, count))`` for moment-fused BatchNorm."""

    features: int
    in_features: int
    kernel: int
    stride: int = 1
    in_space: str = "n"
    out_space: str = "n"
    pad_to: int = 0
    pad_in: int = 0
    conv_variant: str = "xla"
    emit_moments: bool = False

    @nn.compact
    def __call__(self, x):
        k, ci, co = self.kernel, self.in_features, self.features
        w = self.param(
            "kernel", nn.initializers.lecun_normal(), (k, k, ci, co),
            jnp.float32,
        )
        w = w.astype(x.dtype)
        if self.conv_variant == "pallas" and k == 3:
            if (self.in_space, self.out_space) != ("n", "n") or \
                    self.pad_to or self.pad_in:
                raise ValueError(
                    "conv_variant='pallas' composes with neither s2d "
                    "spaces nor lane padding (r5 measured those dense "
                    "retilings negative; the kernel runs normal-space)"
                )
            from fedml_tpu.ops.conv_mxu import conv3x3, conv3x3_moments

            if self.emit_moments:
                y, s, sq = conv3x3_moments(x, w, self.stride)
                count = float(
                    x.shape[0] * (x.shape[1] // self.stride)
                    * (x.shape[2] // self.stride)
                )
                return y, (s, sq, count)
            return conv3x3(x, w, self.stride)
        if self.pad_to or self.pad_in:
            w = jnp.pad(w, ((0, 0), (0, 0), (0, self.pad_in),
                            (0, (self.pad_to - co) if self.pad_to else 0)))
        if self.in_space == "n":
            # baseline nn.Conv uses EXPLICIT padding=k//2 — for the
            # stride-2 transitions this centers windows on even rows,
            # which differs from "SAME" (odd centers); the s2d stride-2
            # kernels below assume the same even-center convention
            p = k // 2
            return jax.lax.conv_general_dilated(
                x, w, (self.stride, self.stride), [(p, p), (p, p)],
                dimension_numbers=_DN,
            )
        if self.stride == 1:
            wp = s2d_kernel_stride1(w)
            y = jax.lax.conv_general_dilated(
                x, wp, (1, 1), "SAME", dimension_numbers=_DN
            )
            return y  # stays in s2d space
        # stride-2 transition: consumes s2d, emits normal space
        if k == 1:
            wp = s2d_kernel_stride2_1x1(w)
            y = jax.lax.conv_general_dilated(
                x, wp, (1, 1), "VALID", dimension_numbers=_DN
            )
        else:
            wp = s2d_kernel_stride2(w)
            y = jax.lax.conv_general_dilated(
                x, wp, (1, 1), [(1, 0), (1, 0)], dimension_numbers=_DN
            )
        if self.out_space == "s":
            y = space_to_depth(y)
        return y


class _XBatchNorm(nn.Module):
    """BatchNorm with the baseline's parameter/stat shapes (per
    ORIGINAL channel) operating on transformed activations: in S2D
    space each original channel appears as 4 sub-channels whose stats
    are pooled (exactly the baseline's per-channel reduction set);
    with lane padding the trailing channels are structural zeros and
    are excluded from the stored running stats.  Matches flax
    ``nn.BatchNorm(momentum=0.9, epsilon=1e-5)`` semantics:
    fast-variance stats, running update ra = m·ra + (1−m)·batch."""

    channels: int
    space: str = "n"
    pad_to: int = 0
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False, moments=None):
        """``moments=(sum, sumsq, count)`` supplies the batch statistics
        pre-reduced (the Pallas conv kernel's fused moment outputs) so
        the train path skips its own full-tensor reductions; only valid
        in normal space with no lane padding."""
        c = self.channels
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )
        if moments is not None and (self.space != "n" or self.pad_to):
            raise ValueError("pre-reduced moments need normal space "
                             "and no lane padding")
        if train:
            if moments is not None:
                s, sq, count = moments
                mean = s / count
                mean2 = sq / count
            elif self.space == "s":
                xr = x.reshape(x.shape[:3] + (4, c)).astype(jnp.float32)
                mean = jnp.mean(xr, axis=(0, 1, 2, 3))
                mean2 = jnp.mean(jnp.square(xr), axis=(0, 1, 2, 3))
            else:
                xf = x.astype(jnp.float32)
                mean = jnp.mean(xf, axis=(0, 1, 2))
                mean2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
                if self.pad_to:
                    mean, mean2 = mean[:c], mean2[:c]
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )
        else:
            mean, var = ra_mean.value, ra_var.value
        mul = scale * jax.lax.rsqrt(var + self.epsilon)
        add = bias - mean * mul
        if self.space == "s":
            mul = jnp.tile(mul, 4)
            add = jnp.tile(add, 4)
        elif self.pad_to:
            mul = jnp.pad(mul, (0, self.pad_to - c))
            add = jnp.pad(add, (0, self.pad_to - c))
        return x * mul.astype(x.dtype) + add.astype(x.dtype)


class BottleneckTPU(nn.Module):
    """Bottleneck with per-block space/padding configuration.  Param
    names and creation order mirror ``resnet.Bottleneck`` exactly
    (Conv_0/BN_0 reduce, Conv_1/BN_1 3×3, Conv_2/BN_2 expand,
    Conv_3/BN_3 shortcut when shapes change)."""

    planes: int
    in_ch: int
    stride: int = 1
    expansion: int = 4
    in_space: str = "n"
    out_space: str = "n"
    pad_to: int = 0   # compute width for the internal `planes` convs
    pad_in: int = 0   # structural-zero channels on the block INPUT
    conv_variant: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        planes, out_ch = self.planes, self.planes * self.expansion
        mid_space = self.in_space  # 1x1 reduce keeps the input space
        y = _XConv(planes, self.in_ch, 1, 1, self.in_space, mid_space,
                   pad_to=self.pad_to, pad_in=self.pad_in,
                   conv_variant=self.conv_variant, name="Conv_0")(x)
        y = _XBatchNorm(planes, mid_space, pad_to=self.pad_to,
                        name="BatchNorm_0")(y, train)
        y = nn.relu(y)
        # the 3×3 is the Pallas target; in train mode its kernel also
        # emits the activation moments the next BatchNorm consumes
        fuse_moments = self.conv_variant == "pallas" and train
        y = _XConv(planes, planes, 3, self.stride, mid_space,
                   self.out_space, pad_to=self.pad_to,
                   pad_in=(self.pad_to - planes if self.pad_to else 0),
                   conv_variant=self.conv_variant,
                   emit_moments=fuse_moments, name="Conv_1")(y)
        y, mom = y if fuse_moments else (y, None)
        post_space = mid_space if self.stride == 1 else self.out_space
        y = _XBatchNorm(planes, post_space, pad_to=self.pad_to,
                        name="BatchNorm_1")(y, train, moments=mom)
        y = nn.relu(y)
        y = _XConv(out_ch, planes, 1, 1, post_space, post_space,
                   pad_in=(self.pad_to - planes if self.pad_to else 0),
                   conv_variant=self.conv_variant, name="Conv_2")(y)
        y = _XBatchNorm(out_ch, post_space, name="BatchNorm_2")(y, train)
        identity = x
        if self.in_ch != out_ch or self.stride != 1:
            identity = _XConv(out_ch, self.in_ch, 1, self.stride,
                              self.in_space, self.out_space,
                              pad_in=self.pad_in,
                              conv_variant=self.conv_variant,
                              name="Conv_3")(x)
            sc_space = self.in_space if self.stride == 1 else self.out_space
            identity = _XBatchNorm(out_ch, sc_space,
                                   name="BatchNorm_3")(identity, train)
        return nn.relu(y + identity)


class CifarResNetTPU(nn.Module):
    """Drop-in execution variant of ``resnet.CifarResNet`` (Bottleneck
    form): identical variable tree, identical function; stages
    1..``s2d_stages`` run in space-to-depth layout and/or stage 1 runs
    lane-padded to ``pad_stage1_to``."""

    layers: Sequence[int]
    num_classes: int = 10
    s2d_stages: int = 0
    pad_stage1_to: int = 0
    conv_variant: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.s2d_stages and self.pad_stage1_to:
            # in s2d space stage 1 already computes 64-wide; combining
            # the transforms would need a pad-aware s2d BatchNorm for
            # no additional lane win
            raise ValueError("s2d_stages and pad_stage1_to are exclusive")
        if self.conv_variant == "pallas" and (
            self.s2d_stages or self.pad_stage1_to
        ):
            # the implicit-GEMM kernel runs normal-space; the dense
            # retilings it replaces were each measured negative (r5)
            raise ValueError(
                "conv_variant='pallas' excludes s2d_stages/pad_stage1_to"
            )
        spaces = ["s" if s < self.s2d_stages else "n" for s in range(3)]
        if self.s2d_stages > 0:
            x = space_to_depth(x)
        fuse_moments = self.conv_variant == "pallas" and train
        x = _XConv(16, 3, 3, 1, spaces[0], spaces[0],
                   conv_variant=self.conv_variant,
                   emit_moments=fuse_moments, name="Conv_0")(x)
        x, mom = x if fuse_moments else (x, None)
        x = _XBatchNorm(16, spaces[0], name="BatchNorm_0")(
            x, train, moments=mom)
        x = nn.relu(x)
        in_ch, j = 16, 0
        for stage, (planes, n_blocks) in enumerate(
            zip((16, 32, 64), self.layers)
        ):
            pad = self.pad_stage1_to if stage == 0 else 0
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                in_space = spaces[stage - 1] if (
                    stage > 0 and i == 0
                ) else spaces[stage]
                x = BottleneckTPU(
                    planes=planes, in_ch=in_ch, stride=stride,
                    in_space=in_space, out_space=spaces[stage],
                    pad_to=pad, conv_variant=self.conv_variant,
                    name=f"Bottleneck_{j}",
                )(x, train)
                in_ch, j = planes * 4, j + 1
        if spaces[2] == "s":
            b, h, w, c4 = x.shape
            x = x.reshape(b, h, w, 4, c4 // 4).mean(axis=(1, 2, 3))
        else:
            x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="Dense_0")(x)


def resnet56_tpu(num_classes: int = 10, image_size: int = 32,
                 s2d_stages: int = 0, pad_stage1_to: int = 0,
                 conv_variant: str = "xla") -> ModelBundle:
    """ResNet-56 (reference factory parity: Bottleneck [6,6,6]) with
    TPU execution transforms.  ``s2d_stages=0, pad_stage1_to=0,
    conv_variant="xla"`` is bit-for-bit the baseline architecture (and
    still asserts tree parity in tests); ``conv_variant="pallas"`` runs
    every 3×3 conv through the implicit-GEMM Pallas kernel
    (``ops/conv_mxu``) with moment-fused train-mode BatchNorm."""
    return ModelBundle(
        module=CifarResNetTPU(
            layers=(6, 6, 6), num_classes=num_classes,
            s2d_stages=s2d_stages, pad_stage1_to=pad_stage1_to,
            conv_variant=conv_variant,
        ),
        input_shape=(image_size, image_size, 3),
    )
