"""Genotype container + parse (reference ``darts/genotypes.py`` and
``model_search.py:258-297``)."""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import numpy as np

from fedml_tpu.models.darts.ops import PRIMITIVES


class Genotype(NamedTuple):
    normal: List[Tuple[str, int]]
    normal_concat: Sequence[int]
    reduce: List[Tuple[str, int]]
    reduce_concat: Sequence[int]


# the published DARTS-v2 CIFAR cell (reference genotypes.py DARTS_V2)
DARTS_V2 = Genotype(
    normal=[("sep_conv_3x3", 0), ("sep_conv_3x3", 1), ("sep_conv_3x3", 0),
            ("sep_conv_3x3", 1), ("sep_conv_3x3", 1), ("skip_connect", 0),
            ("skip_connect", 0), ("dil_conv_3x3", 2)],
    normal_concat=[2, 3, 4, 5],
    reduce=[("max_pool_3x3", 0), ("max_pool_3x3", 1), ("skip_connect", 2),
            ("max_pool_3x3", 1), ("max_pool_3x3", 0), ("skip_connect", 2),
            ("skip_connect", 2), ("max_pool_3x3", 1)],
    reduce_concat=[2, 3, 4, 5],
)


def parse_alphas(alphas: np.ndarray, steps: int = 4) -> List[Tuple[str, int]]:
    """Derive the discrete cell from softmaxed alphas [n_edges, n_ops].

    Reference ``model_search.py:263-291``: per node, keep the 2 incoming
    edges with the strongest non-'none' weight; per kept edge, the
    strongest non-'none' op.
    """
    none_idx = PRIMITIVES.index("none")
    gene = []
    offset = 0
    for i in range(steps):
        n_in = 2 + i
        w = np.asarray(alphas[offset : offset + n_in])
        edge_strength = np.max(
            np.delete(w, none_idx, axis=1), axis=1
        )
        edges = np.argsort(-edge_strength)[:2]
        for j in sorted(edges):
            ops = w[j].copy()
            ops[none_idx] = -np.inf
            gene.append((PRIMITIVES[int(np.argmax(ops))], int(j)))
        offset += n_in
    return gene


def genotype_from_alphas(
    alphas_normal: np.ndarray, alphas_reduce: np.ndarray, steps: int = 4,
    multiplier: int = 4,
) -> Genotype:
    def softmax(a):
        e = np.exp(a - a.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    concat = list(range(2 + steps - multiplier, steps + 2))
    return Genotype(
        normal=parse_alphas(softmax(np.asarray(alphas_normal)), steps),
        normal_concat=concat,
        reduce=parse_alphas(softmax(np.asarray(alphas_reduce)), steps),
        reduce_concat=concat,
    )
