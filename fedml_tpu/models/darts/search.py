"""DARTS search network (continuous relaxation) in flax.

Parity with the reference ``fedml_api/model/cv/darts/model_search.py``:
MixedOp (``:10-24``), Cell with 4 intermediate nodes / 14 edges and
stride-2 mixed ops on the first two inputs of reduction cells
(``:26-60``), Network = 3C stem, reduction at layers//3 and 2·layers//3,
global pool + linear head (``:172-231``).

TPU-first departure: architecture parameters (alphas) are NOT hidden
module state — they are explicit inputs to ``__call__``.  The functional
split lets FedNAS treat (weights, alphas) as two pytrees with different
optimizers and different aggregation, without the reference's id()-based
parameter filtering (``FedNASTrainer.py:38-44``).  All 8 op branches of
a MixedOp evaluate and get weight-summed — a static, dense compute
pattern XLA fuses well on the MXU (the sparse alternative would be
data-dependent control flow, which doesn't jit).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.darts.ops import OPS, PRIMITIVES, FactorizedReduce, ReLUConvBN

PyTree = Any


def num_edges(steps: int = 4) -> int:
    return sum(2 + i for i in range(steps))


class MixedOp(nn.Module):
    C: int
    stride: int

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        # weights: [n_ops]; dense weighted sum of all branches.  Pooling
        # primitives get the reference's affine-free BatchNorm wrapper
        # (model_search.py:14-19) so their magnitudes are commensurate
        # with the conv branches in the alpha-weighted sum.
        outs = []
        for name in PRIMITIVES:
            o = OPS[name](self.C, self.stride, False)(x, train)
            if "pool" in name:
                o = nn.BatchNorm(
                    use_running_average=not train, momentum=0.9,
                    epsilon=1e-5, use_scale=False, use_bias=False,
                )(o)
            outs.append(o)
        return sum(w * o for w, o in zip(weights, outs))


class SearchCell(nn.Module):
    steps: int
    multiplier: int
    C: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = False):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C, affine=False)(s0, train)
        else:
            s0 = ReLUConvBN(self.C, 1, 1, affine=False)(s0, train)
        s1 = ReLUConvBN(self.C, 1, 1, affine=False)(s1, train)

        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            s = sum(
                MixedOp(self.C, 2 if self.reduction and j < 2 else 1)(
                    h, weights[offset + j], train
                )
                for j, h in enumerate(states)
            )
            offset += len(states)
            states.append(s)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


class SearchNetwork(nn.Module):
    C: int = 16
    num_classes: int = 10
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    stem_multiplier: int = 3

    @nn.compact
    def __call__(self, x, alphas_normal, alphas_reduce, train: bool = False):
        wn = jax.nn.softmax(alphas_normal, axis=-1)
        wr = jax.nn.softmax(alphas_reduce, axis=-1)
        c_curr = self.stem_multiplier * self.C
        s0 = s1 = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )(nn.Conv(c_curr, (3, 3), padding=1, use_bias=False)(x))

        c_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c_curr *= 2
            s0, s1 = s1, SearchCell(
                steps=self.steps, multiplier=self.multiplier, C=c_curr,
                reduction=reduction, reduction_prev=reduction_prev,
            )(s0, s1, wr if reduction else wn, train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


@dataclasses.dataclass
class SearchBundle:
    """Functional wrapper: (weights pytree, alphas pytree) kept separate."""

    module: SearchNetwork
    input_shape: Sequence[int]
    input_dtype: Any = jnp.float32

    def init_alphas(self, rng: jax.Array) -> PyTree:
        """1e-3·N(0,1) init, reference ``model_search.py:232-241``."""
        n = num_edges(self.module.steps)
        k = len(PRIMITIVES)
        ka, kb = jax.random.split(rng)
        return {
            "alphas_normal": 1e-3 * jax.random.normal(ka, (n, k)),
            "alphas_reduce": 1e-3 * jax.random.normal(kb, (n, k)),
        }

    def init(self, rng: jax.Array) -> PyTree:
        dummy = jnp.zeros((1, *self.input_shape), self.input_dtype)
        alphas = self.init_alphas(jax.random.fold_in(rng, 1))
        return self.module.init(
            {"params": rng}, dummy, alphas["alphas_normal"],
            alphas["alphas_reduce"], train=False,
        )

    def apply_train(self, variables, alphas, x):
        if "batch_stats" in variables:
            logits, mutated = self.module.apply(
                variables, x, alphas["alphas_normal"], alphas["alphas_reduce"],
                train=True, mutable=["batch_stats"],
            )
            return logits, {**variables, "batch_stats": mutated["batch_stats"]}
        logits = self.module.apply(
            variables, x, alphas["alphas_normal"], alphas["alphas_reduce"],
            train=True,
        )
        return logits, variables

    def apply_eval(self, variables, alphas, x):
        return self.module.apply(
            variables, x, alphas["alphas_normal"], alphas["alphas_reduce"],
            train=False,
        )


def darts_search(C=16, num_classes=10, layers=8, image_size=32,
                 steps=4, multiplier=4, in_channels=3) -> SearchBundle:
    """Reference factory ``Network(C, num_classes, layers, ...)``
    (``model_search.py:174``)."""
    return SearchBundle(
        module=SearchNetwork(C=C, num_classes=num_classes, layers=layers,
                             steps=steps, multiplier=multiplier),
        input_shape=(image_size, image_size, in_channels),
    )
