"""Fixed (discrete) DARTS network built from a Genotype — the FedNAS
"train" stage model.

Parity with the reference ``fedml_api/model/cv/darts/model.py``:
compiled Cell from (op, index) pairs with stride-2 ops on inputs 0/1 of
reduction cells (``model.py:8-60``), NetworkCIFAR with 3C stem and
reductions at layers//3, 2·layers//3 (``model.py:111-160``).
Drop-path and the auxiliary head are omitted (the reference's FedNAS
path runs with ``auxiliary=False`` and drop_path only at inference-time
default 0.5 never exercised federated); affine BN as in the reference's
fixed cells.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle
from fedml_tpu.models.darts.genotypes import Genotype
from fedml_tpu.models.darts.ops import OPS, FactorizedReduce, ReLUConvBN


class FixedCell(nn.Module):
    genotype: Genotype
    C: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, train: bool = False):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C)(s0, train)
        else:
            s0 = ReLUConvBN(self.C, 1, 1)(s0, train)
        s1 = ReLUConvBN(self.C, 1, 1)(s1, train)

        gene = self.genotype.reduce if self.reduction else self.genotype.normal
        concat = (self.genotype.reduce_concat if self.reduction
                  else self.genotype.normal_concat)
        states = [s0, s1]
        for i in range(len(gene) // 2):
            (n1, i1), (n2, i2) = gene[2 * i], gene[2 * i + 1]
            h1 = OPS[n1](self.C, 2 if self.reduction and i1 < 2 else 1, True)(
                states[i1], train
            )
            h2 = OPS[n2](self.C, 2 if self.reduction and i2 < 2 else 1, True)(
                states[i2], train
            )
            states.append(h1 + h2)
        return jnp.concatenate([states[i] for i in concat], axis=-1)


class NetworkCIFAR(nn.Module):
    genotype: Genotype
    C: int = 36
    num_classes: int = 10
    layers: int = 20

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_curr = 3 * self.C
        s0 = s1 = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )(nn.Conv(c_curr, (3, 3), padding=1, use_bias=False)(x))
        c_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c_curr *= 2
            s0, s1 = s1, FixedCell(
                genotype=self.genotype, C=c_curr, reduction=reduction,
                reduction_prev=reduction_prev,
            )(s0, s1, train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


def darts_network(genotype: Genotype, C=36, num_classes=10, layers=20,
                  image_size=32, in_channels=3) -> ModelBundle:
    """Reference factory ``NetworkCIFAR(C, num_classes, layers, auxiliary,
    genotype)`` (``model.py:111-160``)."""
    return ModelBundle(
        module=NetworkCIFAR(genotype=genotype, C=C, num_classes=num_classes,
                            layers=layers),
        input_shape=(image_size, image_size, in_channels),
    )
