"""DARTS primitive operations in flax.

Parity with the reference ``fedml_api/model/cv/darts/operations.py``:
the 8-primitive OPS table (``operations.py:4-21``), ReLUConvBN
(``:23-35``), SepConv (``:53-70``), DilConv (``:37-51``), Zero
(``:81-91``) and FactorizedReduce (``:93-108``).  Search-stage
BatchNorms are affine-free (``model_search.py:35-36`` passes
``affine=False``), mirrored here with ``use_scale/use_bias=False``.

TPU-first: NHWC; depthwise steps use ``feature_group_count``; every op
keeps static shapes so a MixedOp's 8 branches fuse into one XLA
computation.
"""

from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn
import jax.numpy as jnp

PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)


def _bn(train, affine):
    return nn.BatchNorm(
        use_running_average=not train, momentum=0.9, epsilon=1e-5,
        use_scale=affine, use_bias=affine,
    )


class ReLUConvBN(nn.Module):
    C_out: int
    kernel: int = 1
    stride: int = 1
    affine: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.Conv(self.C_out, (self.kernel, self.kernel),
                    strides=self.stride, padding=self.kernel // 2,
                    use_bias=False)(x)
        return _bn(train, self.affine)(x)


class SepConv(nn.Module):
    C_out: int
    kernel: int
    stride: int
    affine: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_in = x.shape[-1]
        pad = self.kernel // 2
        for i, (stride, c_mid) in enumerate(((self.stride, c_in),
                                             (1, self.C_out))):
            x = nn.relu(x)
            ch = x.shape[-1]
            x = nn.Conv(ch, (self.kernel, self.kernel), strides=stride,
                        padding=pad, feature_group_count=ch,
                        use_bias=False)(x)
            x = nn.Conv(c_mid, (1, 1), use_bias=False)(x)
            x = _bn(train, self.affine)(x)
        return x


class DilConv(nn.Module):
    C_out: int
    kernel: int
    stride: int
    dilation: int = 2
    affine: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        ch = x.shape[-1]
        pad = (self.kernel - 1) * self.dilation // 2
        x = nn.relu(x)
        x = nn.Conv(ch, (self.kernel, self.kernel), strides=self.stride,
                    padding=pad, kernel_dilation=self.dilation,
                    feature_group_count=ch, use_bias=False)(x)
        x = nn.Conv(self.C_out, (1, 1), use_bias=False)(x)
        return _bn(train, self.affine)(x)


class FactorizedReduce(nn.Module):
    C_out: int
    affine: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        a = nn.Conv(self.C_out // 2, (1, 1), strides=2, use_bias=False)(x)
        b = nn.Conv(self.C_out - self.C_out // 2, (1, 1), strides=2,
                    use_bias=False)(x[:, 1:, 1:, :])
        out = jnp.concatenate([a, b], axis=-1)
        return _bn(train, self.affine)(out)


class Pool(nn.Module):
    kind: str  # "max" | "avg"
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.kind == "max":
            return nn.max_pool(x, (3, 3), strides=(self.stride, self.stride),
                               padding=((1, 1), (1, 1)))
        # torch avg_pool count_include_pad=False semantics
        ones = jnp.ones_like(x[..., :1])
        s = nn.avg_pool(x, (3, 3), strides=(self.stride, self.stride),
                        padding=((1, 1), (1, 1)))
        n = nn.avg_pool(ones, (3, 3), strides=(self.stride, self.stride),
                        padding=((1, 1), (1, 1)))
        return s / jnp.maximum(n, 1e-12)


class Zero(nn.Module):
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.stride == 1:
            return jnp.zeros_like(x)
        return jnp.zeros_like(x[:, ::self.stride, ::self.stride, :])


class SkipConnect(nn.Module):
    C_out: int
    stride: int
    affine: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.stride == 1:
            return x
        return FactorizedReduce(self.C_out, affine=self.affine)(x, train)


# primitive name -> module factory(C, stride, affine) — operations.py:4-21
OPS: Dict[str, Callable] = {
    "none": lambda C, stride, affine: Zero(stride),
    "max_pool_3x3": lambda C, stride, affine: Pool("max", stride),
    "avg_pool_3x3": lambda C, stride, affine: Pool("avg", stride),
    "skip_connect": lambda C, stride, affine: SkipConnect(C, stride, affine),
    "sep_conv_3x3": lambda C, stride, affine: SepConv(C, 3, stride, affine),
    "sep_conv_5x5": lambda C, stride, affine: SepConv(C, 5, stride, affine),
    "dil_conv_3x3": lambda C, stride, affine: DilConv(C, 3, stride, 2, affine),
    "dil_conv_5x5": lambda C, stride, affine: DilConv(C, 5, stride, 2, affine),
}
