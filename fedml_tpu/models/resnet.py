"""CIFAR ResNets with BatchNorm (reference ``fedml_api/model/cv/resnet.py``).

Architecture parity with the reference (``resnet.py:112-230``): 3×3/16
CIFAR stem, three stages at 16/32/64 planes with stride-2 transitions,
global average pool, linear head.  The reference's ``resnet56``/
``resnet110`` factories use **Bottleneck** blocks with [6,6,6]/[12,12,12]
(``resnet.py:202-244``) — matched here, plus BasicBlock variants.

TPU-first choices: NHWC layout, flax BatchNorm with explicit
``batch_stats`` collection (FedAvg averages them, matching reference
behavior — SURVEY.md §7 BN note), optional ``KD=True`` feature output
used by FedGKT-style distillation (``resnet.py:188-199``).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle

ModuleDef = Any


def _norm(train: bool, name=None):
    return nn.BatchNorm(
        use_running_average=not train, momentum=0.9, epsilon=1e-5, name=name
    )


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        y = nn.Conv(self.planes, (3, 3), strides=self.stride, padding=1, use_bias=False)(x)
        y = _norm(train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False)(y)
        y = _norm(train)(y)
        if identity.shape != y.shape:
            identity = nn.Conv(
                self.planes, (1, 1), strides=self.stride, use_bias=False
            )(x)
            identity = _norm(train)(identity)
        return nn.relu(y + identity)


class Bottleneck(nn.Module):
    planes: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.planes * self.expansion
        identity = x
        y = nn.Conv(self.planes, (1, 1), use_bias=False)(x)
        y = _norm(train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), strides=self.stride, padding=1, use_bias=False)(y)
        y = _norm(train)(y)
        y = nn.relu(y)
        y = nn.Conv(out_ch, (1, 1), use_bias=False)(y)
        y = _norm(train)(y)
        if identity.shape != y.shape:
            identity = nn.Conv(out_ch, (1, 1), strides=self.stride, use_bias=False)(x)
            identity = _norm(train)(identity)
        return nn.relu(y + identity)


class CifarResNet(nn.Module):
    block: Callable
    layers: Sequence[int]
    num_classes: int = 10
    return_features: bool = False  # the reference's KD flag

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False)(x)
        x = _norm(train)(x)
        x = nn.relu(x)
        for stage, (planes, n_blocks) in enumerate(zip((16, 32, 64), self.layers)):
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = self.block(planes=planes, stride=stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(x)
        if self.return_features:
            return x, logits
        return logits


def _bundle(block, layers, num_classes, image_size=32):
    return ModelBundle(
        module=CifarResNet(block=block, layers=layers, num_classes=num_classes),
        input_shape=(image_size, image_size, 3),
    )


def resnet20(num_classes=10, **kw):
    return _bundle(BasicBlock, (3, 3, 3), num_classes, **kw)


def resnet32(num_classes=10, **kw):
    return _bundle(BasicBlock, (5, 5, 5), num_classes, **kw)


def resnet44(num_classes=10, **kw):
    return _bundle(BasicBlock, (7, 7, 7), num_classes, **kw)


def resnet56(num_classes=10, **kw):
    """Reference factory: ResNet(Bottleneck, [6,6,6]) (``resnet.py:202-209``)."""
    return _bundle(Bottleneck, (6, 6, 6), num_classes, **kw)


def resnet110(num_classes=10, **kw):
    """Reference factory: ResNet(Bottleneck, [12,12,12]) (``resnet.py:225-232``)."""
    return _bundle(Bottleneck, (12, 12, 12), num_classes, **kw)
