"""MobileNetV3 (LARGE / SMALL).

Architecture parity with the reference
``fedml_api/model/cv/mobilenet_v3.py``: hard-sigmoid/hard-swish
(``mobilenet_v3.py:35-52``), dense squeeze-excite (``:64-81``),
MobileBlock inverted residuals (``:84-134``), and the LARGE/SMALL stage
tables (``:143-161`` / ``:196-207``) with a width multiplier rounded by
``_make_divisible`` (``:54-61``).

TPU-first: NHWC, grouped conv for the depthwise step, 1×1 convs as the
head (as in the reference) which XLA folds onto the MXU.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle

# (in, out, kernel, stride, nonlinear, se, expansion) — mobilenet_v3.py:143-161
LARGE = (
    (16, 16, 3, 1, "RE", False, 16),
    (16, 24, 3, 2, "RE", False, 64),
    (24, 24, 3, 1, "RE", False, 72),
    (24, 40, 5, 2, "RE", True, 72),
    (40, 40, 5, 1, "RE", True, 120),
    (40, 40, 5, 1, "RE", True, 120),
    (40, 80, 3, 2, "HS", False, 240),
    (80, 80, 3, 1, "HS", False, 200),
    (80, 80, 3, 1, "HS", False, 184),
    (80, 80, 3, 1, "HS", False, 184),
    (80, 112, 3, 1, "HS", True, 480),
    (112, 112, 3, 1, "HS", True, 672),
    (112, 160, 5, 1, "HS", True, 672),
    (160, 160, 5, 2, "HS", True, 672),
    (160, 160, 5, 1, "HS", True, 960),
)
# mobilenet_v3.py:196-207
SMALL = (
    (16, 16, 3, 2, "RE", True, 16),
    (16, 24, 3, 2, "RE", False, 72),
    (24, 24, 3, 1, "RE", False, 88),
    (24, 40, 5, 2, "RE", True, 96),
    (40, 40, 5, 1, "RE", True, 240),
    (40, 40, 5, 1, "RE", True, 240),
    (40, 48, 5, 1, "HS", True, 120),
    (48, 48, 5, 1, "HS", True, 144),
    (48, 96, 5, 2, "HS", True, 288),
    (96, 96, 5, 1, "HS", True, 576),
    (96, 96, 5, 1, "HS", True, 576),
)


def make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def h_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def h_swish(x):
    return x * h_sigmoid(x)


def _bn(train):
    return nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5)


class SqueezeExcite(nn.Module):
    """Dense SE block (reference SqueezeBlock, mobilenet_v3.py:64-81)."""

    divide: int = 4

    @nn.compact
    def __call__(self, x):
        ch = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(ch // self.divide)(s))
        s = h_sigmoid(nn.Dense(ch)(s))
        return x * s[:, None, None, :]


class MobileBlock(nn.Module):
    out_ch: int
    kernel: int
    stride: int
    nonlinear: str  # "RE" | "HS"
    se: bool
    exp_size: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = nn.relu if self.nonlinear == "RE" else h_swish
        identity = x
        in_ch = x.shape[-1]
        # 1x1 expand
        y = nn.Conv(self.exp_size, (1, 1), use_bias=False)(x)
        y = act(_bn(train)(y))
        # depthwise
        y = nn.Conv(self.exp_size, (self.kernel, self.kernel),
                    strides=self.stride, padding=self.kernel // 2,
                    feature_group_count=self.exp_size, use_bias=False)(y)
        y = _bn(train)(y)
        if self.se:
            y = SqueezeExcite()(y)
        y = act(y)
        # pointwise project
        y = nn.Conv(self.out_ch, (1, 1), use_bias=False)(y)
        y = act(_bn(train)(y))
        if self.stride == 1 and in_ch == self.out_ch:
            y = y + identity
        return y


class MobileNetV3(nn.Module):
    model_mode: str = "LARGE"
    num_classes: int = 10
    multiplier: float = 1.0
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        layers = LARGE if self.model_mode == "LARGE" else SMALL
        m = self.multiplier
        x = nn.Conv(make_divisible(16 * m), (3, 3), strides=2, padding=1)(x)
        x = h_swish(_bn(train)(x))
        for (_, out_ch, k, s, nl, se, exp) in layers:
            x = MobileBlock(
                out_ch=make_divisible(out_ch * m), kernel=k, stride=s,
                nonlinear=nl, se=se, exp_size=make_divisible(exp * m),
            )(x, train)
        head = 960 if self.model_mode == "LARGE" else 576
        x = nn.Conv(make_divisible(head * m), (1, 1))(x)
        if self.model_mode == "SMALL":
            # reference SMALL head squeezes before its BN
            # (mobilenet_v3.py:226-231 out_conv1 = Conv+SqueezeBlock+BN)
            x = SqueezeExcite()(x)
        x = h_swish(_bn(train)(x))
        x = jnp.mean(x, axis=(1, 2), keepdims=True)
        x = h_swish(nn.Conv(make_divisible(1280 * m), (1, 1))(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Conv(self.num_classes, (1, 1))(x)
        return x.reshape((x.shape[0], -1))


def mobilenet_v3(num_classes=10, model_mode="LARGE", multiplier=1.0,
                 image_size=224, dropout_rate=0.0):
    """Reference factory (``mobilenet_v3.py:137-141``)."""
    return ModelBundle(
        module=MobileNetV3(model_mode=model_mode, num_classes=num_classes,
                           multiplier=multiplier, dropout_rate=dropout_rate),
        input_shape=(image_size, image_size, 3),
        needs_dropout_rng=dropout_rate > 0,
    )
