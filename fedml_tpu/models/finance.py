"""Finance / VFL party models.

Reference: ``fedml_api/model/finance/`` —
``vfl_feature_extractor.py:1-16`` (Dense → LeakyReLU),
``vfl_classifier.py:1-12`` (single Dense logits head), and the
standalone numpy/torch ``DenseModel``/``LocalModel`` pair
(``vfl_models_standalone.py:6-34, 36-60``).

Here each party's branch is one flax module (extractor + dense head
fused — they always run back-to-back), so a vertical-FL party forward
is a single MXU-friendly matmul chain and the whole multi-party step
jits into one program.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


class VFLFeatureExtractor(nn.Module):
    """Dense(out) → LeakyReLU (reference ``vfl_feature_extractor.py:4-16``)."""

    output_dim: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.leaky_relu(nn.Dense(self.output_dim)(x))


class VFLClassifier(nn.Module):
    """Single Dense logits head (reference ``vfl_classifier.py:4-12``)."""

    output_dim: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.output_dim, use_bias=self.use_bias)(x)


class VFLPartyNet(nn.Module):
    """One vertical party's branch: extractor → logits head.

    Mirrors the guest/host composition in the reference's standalone
    party models (``party_models.py:14-34`` guest = LocalModel +
    DenseModel; ``:95+`` host likewise): the party consumes its private
    feature slice and emits [B, output_dim] logit components that the
    guest sums.
    """

    feature_dim: int
    output_dim: int = 1
    use_bias: bool = True  # reference: guest dense has bias, hosts don't

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = VFLFeatureExtractor(self.feature_dim)(x)
        return VFLClassifier(self.output_dim, use_bias=self.use_bias)(h)


def vfl_party(input_dim: int, feature_dim: int, *, output_dim: int = 1,
              use_bias: bool = True) -> ModelBundle:
    return ModelBundle(
        module=VFLPartyNet(feature_dim, output_dim, use_bias),
        input_shape=(input_dim,),
    )
