"""MobileNet-v1 with a width multiplier.

Architecture parity with the reference ``fedml_api/model/cv/mobilenet.py``:
stem = 3×3 conv(32α) + depthwise-separable(64α) (``mobilenet.py:74-83``),
then four downsample stages 128α/256α/512α(×6)/1024α
(``mobilenet.py:86-205``), global average pool, linear head.  Used in the
benchmark table (MobileNet CIFAR rows, BASELINE.md).

TPU-first: NHWC; depthwise conv = ``nn.Conv(feature_group_count=C)``
which XLA lowers to an MXU-friendly grouped convolution.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


def _bn(train):
    return nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5)


class ConvBN(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (self.kernel, self.kernel),
                    strides=self.stride, padding=1, use_bias=False)(x)
        return nn.relu(_bn(train)(x))


class DepthwiseSeparable(nn.Module):
    """Depthwise 3×3 + BN + relu, pointwise 1×1 + BN + relu
    (reference ``mobilenet.py:7-36``)."""

    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), strides=self.stride, padding=1,
                    feature_group_count=in_ch, use_bias=False)(x)
        x = nn.relu(_bn(train)(x))
        x = nn.Conv(self.features, (1, 1), use_bias=False)(x)
        return nn.relu(_bn(train)(x))


class MobileNet(nn.Module):
    width_multiplier: float = 1.0
    num_classes: int = 100

    @nn.compact
    def __call__(self, x, train: bool = False):
        a = self.width_multiplier
        c = lambda ch: int(ch * a)
        x = ConvBN(c(32))(x, train)
        x = DepthwiseSeparable(c(64))(x, train)
        # stage plan from reference mobilenet.py:86-205
        for planes, blocks in ((128, 2), (256, 2), (512, 6), (1024, 2)):
            for i in range(blocks):
                x = DepthwiseSeparable(c(planes), stride=2 if i == 0 else 1)(
                    x, train
                )
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def mobilenet(num_classes=100, width_multiplier=1.0, image_size=32):
    """Reference factory ``mobilenet(class_num=...)`` (``mobilenet.py:208-210``)."""
    return ModelBundle(
        module=MobileNet(width_multiplier=width_multiplier,
                         num_classes=num_classes),
        input_shape=(image_size, image_size, 3),
    )
