"""ImageNet-style ResNets with GroupNorm
(reference ``fedml_api/model/cv/resnet_gn.py`` + ``group_normalization.py``).

GroupNorm replaces BatchNorm so there is no non-averageable running
state — the benchmark model for fed_CIFAR100 (``resnet18_gn`` at
``main_fedavg.py:229-231``; target 44.7 acc, ``benchmark/README.md:55``).
The reference parameterizes ``num_channels_per_group=32``
(``resnet_gn.py:26-34``); flax ``nn.GroupNorm`` takes group count, so we
convert per-layer.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


def _gn(channels: int, channels_per_group: int = 32):
    groups = max(1, channels // channels_per_group)
    return nn.GroupNorm(num_groups=groups, epsilon=1e-5)


class BasicBlockGN(nn.Module):
    planes: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        y = nn.Conv(self.planes, (3, 3), strides=self.stride, padding=1, use_bias=False)(x)
        y = _gn(self.planes)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False)(y)
        y = _gn(self.planes)(y)
        if identity.shape != y.shape:
            identity = nn.Conv(self.planes, (1, 1), strides=self.stride, use_bias=False)(x)
            identity = _gn(self.planes)(identity)
        return nn.relu(y + identity)


class BottleneckGN(nn.Module):
    planes: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.planes * self.expansion
        identity = x
        y = nn.Conv(self.planes, (1, 1), use_bias=False)(x)
        y = _gn(self.planes)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), strides=self.stride, padding=1, use_bias=False)(y)
        y = _gn(self.planes)(y)
        y = nn.relu(y)
        y = nn.Conv(out_ch, (1, 1), use_bias=False)(y)
        y = _gn(out_ch)(y)
        if identity.shape != y.shape:
            identity = nn.Conv(out_ch, (1, 1), strides=self.stride, use_bias=False)(x)
            identity = _gn(out_ch)(identity)
        return nn.relu(y + identity)


class ResNetGN(nn.Module):
    block: Callable
    layers: Sequence[int]
    num_classes: int = 100
    small_input: bool = True  # 32×32 federated images: 3×3 stem, no maxpool

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.small_input:
            x = nn.Conv(64, (3, 3), padding=1, use_bias=False)(x)
            x = _gn(64)(x)
            x = nn.relu(x)
        else:
            x = nn.Conv(64, (7, 7), strides=2, padding=3, use_bias=False)(x)
            x = _gn(64)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, (planes, n_blocks) in enumerate(
            zip((64, 128, 256, 512), self.layers)
        ):
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = self.block(planes=planes, stride=stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def _bundle(block, layers, num_classes, image_size=32, small_input=True):
    return ModelBundle(
        module=ResNetGN(
            block=block, layers=layers, num_classes=num_classes, small_input=small_input
        ),
        input_shape=(image_size, image_size, 3),
    )


def resnet18_gn(num_classes=100, **kw):
    return _bundle(BasicBlockGN, (2, 2, 2, 2), num_classes, **kw)


def resnet34_gn(num_classes=100, **kw):
    return _bundle(BasicBlockGN, (3, 4, 6, 3), num_classes, **kw)


def resnet50_gn(num_classes=100, **kw):
    return _bundle(BottleneckGN, (3, 4, 6, 3), num_classes, **kw)


def resnet101_gn(num_classes=100, **kw):
    return _bundle(BottleneckGN, (3, 4, 23, 3), num_classes, **kw)


def resnet152_gn(num_classes=100, **kw):
    return _bundle(BottleneckGN, (3, 8, 36, 3), num_classes, **kw)
