"""Linear models (reference: ``fedml_api/model/linear/lr.py:4-11``)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


class LogisticRegression(nn.Module):
    """Single dense layer over flattened input; logits out.

    The reference applies an explicit sigmoid (``lr.py:10``) and then
    trains with CrossEntropyLoss anyway; we emit raw logits and keep the
    softmax inside the loss, which is the numerically sane reading of the
    same model.
    """

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def logistic_regression(input_dim: int = 784, num_classes: int = 10) -> ModelBundle:
    return ModelBundle(
        module=LogisticRegression(num_classes=num_classes),
        input_shape=(input_dim,),
    )
