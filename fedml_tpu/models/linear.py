"""Linear models (reference: ``fedml_api/model/linear/lr.py:4-11``)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


class LogisticRegression(nn.Module):
    """Single dense layer over flattened input; logits out.

    The reference applies an explicit sigmoid (``lr.py:10``) and then
    trains with CrossEntropyLoss anyway; we emit raw logits and keep the
    softmax inside the loss, which is the numerically sane reading of the
    same model.
    """

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def logistic_regression(input_dim: int = 784, num_classes: int = 10) -> ModelBundle:
    return ModelBundle(
        module=LogisticRegression(num_classes=num_classes),
        input_shape=(input_dim,),
    )


class MLP2(nn.Module):
    """Two-layer perceptron: the near-zero-compile stand-in the scaling
    harness uses for CI runs (``tools/bench_scaling.py --model mlp``)."""

    hidden: int = 32
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, dtype=jnp.float32)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def mlp2(input_dim: int, hidden: int = 32, num_classes: int = 10,
         input_shape=None) -> ModelBundle:
    return ModelBundle(
        module=MLP2(hidden=hidden, num_classes=num_classes),
        input_shape=tuple(input_shape) if input_shape else (input_dim,),
    )
