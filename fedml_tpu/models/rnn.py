"""Federated language models (reference ``fedml_api/model/nlp/rnn.py``).

- ``RNNOriginalFedAvg`` — McMahan et al. Shakespeare next-char model
  (``rnn.py:4-37``): embed(90→8) → 2-layer LSTM(256) → dense(vocab),
  predicting from the final hidden state.
- ``RNNStackOverflow`` — Adaptive Federated Optimization Table 9 NWP
  model (``rnn.py:39-77``): embed(10004→96) → 1-layer LSTM(670) →
  dense 96 → dense vocab, per-position logits.

TPU-first: LSTMs run as ``nn.RNN`` (lax.scan over an LSTMCell) in
bfloat16-friendly f32; sequences are fixed-length so everything jits
statically.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


class RNNOriginalFedAvg(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256
    seq_output: bool = False  # fed_shakespeare variant: logits at every step

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        h = nn.RNN(nn.LSTMCell(self.hidden_size))(h)
        h = nn.RNN(nn.LSTMCell(self.hidden_size))(h)
        if self.seq_output:
            return nn.Dense(self.vocab_size)(h)  # [B, T, V]
        return nn.Dense(self.vocab_size)(h[:, -1])  # [B, V]


class RNNStackOverflow(nn.Module):
    vocab_size: int = 10000
    num_oov_buckets: int = 1
    embedding_size: int = 96
    latent_size: int = 670
    num_layers: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        extended = self.vocab_size + 3 + self.num_oov_buckets  # pad/bos/eos/oov
        h = nn.Embed(extended, self.embedding_size)(x.astype(jnp.int32))
        for _ in range(self.num_layers):
            h = nn.RNN(nn.LSTMCell(self.latent_size))(h)
        h = nn.Dense(self.embedding_size)(h)
        return nn.Dense(extended)(h)  # [B, T, V]


def rnn_shakespeare(seq_len: int = 80, vocab_size: int = 90, seq_output: bool = False):
    return ModelBundle(
        module=RNNOriginalFedAvg(vocab_size=vocab_size, seq_output=seq_output),
        input_shape=(seq_len,),
        input_dtype=jnp.int32,
    )


def rnn_stackoverflow(seq_len: int = 20, vocab_size: int = 10000):
    return ModelBundle(
        module=RNNStackOverflow(vocab_size=vocab_size),
        input_shape=(seq_len,),
        input_dtype=jnp.int32,
    )
