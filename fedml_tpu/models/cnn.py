"""FedAvg benchmark CNNs (reference: ``fedml_api/model/cv/cnn.py``).

- ``CNNOriginalFedAvg`` — the McMahan et al. 2016 MNIST/FEMNIST CNN
  (reference ``cnn.py:5-70``): 2×[5×5 conv → 2×2 maxpool] → dense 512 →
  softmax head.
- ``CNNDropOut`` — the TFF baseline variant with dropout
  (reference ``cnn.py:72-146``): 2×[3×3 conv] → maxpool → dropout 0.25 →
  dense 128 → dropout 0.5 → head.

NHWC layout throughout (TPU-native; torch reference is NCHW).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


class CNNOriginalFedAvg(nn.Module):
    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat 784 input
            side = int(x.shape[-1] ** 0.5)
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(10 if self.only_digits else self.num_classes)(x)


class CNNDropOut(nn.Module):
    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:
            side = int(x.shape[-1] ** 0.5)
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else self.num_classes)(x)


def cnn_original_fedavg(only_digits: bool = True, side: int = 28) -> ModelBundle:
    return ModelBundle(
        module=CNNOriginalFedAvg(only_digits=only_digits),
        input_shape=(side, side, 1),
    )


def cnn_dropout(only_digits: bool = False, side: int = 28) -> ModelBundle:
    return ModelBundle(
        module=CNNDropOut(only_digits=only_digits),
        input_shape=(side, side, 1),
        needs_dropout_rng=True,
    )


class _CNNBottom(nn.Module):
    """Conv half of the McMahan CNN — the SplitNN client side
    (reference splits at the flatten boundary, ``split_nn/client.py``)."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:
            side = int(x.shape[-1] ** 0.5)
            x = x.reshape((x.shape[0], side, side, -1))
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x.reshape((x.shape[0], -1))


class _CNNTop(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.num_classes)(x)


def cnn_split_pair(num_classes: int, input_shape) -> tuple:
    """(bottom, top) ModelBundles for SplitNN over image data."""
    side = input_shape[0] if len(input_shape) >= 2 else int(input_shape[0] ** 0.5)
    feat = (side // 4) * (side // 4) * 64
    return (
        ModelBundle(module=_CNNBottom(), input_shape=tuple(input_shape)),
        ModelBundle(module=_CNNTop(num_classes=num_classes),
                    input_shape=(feat,)),
    )
