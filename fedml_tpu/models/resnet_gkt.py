"""Split ResNets for group knowledge transfer (FedGKT).

Architecture parity with the reference
``fedml_api/model/cv/resnet56_gkt/``:

- client nets (``resnet_client.py:206-240``): CIFAR stem (3×3 conv →
  BN → relu) whose output is the **extracted feature map** shipped to
  the server, followed by layer1 only, global pool and a local head;
  ``resnet5_56`` = BasicBlock×1, ``resnet8_56`` = Bottleneck×2.
- server net (``resnet_server.py:113-190``): consumes the 16-channel
  feature map directly (its stem is disabled, ``resnet_server.py:186-189``),
  runs the full three stages, pool, head; ``resnet56_server`` =
  Bottleneck [6,6,6], ``resnet110_server`` = Bottleneck [12,12,12].

Both client and server return ``(logits, features)`` /
``logits`` respectively; the FedGKT algorithm exchanges features and
logits, never weights (SURVEY.md §2.2 row 15).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle
from fedml_tpu.models.resnet import BasicBlock, Bottleneck, _norm


class GKTClientResNet(nn.Module):
    block: type
    n_blocks: int
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False)(x)
        x = _norm(train)(x)
        x = nn.relu(x)
        features = x  # B×H×W×16 — the FedGKT payload
        for _ in range(self.n_blocks):
            x = self.block(planes=16, stride=1)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(x)
        return logits, features


class GKTServerResNet(nn.Module):
    layers: Sequence[int]
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        # input is the client's 16-channel feature map; no stem
        for stage, (planes, n_blocks) in enumerate(zip((16, 32, 64), self.layers)):
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = Bottleneck(planes=planes, stride=stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


@dataclasses.dataclass
class GKTClientBundle(ModelBundle):
    """ModelBundle whose forward returns (logits, features)."""

    def apply_train(self, variables, x, rng=None):
        if "batch_stats" in variables:
            (logits, feats), mutated = self.module.apply(
                variables, x, train=True, mutable=["batch_stats"]
            )
            return (logits, feats), {**variables,
                                     "batch_stats": mutated["batch_stats"]}
        out = self.module.apply(variables, x, train=True)
        return out, variables

    def apply_eval(self, variables, x):
        return self.module.apply(variables, x, train=False)


def resnet5_56(num_classes=10, image_size=32) -> GKTClientBundle:
    """Reference: ResNet(BasicBlock, [1,2,2]) with only layer1 active
    (``resnet_client.py:206-216``)."""
    return GKTClientBundle(
        module=GKTClientResNet(block=BasicBlock, n_blocks=1,
                               num_classes=num_classes),
        input_shape=(image_size, image_size, 3),
    )


def resnet8_56(num_classes=10, image_size=32) -> GKTClientBundle:
    """Reference: ResNet(Bottleneck, [2,2,2]) with only layer1 active
    (``resnet_client.py:232-240``)."""
    return GKTClientBundle(
        module=GKTClientResNet(block=Bottleneck, n_blocks=2,
                               num_classes=num_classes),
        input_shape=(image_size, image_size, 3),
    )


def _server_bundle(layers, num_classes, image_size):
    # server input spec is the FEATURE map, 16 channels at stem resolution
    return ModelBundle(
        module=GKTServerResNet(layers=layers, num_classes=num_classes),
        input_shape=(image_size, image_size, 16),
    )


def resnet56_server(num_classes=10, image_size=32) -> ModelBundle:
    """Reference: ResNet(Bottleneck, [6,6,6]) (``resnet_server.py`` factory)."""
    return _server_bundle((6, 6, 6), num_classes, image_size)


def resnet110_server(num_classes=10, image_size=32) -> ModelBundle:
    return _server_bundle((12, 12, 12), num_classes, image_size)
