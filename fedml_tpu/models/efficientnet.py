"""EfficientNet-B0..B8.

Architecture parity with the reference
``fedml_api/model/cv/efficientnet.py`` (MBConvBlock ``:36-135``,
EfficientNet ``:138-303``) and its utils (compound-scaling table
``efficientnet_utils.py:430-450``, default block args decoded from the
r/k/s/e/i/o/se strings at ``:453-520``, round_filters/round_repeats
``:81-110``): swish activation, SE with 1×1 convs, drop-connect that
scales linearly with block depth, stem 32 → head 1280.

TPU-first: NHWC; static Python loop over blocks (traced once);
stochastic depth implemented with an explicit rng; grouped conv for the
depthwise step.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.base import ModelBundle


@dataclasses.dataclass(frozen=True)
class BlockArgs:
    num_repeat: int
    kernel_size: int
    stride: int
    expand_ratio: int
    input_filters: int
    output_filters: int
    se_ratio: float = 0.25


# decoded form of the reference's default block strings
# (efficientnet_utils.py:469-478)
DEFAULT_BLOCKS = (
    BlockArgs(1, 3, 1, 1, 32, 16),
    BlockArgs(2, 3, 2, 6, 16, 24),
    BlockArgs(2, 5, 2, 6, 24, 40),
    BlockArgs(3, 3, 2, 6, 40, 80),
    BlockArgs(3, 5, 1, 6, 80, 112),
    BlockArgs(4, 5, 2, 6, 112, 192),
    BlockArgs(1, 3, 1, 6, 192, 320),
)

# name -> (width_coeff, depth_coeff, resolution, dropout)
# (efficientnet_utils.py:437-448)
PARAMS = {
    "efficientnet-b0": (1.0, 1.0, 224, 0.2),
    "efficientnet-b1": (1.0, 1.1, 240, 0.2),
    "efficientnet-b2": (1.1, 1.2, 260, 0.3),
    "efficientnet-b3": (1.2, 1.4, 300, 0.3),
    "efficientnet-b4": (1.4, 1.8, 380, 0.4),
    "efficientnet-b5": (1.6, 2.2, 456, 0.4),
    "efficientnet-b6": (1.8, 2.6, 528, 0.5),
    "efficientnet-b7": (2.0, 3.1, 600, 0.5),
    "efficientnet-b8": (2.2, 3.6, 672, 0.5),
}


def round_filters(filters: int, width_coeff: float, divisor: int = 8) -> int:
    """Reference ``efficientnet_utils.py:81-101`` — the same divisor
    rounding as MobileNetV3's ``make_divisible``."""
    from fedml_tpu.models.mobilenet_v3 import make_divisible

    return make_divisible(filters * width_coeff, divisor)


def round_repeats(repeats: int, depth_coeff: float) -> int:
    return int(math.ceil(depth_coeff * repeats))


def _bn(train):
    # reference: bn momentum 0.99, eps 1e-3 (efficientnet_utils.py global params)
    return nn.BatchNorm(use_running_average=not train, momentum=0.99,
                        epsilon=1e-3)


def drop_connect(x, rate, deterministic, rng):
    """Per-sample stochastic depth (reference ``efficientnet_utils.py``
    drop_connect)."""
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, (x.shape[0], 1, 1, 1))
    return x * mask / keep


class MBConvBlock(nn.Module):
    """Mobile inverted residual bottleneck + SE (reference ``efficientnet.py:36-135``)."""

    kernel_size: int
    stride: int
    expand_ratio: int
    output_filters: int
    se_ratio: float
    drop_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        inputs = x
        in_ch = x.shape[-1]
        mid = in_ch * self.expand_ratio
        if self.expand_ratio != 1:
            x = nn.Conv(mid, (1, 1), use_bias=False)(x)
            x = nn.swish(_bn(train)(x))
        x = nn.Conv(mid, (self.kernel_size, self.kernel_size),
                    strides=self.stride, padding="SAME",
                    feature_group_count=mid, use_bias=False)(x)
        x = nn.swish(_bn(train)(x))
        if 0 < self.se_ratio <= 1:
            squeezed = max(1, int(in_ch * self.se_ratio))
            s = jnp.mean(x, axis=(1, 2), keepdims=True)
            s = nn.swish(nn.Conv(squeezed, (1, 1))(s))
            s = nn.sigmoid(nn.Conv(mid, (1, 1))(s))
            x = x * s
        x = nn.Conv(self.output_filters, (1, 1), use_bias=False)(x)
        x = _bn(train)(x)
        if self.stride == 1 and in_ch == self.output_filters:
            rng = (self.make_rng("dropout")
                   if train and self.drop_rate > 0 and self.has_rng("dropout")
                   else None)
            x = drop_connect(x, self.drop_rate, not train, rng)
            x = x + inputs
        return x


class EfficientNet(nn.Module):
    width_coeff: float = 1.0
    depth_coeff: float = 1.0
    dropout_rate: float = 0.2
    drop_connect_rate: float = 0.2
    num_classes: int = 1000
    blocks_args: Sequence[BlockArgs] = DEFAULT_BLOCKS

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(round_filters(32, self.width_coeff), (3, 3), strides=2,
                    padding="SAME", use_bias=False)(x)
        x = nn.swish(_bn(train)(x))

        total_blocks = sum(
            round_repeats(b.num_repeat, self.depth_coeff)
            for b in self.blocks_args
        )
        idx = 0
        for b in self.blocks_args:
            out = round_filters(b.output_filters, self.width_coeff)
            for rep in range(round_repeats(b.num_repeat, self.depth_coeff)):
                x = MBConvBlock(
                    kernel_size=b.kernel_size,
                    stride=b.stride if rep == 0 else 1,
                    expand_ratio=b.expand_ratio,
                    output_filters=out,
                    se_ratio=b.se_ratio,
                    # linear depth scaling, reference efficientnet.py:193-196
                    drop_rate=self.drop_connect_rate * idx / total_blocks,
                )(x, train)
                idx += 1
        x = nn.Conv(round_filters(1280, self.width_coeff), (1, 1),
                    use_bias=False)(x)
        x = nn.swish(_bn(train)(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def efficientnet(name: str = "efficientnet-b0", num_classes: int = 1000,
                 image_size: Optional[int] = None) -> ModelBundle:
    """Reference factory ``EfficientNet.from_name`` (``efficientnet.py:305-325``)."""
    w, d, res, dropout = PARAMS[name]
    return ModelBundle(
        module=EfficientNet(width_coeff=w, depth_coeff=d, dropout_rate=dropout,
                            num_classes=num_classes),
        input_shape=(image_size or res, image_size or res, 3),
        needs_dropout_rng=True,
    )
