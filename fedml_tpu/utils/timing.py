"""Shared benchmark timing methodology (bench.py, tools/bench_scaling.py).

Two axon-tunnel hazards, both observed live on this project:
- ``jax.block_until_ready`` alone can return before the work is done
  (timings come out ~45x too fast) — every timed region must ALSO
  force a scalar readback.
- After the two warmup compiles (one per input signature) a one-off
  ~6s slow execution can still follow — warm up until two consecutive
  fully-synced rounds agree, or aggregate timings are dominated by it.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Tuple

import numpy as np


def sync_round(state: Any, metrics: Any) -> float:
    """Full sync the tunnel can't fake: block AND read scalars back.
    Returns the sum over ALL metric leaves — a NaN/inf in any metric
    (loss included) poisons the result so the caller's finiteness check
    fires."""
    import jax

    jax.block_until_ready((state, metrics))
    return float(sum(float(np.sum(l))
                     for l in jax.tree_util.tree_leaves(metrics)))


def measure_rounds(
    round_fn: Callable,
    state: Any,
    args_dev: Tuple,
    rounds: int,
    *,
    max_warmup: int = 6,
    agree_rtol: float = 0.2,
) -> Tuple[float, Any]:
    """(median seconds per fully-synced round, final state)."""
    prev = None
    for i in range(max_warmup):
        t0 = time.perf_counter()
        state, m = round_fn(state, *args_dev)
        sync_round(state, m)
        dt = time.perf_counter() - t0
        # agreement counts only from round 3 on: the two compile rounds
        # can agree with each other while the slow post-compile
        # execution is still ahead
        if i >= 2 and prev is not None and abs(dt - prev) / max(dt, prev) < agree_rtol:
            break
        prev = dt
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        state, m = round_fn(state, *args_dev)
        scalar = sync_round(state, m)
        times.append(time.perf_counter() - t0)
        if not np.isfinite(scalar):
            raise FloatingPointError(
                f"benchmark round produced non-finite metrics: {scalar}"
            )
    return float(np.median(times)), state
