"""fedml_tpu — a TPU-native federated learning framework.

Built from scratch in JAX/XLA with the capabilities of FedML's research
library (reference: yh-yao/FedML). A TPU pod slice acts as the federated
cluster: each chip hosts one (or more) FL clients on a ``clients`` mesh
axis; the reference's MPI message-passing runtime
(fedml_core/distributed/communication) is replaced by XLA collectives —
broadcast by replication for model sync, masked weighted ``lax.psum``
for aggregation with per-round client subsampling.

Layer map (mirrors SURVEY.md §1, redesigned TPU-first):

  experiments/   entry points (typed config + CLI)          [ref L5]
  algorithms/    FedAvg family, GKT, SplitNN, VFL, NAS ...  [ref L4]
  models/ data/  flax model zoo + federated data pipeline   [ref L3]
  core/ comm/    round engine, partitioners, messages       [ref L2]
  parallel/ ops/ mesh/shard_map + pallas kernels            [ref L1]
"""

__version__ = "0.1.0"

from fedml_tpu.core.types import FedDataset  # noqa: F401
