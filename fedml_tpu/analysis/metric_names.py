"""metric-name — every emitted series/event name is in the registry.

A typo'd metric name does not error: the registry happily creates a
fresh series that no dashboard, summary tool, or assertion ever reads —
the emission "works" and the data silently never aggregates with the
series it was meant to extend (the failure mode PROFILE.md's appendix
can only document after the fact).  This rule makes
``fedml_tpu/obs/metric_schema.py`` the single checked-in source of
truth: every literal name passed to ``inc`` / ``gauge_set`` /
``gauge_max`` / ``observe`` / ``counter_value`` must be registered with
the matching type, every ``event(kind, ...)`` kind must be a registered
event, and dynamic f-string names (``f"span.{name}_s"``) must match a
registered pattern of the right type.

Schema resolution order: an ``obs/metric_schema.py`` in the scanned
file set, else one found on disk next to a scanned file (walking up to
the package root).  Execution is plain ``exec`` of the schema module —
it is stdlib-only literals by construction.
"""

from __future__ import annotations

import ast
import fnmatch
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from fedml_tpu.analysis.base import Finding, SourceFile

RULE = "metric-name"

EMITTERS = {
    "inc": "counter",
    "counter_value": "counter",
    "gauge_set": "gauge",
    "gauge_max": "gauge",
    "observe": "histogram",
}

SCHEMA_REL_SUFFIX = "obs/metric_schema.py"


def _load_schema(files: Sequence[SourceFile]):
    """(metrics: name->type, events: set, patterns: pattern->type) or
    None when no schema module can be found."""
    text: Optional[str] = None
    for sf in files:
        if sf.rel.endswith(SCHEMA_REL_SUFFIX):
            text = sf.text
            break
    if text is None:
        for sf in files:
            p = Path(sf.path).resolve()
            for parent in p.parents:
                cand = parent / "fedml_tpu" / "obs" / "metric_schema.py"
                if cand.is_file():
                    text = cand.read_text(encoding="utf-8")
                    break
            if text is not None:
                break
    if text is None:
        return None
    ns: dict = {}
    exec(compile(text, SCHEMA_REL_SUFFIX, "exec"), ns)  # stdlib-only module
    metrics: Dict[str, str] = {}
    for kind, table in (("counter", "COUNTERS"), ("gauge", "GAUGES"),
                        ("histogram", "HISTOGRAMS")):
        for name in ns.get(table, {}):
            metrics[name] = kind
    events = set(ns.get("EVENTS", {}))
    patterns: Dict[str, str] = dict(ns.get("METRIC_PATTERNS", {}))
    return metrics, events, patterns


def _name_arg(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """(name-or-pattern, is_pattern) from the first argument, or None
    when it is not a string (Histogram.observe(float) etc.)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr):
        parts: List[str] = []
        for piece in arg.values:
            if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
                parts.append(piece.value)
            else:
                parts.append("*")
        return "".join(parts), True
    return None


def check(files: Sequence[SourceFile]) -> List[Finding]:
    schema = _load_schema(files)
    findings: List[Finding] = []
    if schema is None:
        anchor = files[0].rel if files else "?"
        return [Finding(
            RULE, anchor, 1, 0,
            "metric registry fedml_tpu/obs/metric_schema.py not found in "
            "or near the scanned tree — metric names cannot be checked",
        )]
    metrics, events, patterns = schema
    for sf in files:
        if sf.rel.endswith(SCHEMA_REL_SUFFIX):
            continue  # the registry itself
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in EMITTERS:
                named = _name_arg(node)
                if named is None:
                    continue
                name, is_pattern = named
                findings.extend(_check_metric(
                    sf, node, name, is_pattern, EMITTERS[attr],
                    metrics, patterns,
                ))
            elif attr == "event":
                named = _name_arg(node)
                if named is None or named[1]:
                    continue
                kind = named[0]
                if kind not in events:
                    findings.append(Finding(
                        RULE, sf.rel, node.lineno, node.col_offset,
                        f"event kind '{kind}' is not registered in "
                        "obs/metric_schema.py EVENTS — unregistered "
                        "events silently vanish from every consumer",
                    ))
    return findings


def _check_metric(sf: SourceFile, node: ast.Call, name: str,
                  is_pattern: bool, want_type: str,
                  metrics: Dict[str, str],
                  patterns: Dict[str, str]) -> List[Finding]:
    if is_pattern:
        # a dynamic name must be covered by a registered pattern of the
        # right type (``f"span.{name}_s"`` -> ``span.*_s``)
        for pat, ptype in patterns.items():
            if fnmatch.fnmatchcase(name, pat) and ptype == want_type:
                return []
        return [Finding(
            RULE, sf.rel, node.lineno, node.col_offset,
            f"dynamic metric name '{name}' matches no registered "
            f"{want_type} pattern in obs/metric_schema.py "
            "METRIC_PATTERNS",
        )]
    have = metrics.get(name)
    if have == want_type:
        return []
    if have is not None:
        return [Finding(
            RULE, sf.rel, node.lineno, node.col_offset,
            f"metric '{name}' is registered as a {have} but emitted "
            f"here as a {want_type} — one of the two is wrong",
        )]
    for pat, ptype in patterns.items():
        if fnmatch.fnmatchcase(name, pat):
            if ptype == want_type:
                return []
            return [Finding(
                RULE, sf.rel, node.lineno, node.col_offset,
                f"metric '{name}' matches pattern '{pat}' registered as "
                f"a {ptype} but is emitted here as a {want_type}",
            )]
    return [Finding(
        RULE, sf.rel, node.lineno, node.col_offset,
        f"{want_type} '{name}' is not registered in "
        "obs/metric_schema.py — typo'd series silently never aggregate",
    )]
