"""lock-discipline — guarded attributes only touched under their lock.

The threaded modules (hub reader threads + sender pool, the server's
reader-thread fold + deadline timer, the chaos wrapper's held-message
tables) protect shared state with per-instance locks, but nothing
enforced the association — a new code path touching ``self.pending``
without ``self._round_lock`` compiles, passes single-threaded tests,
and corrupts a round under load.

Convention this rule checks: a class declares

    _GUARDED_BY = {"pending": "_round_lock", "_agg_acc": "_round_lock"}

and every ``self.<attr>`` access (read or write) to a declared attribute
must then sit lexically inside a ``with self.<lock>:`` block.  Escapes:

- ``__init__`` is exempt (construction happens-before publication);
- a method whose ``def`` line carries ``# fedlint: holds=<lock>``
  asserts the caller-holds-the-lock contract for its whole body — a
  promise the runtime verifies via ``analysis.locks.assert_held`` when
  checked locks are enabled;
- nested functions/lambdas reset the held set (they run later, on
  whatever thread calls them — lexical nesting proves nothing).

The checker is intentionally lexical: state snapshotted under the lock
into a local and used outside (the codebase's standard pattern) passes,
because the ``self.<attr>`` access itself is inside the block.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Sequence

from fedml_tpu.analysis.base import Finding, SourceFile

RULE = "lock-discipline"

GUARDED_DECL = "_GUARDED_BY"
EXEMPT_METHODS = ("__init__",)


def _guarded_map(cls: ast.ClassDef) -> Dict[str, str]:
    """Parse the class's ``_GUARDED_BY`` dict literal, if any."""
    for node in cls.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign):
            target = node.target
            value = node.value
        if not (isinstance(target, ast.Name) and target.id == GUARDED_DECL):
            continue
        if not isinstance(value, ast.Dict):
            return {}
        out: Dict[str, str] = {}
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                out[k.value] = v.value
        return out
    return {}


def _self_attr(node: ast.AST) -> str:
    """``_lock`` for a plain ``self._lock`` expression, else ''."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def check(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                guarded = _guarded_map(node)
                if guarded:
                    findings.extend(_check_class(sf, node, guarded))
    return findings


def _check_class(sf: SourceFile, cls: ast.ClassDef,
                 guarded: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    lock_names = frozenset(guarded.values())
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name in EXEMPT_METHODS:
            continue
        held = frozenset(
            h for h in sf.holds.get(item.lineno, ()) if h in lock_names
        )
        for stmt in item.body:
            _walk(sf, cls.name, item.name, stmt, guarded, held, findings)
    return findings


def _walk(sf: SourceFile, cls_name: str, meth_name: str, node: ast.AST,
          guarded: Dict[str, str], held: FrozenSet[str],
          findings: List[Finding]) -> None:
    if isinstance(node, (ast.With, ast.AsyncWith)):
        acquired = set()
        for item in node.items:
            # the lock expression itself evaluates BEFORE the acquire
            _walk(sf, cls_name, meth_name, item.context_expr, guarded,
                  held, findings)
            name = _self_attr(item.context_expr)
            if name in guarded.values():
                acquired.add(name)
        inner = held | acquired
        for stmt in node.body:
            _walk(sf, cls_name, meth_name, stmt, guarded, inner, findings)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        # a nested callable runs later, on an arbitrary thread: locks
        # held lexically around its DEFINITION prove nothing
        for child in ast.iter_child_nodes(node):
            _walk(sf, cls_name, meth_name, child, guarded,
                  frozenset(), findings)
        return
    attr = _self_attr(node)
    if attr and attr in guarded and guarded[attr] not in held:
        lock = guarded[attr]
        findings.append(Finding(
            RULE, sf.rel, node.lineno, node.col_offset,
            f"{cls_name}.{meth_name}: 'self.{attr}' touched outside "
            f"'with self.{lock}' (declared guarded by {GUARDED_DECL}) — "
            f"wrap the access, or annotate the method "
            f"'# fedlint: holds={lock}' if the caller holds it",
        ))
    for child in ast.iter_child_nodes(node):
        _walk(sf, cls_name, meth_name, child, guarded, held, findings)
