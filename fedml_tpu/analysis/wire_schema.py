"""wire-schema — reserved frame-header keys are defined exactly once.

The wire format's reserved keys (``__hub__`` control frames,
``__binlen__`` payload-length announcements, ``__ndbuf__`` buffer
references, ``__wiretree__`` pytree envelopes, ``__ndarray__`` b64
leaves, ``__trace__`` hop contexts) are protocol: every reader and
writer must agree on the exact byte string.  A literal copy in a second
module is the drift class behind silent wire-format skew — rename the
canonical one (PR 6's ``hub.mcast_frames`` rename was exactly this
class, in metric space) and the copy keeps "working" while routing or
tracing quietly breaks.

Rule: each reserved key may appear as a string literal ONLY in its
defining module, and there only as the right-hand side of its canonical
constant assignment.  Every other use must reference the constant
(``from fedml_tpu.comm.message import HUB_KEY``, ...).

Docstrings and comments are unaffected (the checker compares whole
string-literal VALUES, and comments never reach the AST).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Tuple

from fedml_tpu.analysis.base import Finding, SourceFile

RULE = "wire-schema"

# literal -> (canonical constant, defining module rel path)
RESERVED_KEYS: Dict[str, Tuple[str, str]] = {
    "__hub__": ("HUB_KEY", "fedml_tpu/comm/message.py"),
    "__binlen__": ("FRAME_BINLEN_KEY", "fedml_tpu/comm/message.py"),
    "__ndbuf__": ("FRAME_NDBUF_KEY", "fedml_tpu/comm/message.py"),
    "__wiretree__": ("WIRETREE_KEY", "fedml_tpu/comm/message.py"),
    "__ndarray__": ("NDARRAY_KEY", "fedml_tpu/comm/message.py"),
    "__trace__": ("TRACE_KEY", "fedml_tpu/obs/trace_ctx.py"),
    "__digest__": ("DIGEST_KEY", "fedml_tpu/obs/digest.py"),
    "__shmseq__": ("SHM_SEQ_KEY", "fedml_tpu/comm/message.py"),
    # not a frame-header key but the same drift class: the edge-hub
    # uplink's partial-aggregate message tag is protocol between two
    # tiers of aggregator — a literal copy in a second module would
    # let the tiers skew silently
    "E2S_PARTIAL": ("MSG_TYPE_E2S_PARTIAL", "fedml_tpu/comm/message.py"),
}


def _definition_lines(sf: SourceFile) -> Dict[str, int]:
    """lineno of each module-level ``CONST = "literal"`` assignment."""
    out: Dict[str, int] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.lineno
    return out


def check(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.rel.startswith("fedml_tpu/analysis/"):
            # the checker's own registry (this module) names the keys
            # by necessity; analysis/ is stdlib-only and cannot import
            # the numpy-backed comm.message constants
            continue
        defs = _definition_lines(sf)
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value in RESERVED_KEYS):
                continue
            const, home = RESERVED_KEYS[node.value]
            if sf.rel == home and defs.get(const) == node.lineno:
                continue  # the one canonical definition
            where = (f"defined once in {home}"
                     if sf.rel != home else
                     f"this module's canonical '{const} = ...' assignment")
            findings.append(Finding(
                RULE, sf.rel, node.lineno, node.col_offset,
                f"reserved wire key '{node.value}' as a string literal — "
                f"use the constant {const} ({where}); literal copies "
                "drift when the protocol evolves",
            ))
    return findings
