"""jit-purity — no host side effects reachable from jit/shard_map roots.

A function traced by ``jax.jit`` / ``jax.shard_map`` / ``pjit`` runs its
Python body ONCE, at trace time; host side effects inside it either
vanish on cache hits (a ``print``/telemetry call that "works" on round 0
and never again), silently force device→host syncs (``.item()``), or
poison determinism (numpy/stdlib RNG draws baked into the trace).  This
rule finds jit roots statically, walks the call graph conservatively,
and flags host effects inside any reachable function body:

- ``print(...)``;
- host clocks: ``time.time/perf_counter/monotonic/sleep/...``;
- device→host sync: ``.item()``;
- untraced RNG: ``np.random.*`` and stdlib ``random.*`` draws
  (``jax.random`` is the traced, splittable stream and passes);
- telemetry/logging: ``get_telemetry(...)`` and ``logging.*`` calls.

Root detection (resolvable cases only — a root applied to a CALL
RESULT, e.g. ``jax.jit(make_round_fn(...))``, cannot be traced
statically and is skipped):

- ``jax.jit(f)`` / ``pjit(f)`` / ``jax.shard_map(f, ...)`` where ``f``
  is a name bound to a def (module-level or nested — resolution is
  innermost-scope-first);
- decorators ``@jax.jit``, ``@jit``, ``@pjit``, ``@jax.jit(...)``, and
  ``@partial(jax.jit, ...)``;
- local aliases of the transforms (``shard_map = jax.shard_map``).

Reachability follows simple-name calls, names passed as call arguments
(``lax.scan(body, ...)`` traces ``body``), and cross-module
``from fedml_tpu.x import f`` / ``mod.f`` references into other scanned
files.  Scope: the whole package.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fedml_tpu.analysis.base import (
    Finding,
    SourceFile,
    dotted_name,
    module_aliases,
    resolve_call_target,
)

RULE = "jit-purity"

JIT_TRANSFORMS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    # the package's version-tolerant shim — call sites import the
    # transform from here, and they are jit roots all the same
    "fedml_tpu.parallel.compat.shard_map",
    # the partition-rule engine's jit entry point (jax.jit with
    # NamedSharding annotations): every function compiled through the
    # sharding subsystem is a jit root for the purity scan too
    "fedml_tpu.parallel.partition.jit_sharded",
}

HOST_CLOCKS = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "time.sleep", "time.process_time",
}


class _Scope:
    """One function body: its statements (nested defs excluded), the
    scope path for name resolution, and its source file."""

    def __init__(self, sf: SourceFile, qual: Tuple[str, ...], node: ast.AST):
        self.sf = sf
        self.qual = qual  # ("make_eval", "evaluate") etc.
        self.node = node


def _collect_defs(sf: SourceFile) -> Dict[Tuple[str, ...], _Scope]:
    defs: Dict[Tuple[str, ...], _Scope] = {}

    def walk(node: ast.AST, scope: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = scope + (child.name,)
                defs[qual] = _Scope(sf, qual, child)
                walk(child, qual)
            elif isinstance(child, ast.ClassDef):
                walk(child, scope + (child.name,))
            else:
                walk(child, scope)

    walk(sf.tree, ())
    return defs


def _own_body(fn: ast.AST):
    """Every node of a function body EXCLUDING nested function/class
    subtrees (those are separate call-graph nodes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        # lambdas stay in: they are traced inline by the enclosing jit.
        # Nested def/class subtrees are separate call-graph nodes.
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


def _resolve_local(name: str, scope: Tuple[str, ...],
                   defs: Dict[Tuple[str, ...], _Scope],
                   ) -> Optional[Tuple[str, ...]]:
    """Innermost-first: ``evaluate`` referenced inside ``make_eval``
    finds ``("make_eval", "evaluate")`` before a module-level def."""
    for depth in range(len(scope), -1, -1):
        qual = scope[:depth] + (name,)
        if qual in defs:
            return qual
    return None


def _transform_aliases(sf: SourceFile, aliases: Dict[str, str]) -> Set[str]:
    """Names that refer to a jit-like transform in this module: the
    canonical dotted forms, plus ``from jax import jit`` aliases and
    module-level re-bindings (``shard_map = jax.shard_map``)."""
    names = set(JIT_TRANSFORMS)
    for alias, target in aliases.items():
        if target in JIT_TRANSFORMS:
            names.add(alias)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = resolve_call_target(node.value, aliases) \
                if isinstance(node.value, (ast.Attribute, ast.Name)) else None
            if target in JIT_TRANSFORMS:
                names.add(node.targets[0].id)
    return names


def _is_transform(func: ast.AST, aliases: Dict[str, str],
                  transform_names: Set[str]) -> bool:
    name = dotted_name(func)
    if name is None:
        return False
    if name in transform_names:
        return True
    resolved = resolve_call_target(func, aliases)
    return resolved in JIT_TRANSFORMS


def _fn_arg(call: ast.Call) -> Optional[ast.AST]:
    """The function operand of a transform call: first positional arg,
    or the ``fun=``/``f=`` keyword."""
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in ("fun", "f", "func"):
            return kw.value
    return None


def check(files: Sequence[SourceFile]) -> List[Finding]:
    all_defs: Dict[Tuple[str, ...], _Scope] = {}
    per_file_defs: Dict[str, Dict[Tuple[str, ...], _Scope]] = {}
    per_file_aliases: Dict[str, Dict[str, str]] = {}
    # (module, top-level def name) -> (file rel, qual) for cross-module hops
    exported: Dict[Tuple[str, str], Tuple[str, Tuple[str, ...]]] = {}
    for sf in files:
        defs = _collect_defs(sf)
        per_file_defs[sf.rel] = defs
        per_file_aliases[sf.rel] = module_aliases(sf.tree)
        for qual in defs:
            if len(qual) == 1:
                exported[(sf.module, qual[0])] = (sf.rel, qual)

    # --- roots: (rel, qual) reachable seeds + where the root was seen
    roots: List[Tuple[str, Tuple[str, ...], str]] = []
    lambda_roots: List[Tuple[SourceFile, Tuple[str, ...], ast.Lambda, str]] = []

    for sf in files:
        defs = per_file_defs[sf.rel]
        aliases = per_file_aliases[sf.rel]
        transforms = _transform_aliases(sf, aliases)

        # decorator roots
        for qual, scope in defs.items():
            fn = scope.node
            for dec in getattr(fn, "decorator_list", ()):
                root_desc = f"{sf.rel}:{fn.lineno}"
                if _is_transform(dec, aliases, transforms):
                    roots.append((sf.rel, qual, root_desc))
                elif isinstance(dec, ast.Call):
                    if _is_transform(dec.func, aliases, transforms):
                        roots.append((sf.rel, qual, root_desc))
                    elif resolve_call_target(dec.func, aliases) in (
                            "functools.partial", "partial") and dec.args \
                            and _is_transform(dec.args[0], aliases, transforms):
                        roots.append((sf.rel, qual, root_desc))

        # call-site roots: jax.jit(f) / shard_map(f, ...) anywhere
        def scan_calls(container: ast.AST, scope_qual: Tuple[str, ...]):
            # _own_body for the module pass too: nested defs are scanned
            # with their own scope, and resolving their call sites at
            # module scope would bind local names to unrelated
            # module-level defs
            for node in _own_body(container):
                if not isinstance(node, ast.Call):
                    continue
                is_partial = (resolve_call_target(node.func, aliases)
                              in ("functools.partial", "partial")
                              and node.args
                              and _is_transform(node.args[0], aliases,
                                                transforms))
                if not (_is_transform(node.func, aliases, transforms)
                        or is_partial):
                    continue
                arg = (_fn_arg(ast.Call(func=node.func,
                                        args=node.args[1:],
                                        keywords=node.keywords))
                       if is_partial else _fn_arg(node))
                if arg is None:
                    continue
                desc = f"{sf.rel}:{node.lineno}"
                if isinstance(arg, ast.Name):
                    qual = _resolve_local(arg.id, scope_qual, defs)
                    if qual is not None:
                        roots.append((sf.rel, qual, desc))
                elif isinstance(arg, ast.Lambda):
                    lambda_roots.append((sf, scope_qual, arg, desc))

        scan_calls(sf.tree, ())
        for qual, scope in defs.items():
            scan_calls(scope.node, qual)

    # --- reachability over (rel, qual) nodes
    reached: Dict[Tuple[str, Tuple[str, ...]], str] = {}
    work = [(rel, qual, desc) for rel, qual, desc in roots]
    while work:
        rel, qual, desc = work.pop()
        key = (rel, qual)
        if key in reached:
            continue
        reached[key] = desc
        defs = per_file_defs[rel]
        scope = defs.get(qual)
        if scope is None:
            continue
        aliases = per_file_aliases[rel]
        for node in _own_body(scope.node):
            names: List[str] = []
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name):
                    names.append(node.func.id)
                # function-valued arguments: lax.scan(body, ...),
                # vmap(f), custom_vjp wiring — trace them all
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        names.append(arg.id)
                target = resolve_call_target(node.func, aliases)
                if target is not None and target.startswith("fedml_tpu."):
                    mod, _, leaf = target.rpartition(".")
                    hop = exported.get((mod, leaf))
                    if hop is not None:
                        work.append((hop[0], hop[1], desc))
            for name in names:
                local = _resolve_local(name, qual, defs)
                if local is not None:
                    work.append((rel, local, desc))
                    continue
                imported = aliases.get(name)
                if imported and imported.startswith("fedml_tpu."):
                    mod, _, leaf = imported.rpartition(".")
                    hop = exported.get((mod, leaf))
                    if hop is not None:
                        work.append((hop[0], hop[1], desc))

    # --- impurity scan
    findings: List[Finding] = []
    by_rel = {sf.rel: sf for sf in files}
    for (rel, qual), desc in sorted(reached.items()):
        sf = by_rel[rel]
        scope = per_file_defs[rel][qual]
        aliases = per_file_aliases[rel]
        findings.extend(
            _scan_effects(sf, ".".join(qual), scope.node, aliases, desc)
        )
    for sf, scope_qual, lam, desc in lambda_roots:
        findings.extend(
            _scan_effects(sf, "<lambda>", lam,
                          per_file_aliases[sf.rel], desc, include_nested=True)
        )
    return findings


def _scan_effects(sf: SourceFile, qualname: str, fn: ast.AST,
                  aliases: Dict[str, str], root_desc: str,
                  include_nested: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    nodes = ast.walk(fn) if include_nested else _own_body(fn)
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        effect = _classify_effect(node, aliases)
        if effect is not None:
            findings.append(Finding(
                RULE, sf.rel, node.lineno, node.col_offset,
                f"{effect} inside '{qualname}', which is traced by a "
                f"jit/shard_map root at {root_desc} — host effects "
                "run once at trace time, not per step",
            ))
    return findings


def _classify_effect(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name) and func.id == "print":
        return "host 'print()'"
    if isinstance(func, ast.Attribute) and func.attr == "item" \
            and not call.args and not call.keywords:
        return "device->host sync '.item()'"
    target = resolve_call_target(func, aliases)
    if target is None:
        return None
    if target in HOST_CLOCKS:
        return f"host clock '{target}()'"
    if target.startswith("numpy.random."):
        return f"untraced numpy RNG '{target}()'"
    head, _, tail = target.partition(".")
    if head == "random" and tail:
        return f"untraced stdlib RNG 'random.{tail}()'"
    if target.endswith("get_telemetry"):
        return "telemetry registry access 'get_telemetry()'"
    if head == "logging" and tail:
        return f"host logging call '{target}()'"
    return None
