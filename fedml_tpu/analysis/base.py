"""Shared fedlint infrastructure: findings, pragmas, source loading.

Checkers operate on ``SourceFile`` objects — parsed AST plus the raw
source lines (the AST drops comments, and both pragma forms live in
comments).  Paths are kept package-relative with forward slashes
(``fedml_tpu/comm/tcp.py``) so rule scoping is platform-independent and
fixture tests can fabricate in-memory files at any virtual path.

Pragmas (parsed here, honored by ``analysis.run_all`` / the checkers):

- ``# fedlint: disable=<rule>[,<rule>...] -- <justification>`` —
  suppress findings of those rules on THIS line.  The justification is
  required: a bare disable is recorded as a ``pragma`` finding, which
  no pragma can suppress.
- ``# fedlint: holds=<lock>[,<lock>...]`` on a ``def`` line — the
  lock-discipline checker treats the whole function as holding those
  locks (the caller-holds-the-lock contract; ``locks.assert_held``
  verifies it at runtime when checked locks are enabled).

Stdlib-only: the CI lint job runs on a bare interpreter.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Union

PRAGMA_RE = re.compile(
    r"#\s*fedlint:\s*(disable|holds)=([\w.,-]+)"
    r"(?:\s+--\s*(\S.*?))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One linter finding, anchored to a source line."""

    rule: str
    path: str  # package-relative, forward slashes
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """Parsed module + pragma tables.

    ``rel`` is the rule-scoping path (``fedml_tpu/comm/tcp.py``);
    fixture tests construct instances directly with synthetic ``rel``
    values to land in a checker's scope without touching disk.
    """

    def __init__(self, text: str, rel: str, path: Optional[str] = None):
        self.rel = rel.replace("\\", "/")
        self.path = str(path) if path is not None else self.rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)
        self.disables: Dict[int, Set[str]] = {}
        self.holds: Dict[int, Set[str]] = {}
        self.pragma_errors: List[Finding] = []
        for lineno, line in enumerate(self.lines, 1):
            m = PRAGMA_RE.search(line)
            if m is None:
                continue
            kind = m.group(1)
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            justification = (m.group(3) or "").strip()
            if kind == "disable":
                if not justification:
                    self.pragma_errors.append(Finding(
                        "pragma", self.rel, lineno, m.start(),
                        "disable pragma requires a justification: "
                        "'# fedlint: disable=<rule> -- <why>'",
                    ))
                    continue
                self.disables.setdefault(lineno, set()).update(names)
            else:
                self.holds.setdefault(lineno, set()).update(names)

    @property
    def module(self) -> str:
        """Dotted module name derived from ``rel`` (for cross-module
        resolution in the jit-purity call graph)."""
        rel = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        parts = [p for p in rel.split("/") if p]
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)


def load_files(roots: Union[str, Path, Sequence[Union[str, Path]]],
               ) -> List[SourceFile]:
    """Load every ``*.py`` under the given root(s).

    For a directory root, files get rel paths anchored at the root's
    OWN name (``fedml_tpu/...`` when pointed at the package dir) so the
    checkers' path scoping matches however the tree was reached."""
    if isinstance(roots, (str, Path)):
        roots = [roots]
    out: List[SourceFile] = []
    for root in roots:
        root = Path(root).resolve()
        if root.is_file():
            paths = [(root, root.name)]
        else:
            paths = [
                (p, p.relative_to(root.parent).as_posix())
                for p in sorted(root.rglob("*.py"))
                if "__pycache__" not in p.parts
            ]
        for path, rel in paths:
            try:
                text = path.read_text(encoding="utf-8")
            except (OSError, UnicodeDecodeError) as e:
                raise RuntimeError(f"fedlint cannot read {path}: {e}") from e
            try:
                out.append(SourceFile(text, rel=rel, path=str(path)))
            except SyntaxError as e:
                raise RuntimeError(
                    f"fedlint cannot parse {path}: {e}"
                ) from e
    return out


# --- shared AST helpers ------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_aliases(tree: ast.AST) -> Dict[str, str]:
    """alias → canonical module for every ``import``/``from`` anywhere
    in the module (function-level imports included — the codebase leans
    on them for lazy loading).  ``from x import y`` maps ``y`` to
    ``x.y`` so attribute chains resolve uniformly."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def resolve_call_target(func: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted target of a call's ``func`` node, with the
    module-alias table applied to the chain root: ``np.random.rand``
    with ``import numpy as np`` resolves to ``numpy.random.rand``."""
    name = dotted_name(func)
    if name is None:
        return None
    head, _, tail = name.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{tail}" if tail else head
