"""Project-specific static analysis — the ``fedlint`` checker suite.

The rebuild's headline guarantees (bit-identical same-seed digests, the
encode-once zero-copy wire path, a lock-protected streaming fold under
reader threads + a sender pool) were enforced by convention and runtime
tests only.  This package makes them *checkable*: five AST-based
invariant linters over the whole package, plus a runtime ``CheckedLock``
harness (``analysis.locks``) that records a lock-order graph under the
concurrency stress tests.

Rules (one module per rule; ``tools/fedlint.py`` is the CLI):

- ``determinism``     — no seedless ``random.*``/``np.random.*`` or
  wall-clock ``time.time()`` in round-path modules; randomness must be
  seeded (``RandomState(seed)``, ``fold_in``-derived streams) and
  timestamps belong to ``obs/``.
- ``jit-purity``      — functions reachable from ``jax.jit`` /
  ``shard_map`` / ``pjit`` call sites must not contain host side
  effects (``print``, ``time.*``, ``.item()``, numpy RNG, telemetry).
- ``wire-schema``     — reserved frame-header keys (``__hub__``,
  ``__trace__``, ``__binlen__``, ``__ndbuf__``, ``__wiretree__``,
  ``__ndarray__``) are defined ONCE (``comm/message.py`` /
  ``obs/trace_ctx.py``); literal duplicates elsewhere are the drift
  class behind silent wire-format skew.
- ``metric-name``     — every counter/gauge/histogram/event name used
  in code must appear in ``obs/metric_schema.py`` (typo'd series
  silently never aggregate).
- ``lock-discipline`` — attributes a class declares in ``_GUARDED_BY``
  may only be touched inside ``with self.<lock>:`` scopes (or in
  methods annotated ``# fedlint: holds=<lock>``, verified at runtime
  by ``locks.assert_held``).

Suppression: ``# fedlint: disable=<rule> -- <justification>`` on the
finding's line.  The justification is REQUIRED — a bare disable is
itself a (non-suppressible) ``pragma`` finding.

Everything in this package is stdlib-only by design: the CI lint job
runs ``tools/fedlint.py`` on a bare Python with no jax/numpy installed.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from fedml_tpu.analysis.base import Finding, SourceFile, load_files

RULES = (
    "determinism",
    "jit-purity",
    "wire-schema",
    "metric-name",
    "lock-discipline",
)


def run_all(files: Sequence[SourceFile],
            rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected checkers (default: all) over ``files`` and
    return pragma-filtered findings, sorted by (path, line).  Pragma
    misuse (a ``disable`` with no justification) is appended as
    ``pragma`` findings — those are never suppressible."""
    # checker imports are function-level so importing the package (for
    # ``analysis.locks``) stays O(one small module) on hot paths
    from fedml_tpu.analysis import (
        determinism,
        jit_purity,
        lock_discipline,
        metric_names,
        wire_schema,
    )

    checkers = {
        "determinism": determinism.check,
        "jit-purity": jit_purity.check,
        "wire-schema": wire_schema.check,
        "metric-name": metric_names.check,
        "lock-discipline": lock_discipline.check,
    }
    selected = list(rules) if rules else list(RULES)
    unknown = [r for r in selected if r not in checkers]
    if unknown:
        raise ValueError(f"unknown fedlint rules: {unknown} (have {RULES})")
    findings: List[Finding] = []
    by_rel = {f.rel: f for f in files}
    for rule in selected:
        for fnd in checkers[rule](files):
            sf = by_rel.get(fnd.path)
            if sf is not None and fnd.rule in sf.disables.get(fnd.line, ()):
                continue  # suppressed by a justified pragma
            findings.append(fnd)
    for sf in files:
        findings.extend(sf.pragma_errors)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


__all__ = ["Finding", "SourceFile", "load_files", "run_all", "RULES"]
