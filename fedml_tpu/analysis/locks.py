"""CheckedLock — runtime complement to the lock-discipline linter.

The static checker (``analysis.lock_discipline``) enforces LEXICAL
discipline: guarded attributes touched only inside ``with self.<lock>``
blocks, with ``# fedlint: holds=<lock>`` escapes for
caller-holds-the-lock methods.  Those escapes are promises the AST
cannot verify — this module verifies them at runtime, and additionally
records a process-wide lock-ORDER graph so the concurrency stress tests
can assert deadlock-freedom (an acyclic graph) instead of hoping.

Usage: threaded modules create their locks through ``make_lock(name)``.
Off (the default), that returns a plain ``threading.Lock`` — zero
overhead, nothing recorded.  On (``FEDML_TPU_CHECKED_LOCKS=1`` or
``set_enabled(True)``), it returns a ``CheckedLock`` that

- keeps a per-thread stack of held locks;
- on every acquire, records ``held → acquiring`` edges by lock NAME
  (lock names identify the lock's ROLE — ``TcpHub._lock`` — so the
  graph is over lock classes, which is what deadlock discipline is
  about; cycles mean two threads can wait on each other);
- raises ``LockDisciplineError`` on a recursive acquire of the same
  instance (a plain Lock would silently deadlock there);
- answers ``held_by_me()`` so ``assert_held`` can verify ``holds=``
  contracts at the top of caller-holds methods.

``find_cycle()``/``assert_acyclic()`` inspect the recorded graph; the
stress tests call ``reset()`` first and ``assert_acyclic()`` after.

Stdlib-only by design: ``comm/tcp.py`` and the other threaded modules
import this at module level, and the lint CI runs without jax/numpy.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple, Union

ENV_ENABLE = "FEDML_TPU_CHECKED_LOCKS"

_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()

_registry_lock = threading.Lock()
_edges: Set[Tuple[str, str]] = set()  # (held lock name, acquired lock name)
_held_local = threading.local()

# single acquisition tap (the flight recorder's lock ring): called as
# ``fn(lock_name, held_depth, wait_s)`` after every successful
# CheckedLock acquire, where ``wait_s`` is the measured block time of
# the underlying acquire — the runtime CONTENTION probe (a hot lock
# shows up as nonzero waits in the flight ring, not as a hunch).
# Atomic ref swap, exceptions swallowed at the call site — same
# contract as the telemetry taps.  Costs nothing with checking off
# (plain Locks never reach it).
_acquire_tap = None


def set_acquire_tap(fn) -> None:
    global _acquire_tap
    _acquire_tap = fn


class LockDisciplineError(RuntimeError):
    """A lock contract was violated at runtime (recursive acquire, or a
    ``holds=`` method entered without its lock)."""


def enabled() -> bool:
    """Process-wide switch (env ``FEDML_TPU_CHECKED_LOCKS=1``), cached
    after first read; ``set_enabled`` overrides for in-process tests."""
    global _enabled
    if _enabled is None:
        with _enabled_lock:
            if _enabled is None:
                _enabled = os.environ.get(ENV_ENABLE, "") == "1"
    return _enabled


def set_enabled(flag: Optional[bool]) -> None:
    """Override the switch (True/False); ``None`` re-reads the env on
    next use.  Only affects locks created AFTER the call — existing
    plain locks stay plain."""
    global _enabled
    with _enabled_lock:
        _enabled = flag


def _stack() -> List["CheckedLock"]:
    stack = getattr(_held_local, "stack", None)
    if stack is None:
        stack = _held_local.stack = []
    return stack


class CheckedLock:
    """``threading.Lock`` wrapper that records ordering + ownership."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = _stack()
        for held in stack:
            if held is self:
                raise LockDisciplineError(
                    f"recursive acquire of non-reentrant lock {self.name!r}"
                )
        if stack:
            # record BEFORE blocking: the edge describes the wait that
            # can deadlock, not the acquisition that succeeded
            with _registry_lock:
                for held in stack:
                    if held.name != self.name:
                        _edges.add((held.name, self.name))
        t0 = time.perf_counter()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            stack.append(self)
            tap = _acquire_tap
            if tap is not None:
                try:
                    tap(self.name, len(stack),
                        time.perf_counter() - t0)
                except Exception:
                    pass
        return ok

    def release(self) -> None:
        stack = _stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        else:
            raise LockDisciplineError(
                f"release of {self.name!r} by a thread that does not hold it"
            )
        self._lock.release()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_me(self) -> bool:
        return any(held is self for held in _stack())

    def __repr__(self) -> str:
        return f"CheckedLock({self.name!r})"


LockLike = Union[threading.Lock, CheckedLock]


def make_lock(name: str) -> LockLike:
    """The lock factory threaded modules use: a plain ``threading.Lock``
    normally, a ``CheckedLock`` when runtime checking is enabled."""
    return CheckedLock(name) if enabled() else threading.Lock()


def assert_held(lock: LockLike, what: str = "") -> None:
    """Verify a ``# fedlint: holds=<lock>`` contract at runtime.  No-op
    for plain locks (checking off) — callers sprinkle this freely at
    the top of caller-holds methods."""
    if isinstance(lock, CheckedLock) and not lock.held_by_me():
        raise LockDisciplineError(
            f"{what or 'guarded section'} entered without holding "
            f"{lock.name!r} (a '# fedlint: holds=' contract was broken)"
        )


# --- lock-order graph inspection ---------------------------------------------

def lock_order_edges() -> Set[Tuple[str, str]]:
    with _registry_lock:
        return set(_edges)


def find_cycle() -> Optional[List[str]]:
    """A cycle in the recorded order graph as ``[a, b, ..., a]``, or
    None.  Iterative DFS with the classic white/grey/black coloring."""
    edges = lock_order_edges()
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    color: Dict[str, int] = {}  # 1 = on stack, 2 = done
    for root in sorted(adj):
        if color.get(root):
            continue
        path: List[str] = []
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, idx = work.pop()
            if idx == 0:
                color[node] = 1
                path.append(node)
            kids = adj.get(node, ())
            if idx < len(kids):
                work.append((node, idx + 1))
                kid = kids[idx]
                if color.get(kid) == 1:
                    return path[path.index(kid):] + [kid]
                if not color.get(kid):
                    work.append((kid, 0))
            else:
                color[node] = 2
                path.pop()
    return None


def assert_acyclic() -> None:
    cycle = find_cycle()
    if cycle is not None:
        raise LockDisciplineError(
            "lock-order cycle (deadlock potential): " + " -> ".join(cycle)
        )


def reset() -> None:
    """Clear the recorded graph (test isolation).  Held-lock stacks are
    thread-local and empty between well-behaved tests."""
    with _registry_lock:
        _edges.clear()
