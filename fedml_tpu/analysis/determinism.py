"""determinism — seedless randomness / wall-clock bans in round-path code.

The reproducibility contract (PR 4/5: byte-identical same-seed upload
digests, deterministic chaos schedules) only holds if every random draw
in the round path flows from an explicit seed — ``np.random.RandomState
(seed)``, ``random.Random(seed_string)``, or a jax ``fold_in``-derived
stream — and nothing reads the wall clock where behavior depends on it.
This rule flags, in round-path modules only:

- module-level stdlib ``random.*`` draws (the shared, seedless global
  RNG: ``random.random()``, ``randint``, ``choice``, ...);
- ``random.Random()`` constructed WITHOUT a seed;
- ``np.random.*`` draws and ``np.random.seed`` (global-state RNG);
  seeded constructors (``RandomState(seed)``, ``default_rng(seed)``)
  pass, the same constructors with no argument do not;
- ``time.time()`` / ``time.time_ns()`` — wall-clock timestamps belong
  to the obs/ layer (monotonic ``perf_counter`` spans are fine and are
  not flagged).

Round-path scope: ``comm/``, ``algorithms/``, ``core/``, ``compress/``,
``faults/``, ``parallel/``, ``ops/``.  ``obs/`` (whose whole job is
timestamps), ``experiments/`` (driver wall-time reporting), ``data/``
and ``models/`` are out of scope.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from fedml_tpu.analysis.base import (
    Finding,
    SourceFile,
    module_aliases,
    resolve_call_target,
)

RULE = "determinism"

ROUND_PATH_PREFIXES = (
    "fedml_tpu/comm/",
    "fedml_tpu/algorithms/",
    "fedml_tpu/core/",
    "fedml_tpu/compress/",
    "fedml_tpu/faults/",
    "fedml_tpu/parallel/",
    "fedml_tpu/ops/",
)

# module-level functions on stdlib random's hidden global Random()
SEEDLESS_RANDOM_FNS = {
    "random", "randint", "randrange", "randbytes", "choice", "choices",
    "shuffle", "sample", "uniform", "triangular", "betavariate",
    "expovariate", "gammavariate", "gauss", "lognormvariate",
    "normalvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "getrandbits",
}

# np.random constructors that are deterministic WHEN GIVEN a seed
NP_SEEDED_CONSTRUCTORS = {
    "RandomState", "default_rng", "Generator", "SeedSequence",
    "PCG64", "Philox", "MT19937", "SFC64", "BitGenerator",
}

WALL_CLOCK_FNS = {"time.time", "time.time_ns"}


def in_scope(rel: str) -> bool:
    return rel.startswith(ROUND_PATH_PREFIXES)


def check(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if not in_scope(sf.rel):
            continue
        aliases = module_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve_call_target(node.func, aliases)
            if target is None:
                continue
            msg = _classify(target, node)
            if msg is not None:
                findings.append(
                    Finding(RULE, sf.rel, node.lineno, node.col_offset, msg)
                )
    return findings


def _classify(target: str, call: ast.Call):
    """None when the call is fine; otherwise the finding message."""
    head, _, tail = target.partition(".")
    if head == "random":
        if tail in SEEDLESS_RANDOM_FNS:
            return (
                f"seedless stdlib RNG 'random.{tail}()' in a round-path "
                "module — draw from an explicitly seeded "
                "random.Random(...) or a fold_in-derived stream"
            )
        if tail == "Random" and not call.args and not call.keywords:
            return (
                "'random.Random()' without a seed — pass an explicit "
                "seed (a stable string seeds via sha512, cross-process)"
            )
        return None
    if target.startswith("numpy.random.") or head == "numpy.random":
        fn = target.split(".")[-1]
        if fn in NP_SEEDED_CONSTRUCTORS:
            if call.args or call.keywords:
                return None  # RandomState(seed), default_rng(seed): seeded
            return (
                f"'np.random.{fn}()' without a seed in a round-path "
                "module — pass the run seed (or a fold_in-derived value)"
            )
        return (
            f"seedless/global-state numpy RNG 'np.random.{fn}(...)' in a "
            "round-path module — use np.random.RandomState(seed) or a "
            "fold_in-derived stream"
        )
    if target in WALL_CLOCK_FNS:
        return (
            f"wall-clock '{target}()' in a round-path module — "
            "timestamps belong to obs/ (use time.perf_counter() for "
            "spans; wall stamps only via the telemetry layer)"
        )
    return None
