"""Classical vertical FL (feature-split), TPU-native.

Reference mechanism (SURVEY.md §2 row 17, §3):
``classical_vertical_fl/guest_trainer.py:74-110`` — hosts each forward
their private feature slice and send logit components; the guest adds
its own logits, computes ``BCEWithLogitsLoss`` on the sum, and returns
the **common gradient** dL/dU to every host
(``party_models.py:56-75``); each party then backprops its own branch
with that gradient (SGD momentum 0.9, weight-decay 0.01,
``vfl_models_standalone.py:13,46``).

TPU-native design: the whole multi-party step is ONE jitted program.
Each party's forward is captured as a ``jax.vjp``; the guest loss is
differentiated once w.r.t. the summed logits ``U``; that single [B, 1]
cotangent — the exact tensor the reference ships over the network —
fans back through every party's vjp.  This is mathematically identical
to joint autodiff but keeps the protocol's structure: the only value
crossing party boundaries is (logit components up, common gradient
down), so the same step functions drive an inproc simulation or a real
multi-host deployment over the comm backend with parties on separate
hosts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.models.base import ModelBundle

PyTree = Any


class PartyState(NamedTuple):
    params: PyTree
    opt_state: Any


def bce_with_logits(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Mean binary cross-entropy on logits (torch BCEWithLogitsLoss)."""
    y = y.reshape(logits.shape).astype(logits.dtype)
    return optax.sigmoid_binary_cross_entropy(logits, y).mean()


def _party_optimizer(lr: float, momentum: float = 0.9, weight_decay: float = 0.01):
    """Reference party optimizer: SGD(momentum=0.9, wd=0.01)
    (``vfl_models_standalone.py:13``)."""
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(lr, momentum=momentum),
    )


@dataclasses.dataclass
class VerticalFederation:
    """Guest (party 0, holds labels) + hosts, each with a private feature
    slice.  Mirrors the standalone driver
    ``VerticalMultiplePartyLogisticRegressionFederatedLearning.fit``
    (``vfl.py:21-49``) and the distributed guest/host trainer pair
    (``guest_trainer.py``, ``host_trainer.py``).

    ``bundles[i]`` consumes feature slice i; party 0 is the guest.
    """

    bundles: Sequence[ModelBundle]
    lr: float = 0.05
    momentum: float = 0.9
    weight_decay: float = 0.01

    def __post_init__(self):
        self._opt = _party_optimizer(self.lr, self.momentum, self.weight_decay)
        self._step = jax.jit(self._make_step())
        self._predict = jax.jit(self._make_predict())

    # -- state ---------------------------------------------------------
    def init(self, rng: jax.Array) -> Tuple[PartyState, ...]:
        states = []
        for i, b in enumerate(self.bundles):
            params = b.init(jax.random.fold_in(rng, i))["params"]
            states.append(PartyState(params, self._opt.init(params)))
        return tuple(states)

    # -- compiled step -------------------------------------------------
    def _apply(self, bundle: ModelBundle, params: PyTree, x: jax.Array):
        return bundle.module.apply({"params": params}, x, train=True)

    def _make_step(self):
        bundles = tuple(self.bundles)
        opt = self._opt

        def step(states: Tuple[PartyState, ...], xs: Tuple[jax.Array, ...], y):
            # Forward every party, capturing its branch's vjp.
            logits, vjps = [], []
            for b, st, x in zip(bundles, states, xs):
                out, vjp = jax.vjp(lambda p, _b=b, _x=x: self._apply(_b, p, _x), st.params)
                logits.append(out)
                vjps.append(vjp)
            U = sum(logits)  # guest sums components (guest_trainer.py:87-89)
            loss, dU = jax.value_and_grad(bce_with_logits)(U, y)
            # dU is the common gradient — the single tensor the guest
            # sends to every host (guest_trainer.py:96-104).
            new_states = []
            for st, vjp in zip(states, vjps):
                (g,) = vjp(dU)
                updates, opt_state = opt.update(g, st.opt_state, st.params)
                new_states.append(
                    PartyState(optax.apply_updates(st.params, updates), opt_state)
                )
            return tuple(new_states), loss

        return step

    def _make_predict(self):
        bundles = tuple(self.bundles)

        def predict(states, xs):
            U = sum(
                b.module.apply({"params": st.params}, x, train=False)
                for b, st, x in zip(bundles, states, xs)
            )
            # reference predict: sigmoid(sum U) (party_models.py:42-47)
            return jax.nn.sigmoid(jnp.sum(U, axis=1))

        return predict

    # -- driver API (mirrors vfl_fixture.py) ---------------------------
    def fit(self, states, xs, y):
        """One global step on one batch; returns (new_states, loss)."""
        return self._step(states, tuple(xs), y)

    def predict(self, states, xs) -> jax.Array:
        return self._predict(states, tuple(xs))

    def evaluate(self, states, xs, y) -> dict:
        prob = np.asarray(self.predict(states, xs))
        y = np.asarray(y).reshape(-1)
        pred = (prob > 0.5).astype(np.int32)
        acc = float((pred == y).mean())
        return {"accuracy": acc, "auc": float(binary_auc(y, prob))}


def binary_auc(y_true: np.ndarray, score: np.ndarray) -> float:
    """Rank-based ROC-AUC (ties averaged) — replaces the reference's
    sklearn ``roc_auc_score`` (``guest_trainer.py:5-6``) without the
    dependency."""
    y_true = np.asarray(y_true).reshape(-1)
    score = np.asarray(score).reshape(-1)
    pos, neg = (y_true == 1).sum(), (y_true == 0).sum()
    if pos == 0 or neg == 0:
        return float("nan")
    order = np.argsort(score, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(score) + 1)
    # tie-average in O(n log n): mean rank per unique score via bincount
    _, inv = np.unique(score, return_inverse=True)
    sums = np.bincount(inv, weights=ranks)
    counts = np.bincount(inv)
    ranks = (sums / counts)[inv]
    return float((ranks[y_true == 1].sum() - pos * (pos + 1) / 2) / (pos * neg))


def run_vfl(
    federation: VerticalFederation,
    xs_train: Sequence[np.ndarray],
    y_train: np.ndarray,
    xs_test: Sequence[np.ndarray],
    y_test: np.ndarray,
    *,
    rng: Optional[jax.Array] = None,
    epochs: int = 1,
    batch_size: int = 256,
    eval_every: int = 1,
) -> Tuple[Tuple[PartyState, ...], List[dict]]:
    """Batch-loop driver, the ``FederatedLearningFixture`` analogue
    (``vfl_fixture.py:27+``)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    states = federation.init(rng)
    n = len(y_train)
    history = []
    step = 0
    for epoch in range(epochs):
        for lo in range(0, n, batch_size):
            sl = slice(lo, min(lo + batch_size, n))
            xs = [jnp.asarray(x[sl]) for x in xs_train]
            states, loss = federation.fit(states, xs, jnp.asarray(y_train[sl]))
            step += 1
        if (epoch + 1) % eval_every == 0:
            m = federation.evaluate(
                states, [jnp.asarray(x) for x in xs_test], y_test
            )
            m.update(epoch=epoch, step=step)
            history.append(m)
    return states, history
