"""Centralized (non-federated) baseline trainer.

Reference: ``fedml_api/centralized/centralized_trainer.py:9-70`` — trains
on the union of all clients' data with the same data contract, used by
the CI equivalence oracle (FedAvg == centralized at full participation /
full batch / E=1, ``CI-script-fedavg.sh:42-48``).  Here it is literally
the same local-update kernel applied to one "client" holding everything,
which makes the oracle a structural identity rather than a coincidence.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.client import make_client_optimizer, make_evaluator, make_local_update
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.types import FedDataset, batch_eval_pack
from fedml_tpu.models.base import ModelBundle


class CentralizedTrainer:
    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        *,
        epochs_per_call: int = 1,
        batch_size: int = 64,
        optimizer: str = "sgd",
        lr: float = 0.03,
        momentum: float = 0.0,
        weight_decay: Optional[float] = None,
        grad_clip: Optional[float] = None,
        loss_fn: LossFn = masked_softmax_ce,
        seed: int = 0,
        shuffle: bool = True,
    ):
        self.bundle = bundle
        self.dataset = dataset
        opt = make_client_optimizer(
            optimizer, lr, momentum=momentum, weight_decay=weight_decay, grad_clip=grad_clip
        )
        self.update = jax.jit(
            make_local_update(
                bundle, opt, epochs_per_call, loss_fn, shuffle=shuffle
            ).fn
        )
        self.evaluator = make_evaluator(bundle, loss_fn)
        self.key = jax.random.PRNGKey(seed)
        self.variables = bundle.init(self.key)
        self._train_pack = batch_eval_pack(dataset.train_x, dataset.train_y, batch_size)
        self._test_pack = batch_eval_pack(
            dataset.test_x, dataset.test_y, max(batch_size, 64)
        )
        self.epoch = 0

    def train(self, epochs: int = 1) -> dict:
        x, y, m = self._train_pack
        metrics = {}
        for _ in range(epochs):
            self.variables, metrics = self.update(
                self.variables,
                jnp.asarray(x),
                jnp.asarray(y),
                jnp.asarray(m),
                jax.random.fold_in(self.key, 17 + self.epoch),
            )
            self.epoch += 1
        out = {k: float(v) for k, v in metrics.items()}
        if out.get("count"):
            out["train_acc"] = out["correct"] / out["count"]
            out["train_loss"] = out["loss_sum"] / out["count"]
        return out

    def evaluate(self) -> dict:
        x, y, m = self._test_pack
        res = self.evaluator(
            self.variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m)
        )
        c = float(res["count"])
        return {
            "test_acc": float(res["correct"]) / max(c, 1.0),
            "test_loss": float(res["loss_sum"]) / max(c, 1.0),
        }
