"""FedProx — FedAvg plus a proximal term on the client objective.

Reference (``fedml_api/distributed/fedprox/MyModelTrainer.py:41-60``):
client loss += (mu/2)·‖w − w_global‖²; aggregation is identical to
FedAvg.  Here the proximal term is a flag on the shared local-update
operator (``fedml_tpu.core.client.make_local_update(prox_mu=...)``)
computed over parameters only — the reference's parameter/buffer index
misalignment (SURVEY.md §7 known defects) is not replicated.

FedProx also supports preprocessed client-sampling lists
(``FedProxAPI.py:19-60``); pass ``sampling_schedule`` to override the
per-round seeded uniform sampling.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.types import FedDataset
from fedml_tpu.models.base import ModelBundle


class FedProxSimulation(FedAvgSimulation):
    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        mu: float = 0.1,
        sampling_schedule: Optional[Sequence[Sequence[int]]] = None,
        loss_fn: LossFn = masked_softmax_ce,
        **kwargs,
    ):
        import dataclasses

        config = dataclasses.replace(config, prox_mu=mu)
        super().__init__(bundle, dataset, config, loss_fn=loss_fn, **kwargs)
        self._sampling_schedule = sampling_schedule

    def _sample_ids(self, round_idx: int) -> np.ndarray:
        if self._sampling_schedule is not None:
            sched = self._sampling_schedule
            return np.asarray(sched[round_idx % len(sched)])
        return super()._sample_ids(round_idx)
