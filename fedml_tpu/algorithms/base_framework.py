"""Base-framework template — the tutorial algorithm new algorithms copy.

Reference ``fedml_api/distributed/base_framework/`` (the documented
starting point for new algorithms): ``algorithm_api.py:16-39`` forks
process roles, ``central_worker.py:4-32`` collects one scalar "local
result" per client and sums them, ``central_manager.py:8-53`` runs the
INIT → collect → aggregate → broadcast round loop over MPI.

The TPU rebuild keeps the template in BOTH native forms so a new
algorithm can start from whichever coupling it needs:

- **message form** — ``BaseCentralManager`` / ``BaseClientManager``
  over any ``CommBackend`` (inproc simulation, TCP hub): the
  loosely-coupled host control plane, reference choreography intact
  (minus the MPI ``Abort()`` shutdown — a FINISH message instead).
- **compiled form** — the identical round as ONE jitted
  ``shard_map``/``psum`` over a ``clients`` mesh axis: what the
  message choreography compiles down to when every participant is a
  chip on the slice.

``run_base_framework`` drives the message form; ``make_compiled_round``
builds the collective form; the test suite asserts they agree exactly.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from fedml_tpu.parallel.compat import shard_map
from fedml_tpu.comm.backend import CommBackend, NodeManager
from fedml_tpu.comm.inproc import InprocBus
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_S2C_FINISH,
    MSG_TYPE_S2C_INIT_CONFIG,
    Message,
)

SERVER = 0

# template-specific vocabulary (reference base_framework/message_define.py)
MSG_TYPE_S2C_INFORMATION = "S2C_INFORMATION"
MSG_TYPE_C2S_INFORMATION = "C2S_INFORMATION"
MSG_ARG_KEY_INFORMATION = "information"

# a client's contribution given (client_id, round_idx, global_result)
LocalComputeFn = Callable[[int, int, float], float]


def default_local_compute(client_id: int, round_idx: int,
                          global_result: float) -> float:
    """Deterministic stand-in "local training": decays the global value
    and adds a per-client offset, so rounds produce a checkable series."""
    return 0.5 * global_result / (client_id + 1) + (client_id + 1) * 0.01


class BaseCentralWorker:
    """Scalar aggregator (reference ``central_worker.py:4-32``): collect
    one local result per client, sum when all have arrived."""

    def __init__(self, client_num: int):
        self.client_num = client_num
        self.local_results: Dict[int, float] = {}

    def add_client_local_result(self, index: int, result: float) -> None:
        self.local_results[index] = result

    def check_whether_all_receive(self) -> bool:
        return len(self.local_results) == self.client_num

    def aggregate(self) -> float:
        total = float(sum(self.local_results.values()))
        self.local_results.clear()
        return total


class BaseClientWorker:
    """Per-client compute (reference ``client_worker.py``)."""

    def __init__(self, client_id: int,
                 local_compute: LocalComputeFn = default_local_compute):
        self.client_id = client_id
        self.local_compute = local_compute

    def compute(self, round_idx: int, global_result: float) -> float:
        return self.local_compute(self.client_id, round_idx, global_result)


class BaseCentralManager(NodeManager):
    """Round loop (reference ``central_manager.py:8-53``): INIT to all,
    collect C2S_INFORMATION, aggregate, broadcast or finish."""

    def __init__(self, backend: CommBackend, aggregator: BaseCentralWorker,
                 comm_rounds: int):
        self.aggregator = aggregator
        self.comm_rounds = comm_rounds
        self.round_idx = 0
        self.history: List[float] = []
        super().__init__(backend)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_INFORMATION, self._on_information
        )

    def start(self) -> None:
        for node in range(1, self.aggregator.client_num + 1):
            self.send_message(
                Message(MSG_TYPE_S2C_INIT_CONFIG, SERVER, node)
                .add_params(MSG_ARG_KEY_ROUND_INDEX, 0)
                .add_params(MSG_ARG_KEY_INFORMATION, 0.0)
            )

    def _on_information(self, msg: Message) -> None:
        self.aggregator.add_client_local_result(
            msg.sender - 1, msg.get(MSG_ARG_KEY_INFORMATION)
        )
        if not self.aggregator.check_whether_all_receive():
            return
        global_result = self.aggregator.aggregate()
        self.history.append(global_result)
        self.round_idx += 1
        if self.round_idx >= self.comm_rounds:
            for node in range(1, self.aggregator.client_num + 1):
                self.send_message(Message(MSG_TYPE_S2C_FINISH, SERVER, node))
            self.finish()
            return
        for node in range(1, self.aggregator.client_num + 1):
            self.send_message(
                Message(MSG_TYPE_S2C_INFORMATION, SERVER, node)
                .add_params(MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
                .add_params(MSG_ARG_KEY_INFORMATION, global_result)
            )


class BaseClientManager(NodeManager):
    """Client loop (reference ``client_manager.py``): on INIT or
    S2C_INFORMATION, compute the local result and send it up."""

    def __init__(self, backend: CommBackend, worker: BaseClientWorker):
        self.worker = worker
        super().__init__(backend)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self._on_round
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INFORMATION, self._on_round
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_FINISH, lambda msg: self.finish()
        )

    def _on_round(self, msg: Message) -> None:
        result = self.worker.compute(
            msg.get(MSG_ARG_KEY_ROUND_INDEX), msg.get(MSG_ARG_KEY_INFORMATION)
        )
        self.send_message(
            Message(MSG_TYPE_C2S_INFORMATION, self.backend.node_id, SERVER)
            .add_params(MSG_ARG_KEY_INFORMATION, result)
        )


def run_base_framework(
    num_workers: int,
    comm_rounds: int,
    local_compute: LocalComputeFn = default_local_compute,
) -> List[float]:
    """Drive the message-form template on the inproc bus; returns the
    per-round global results (reference's mpirun localhost demo,
    ``CI-script-framework.sh:16-23``)."""
    if comm_rounds < 1:
        raise ValueError(f"comm_rounds must be >= 1, got {comm_rounds}")
    bus = InprocBus()
    central = BaseCentralManager(
        bus.register(SERVER), BaseCentralWorker(num_workers), comm_rounds
    )
    managers = [
        BaseClientManager(bus.register(i + 1),
                          BaseClientWorker(i, local_compute))
        for i in range(num_workers)
    ]
    del managers
    central.start()
    bus.drain()
    return central.history


def make_compiled_round(
    mesh,
    local_compute_jax: Optional[Callable[[jax.Array, jax.Array, jax.Array],
                                         jax.Array]] = None,
    axis: str = "clients",
):
    """The SAME template round as one compiled collective: each device
    computes its clients' local results and a ``psum`` over the mesh
    axis replaces collect+aggregate+broadcast.

    ``local_compute_jax(client_ids, round_idx, global_result)`` maps the
    local shard of client ids to local results (vectorized); defaults to
    the jnp translation of ``default_local_compute``.
    """
    if local_compute_jax is None:
        # the default template compute is pure arithmetic, so the message
        # form and the compiled form share one definition
        local_compute_jax = default_local_compute

    def _round(client_ids, round_idx, global_result):
        local = local_compute_jax(client_ids, round_idx, global_result)
        return lax.psum(jnp.sum(local), axis)

    sharded = shard_map(
        _round, mesh=mesh, in_specs=(P(axis), P(), P()), out_specs=P()
    )

    @jax.jit
    def run_rounds(client_ids, num_rounds_arr):
        def body(g, r):
            g = sharded(client_ids, r, g)
            return g, g

        _, history = lax.scan(
            body, jnp.asarray(0.0, jnp.float32), num_rounds_arr
        )
        return history

    def run(num_clients: int, comm_rounds: int) -> np.ndarray:
        cids = jnp.arange(num_clients, dtype=jnp.float32)
        return np.asarray(
            run_rounds(cids, jnp.arange(comm_rounds, dtype=jnp.int32))
        )

    return run
