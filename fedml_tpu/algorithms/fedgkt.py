"""FedGKT — group knowledge transfer, TPU-native.

Reference (SURVEY.md §2.2 row 15, §3.4): each edge client trains a small
CNN locally with CE + α·KL against the server's last logits
(``fedgkt/GKTClientTrainer.py:66-90``), then records per-batch feature
maps + logits + labels and ships them to the server
(``GKTClientTrainer.py:92-120``, ``GKTClientManager.py:39-48``); the
server trains a large CNN on the stored features with CE + α·KL
distillation from the client logits (``GKTServerTrainer.py:233-290``)
and returns per-client server logits (``GKTServerManager.py:59+``).
Activations — not weights — are the payload.

TPU-native design: both phases are single compiled programs over
fixed-shape packs.  The client phase maps over the packed client axis
(local epochs scan + a feature-extraction scan); the server phase scans
over (client × batch) slots with a per-slot mask, so heterogeneous
client sizes cost no recompilation.  The feature/logit "messages" are
just arrays handed between the two jits — on a mesh they become the
sharded residents of the ``clients`` axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.client import make_client_optimizer
from fedml_tpu.core.losses import masked_kd_kl, masked_softmax_ce
from fedml_tpu.core.types import FedDataset, batch_eval_pack, cohort_steps_per_epoch, pack_clients
from fedml_tpu.models.base import ModelBundle

PyTree = Any


@dataclasses.dataclass
class FedGKTConfig:
    num_clients: int = 4
    comm_rounds: int = 5
    epochs_client: int = 1
    epochs_server: int = 1
    batch_size: int = 8
    lr_client: float = 0.01
    lr_server: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 5e-4
    temperature: float = 3.0   # reference --temperature default
    alpha: float = 1.0         # KD loss weight, reference --alpha
    whether_distill_on_client: bool = True
    grad_clip: Optional[float] = 5.0
    seed: int = 0


class FedGKT:
    """Two-net GKT driver: small client nets (one per client, stacked) +
    one large server net; exchange = (features, logits, labels)."""

    def __init__(
        self,
        client_bundle: ModelBundle,
        server_bundle: ModelBundle,
        dataset: FedDataset,
        config: FedGKTConfig,
    ):
        self.cb = client_bundle
        self.sb = server_bundle
        self.ds = dataset
        self.cfg = config

        key = jax.random.PRNGKey(config.seed)
        # K independent client models (GKT never averages them)
        self.client_vars = jax.vmap(
            lambda i: client_bundle.init(jax.random.fold_in(key, i))
        )(jnp.arange(config.num_clients))
        self.server_vars = server_bundle.init(jax.random.fold_in(key, 10**6))
        self.server_opt = make_client_optimizer(
            "sgd", config.lr_server, momentum=config.momentum,
            weight_decay=config.weight_decay, grad_clip=config.grad_clip,
        )
        self.server_opt_state = self.server_opt.init(self.server_vars["params"])
        # client optimizers persist across rounds (reference constructs
        # them once in GKTClientTrainer.__init__); stacked like the models
        self.client_opt = make_client_optimizer(
            "sgd", config.lr_client, momentum=config.momentum,
            weight_decay=config.weight_decay, grad_clip=config.grad_clip,
        )
        self.client_opt_states = self.client_opt.init(self.client_vars["params"])
        self.key = key

        # fixed pack geometry: every client padded to the max shard size
        self.steps = cohort_steps_per_epoch(dataset, config.batch_size)
        self.pack = pack_clients(
            dataset, list(range(config.num_clients)), config.batch_size,
            steps_per_epoch=self.steps, seed=config.seed,
        )
        self.num_classes = dataset.num_classes
        # server logits start at zero => round-0 KD term vanishes only if
        # alpha masked; reference round 0 trains clients without KD
        self.server_logits = jnp.zeros(
            (config.num_clients, self.steps, config.batch_size, self.num_classes),
            jnp.float32,
        )
        self._test_pack = batch_eval_pack(
            dataset.test_x, dataset.test_y, max(config.batch_size, 64)
        )

        self._client_phase = jax.jit(self._build_client_phase())
        self._server_phase = jax.jit(self._build_server_phase())
        self._eval_fn = jax.jit(self._build_eval())
        self.round_idx = 0
        self.history = []

    # ---- client phase -------------------------------------------------
    def _build_client_phase(self):
        cfg = self.cfg
        opt = self.client_opt

        def loss_fn(params, others, bx, by, bm, s_logits, use_kd):
            variables = {**others, "params": params}
            (logits, _), new_vars = self.cb.apply_train(variables, bx)
            ce, aux = masked_softmax_ce(logits, by, bm)
            kd = masked_kd_kl(logits, s_logits, bm, cfg.temperature)
            loss = ce + cfg.alpha * kd * use_kd
            return loss, (new_vars, aux)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def one_client(variables, opt_state, x, y, mask, s_logits, use_kd):
            def step(carry, batch):
                variables, opt_state = carry
                bx, by, bm, bl = batch
                others = {k: v for k, v in variables.items() if k != "params"}
                (_, (new_vars, aux)), grads = grad_fn(
                    variables["params"], others, bx, by, bm, bl, use_kd
                )
                updates, opt_state = opt.update(grads, opt_state,
                                                variables["params"])
                params = optax.apply_updates(variables["params"], updates)
                has_real = (bm.sum() > 0).astype(jnp.float32)
                params = jax.tree_util.tree_map(
                    lambda n, o: has_real * n + (1 - has_real) * o,
                    params, variables["params"],
                )
                return ({**new_vars, "params": params}, opt_state), aux

            def epoch(carry, _):
                return jax.lax.scan(step, carry, (x, y, mask, s_logits))

            (variables, opt_state), auxs = jax.lax.scan(
                epoch, (variables, opt_state), jnp.arange(cfg.epochs_client)
            )

            # extraction pass: per-batch features + logits (eval mode,
            # reference GKTClientTrainer.py:92-120 uses model.eval())
            def extract(_, batch):
                bx, _by = batch
                logits, feats = self.cb.apply_eval(variables, bx)
                return (), (feats, logits)

            _, (feats, logits) = jax.lax.scan(extract, (), (x, y))
            metrics = {k: v[-1].sum() for k, v in auxs.items()}
            return variables, opt_state, feats, logits, metrics

        def client_phase(client_vars, opt_states, x, y, mask, server_logits,
                         use_kd):
            return jax.lax.map(
                lambda a: one_client(*a, use_kd),
                (client_vars, opt_states, x, y, mask, server_logits),
            )

        return client_phase

    # ---- server phase -------------------------------------------------
    def _build_server_phase(self):
        cfg = self.cfg

        def loss_fn(params, others, bf, by, bm, c_logits):
            variables = {**others, "params": params}
            logits, new_vars = self.sb.apply_train(variables, bf)
            ce, aux = masked_softmax_ce(logits, by, bm)
            kd = masked_kd_kl(logits, c_logits, bm, cfg.temperature)
            return ce + cfg.alpha * kd, (new_vars, aux)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def server_phase(server_vars, opt_state, feats, y, mask, c_logits):
            # flatten (client, step) into one scan axis
            K, S = y.shape[0], y.shape[1]
            ff = feats.reshape(K * S, *feats.shape[2:])
            yy = y.reshape(K * S, -1)
            mm = mask.reshape(K * S, -1)
            ll = c_logits.reshape(K * S, *c_logits.shape[2:])

            def step(carry, batch):
                variables, opt_state = carry
                bf, by, bm, bl = batch
                others = {k: v for k, v in variables.items() if k != "params"}
                (_, (new_vars, aux)), grads = grad_fn(
                    variables["params"], others, bf, by, bm, bl
                )
                updates, opt_state = self.server_opt.update(
                    grads, opt_state, variables["params"]
                )
                params = optax.apply_updates(variables["params"], updates)
                has_real = (bm.sum() > 0).astype(jnp.float32)
                params = jax.tree_util.tree_map(
                    lambda n, o: has_real * n + (1 - has_real) * o,
                    params, variables["params"],
                )
                return ({**new_vars, "params": params}, opt_state), aux

            def epoch(carry, _):
                return jax.lax.scan(step, carry, (ff, yy, mm, ll))

            (server_vars, opt_state), auxs = jax.lax.scan(
                epoch, (server_vars, opt_state), jnp.arange(cfg.epochs_server)
            )

            # distill back: per-client server logits on stored features
            def back(_, batch):
                bf, = batch
                return (), self.sb.apply_eval(server_vars, bf)

            _, s_logits = jax.lax.scan(back, (), (ff,))
            s_logits = s_logits.reshape(K, S, *s_logits.shape[1:])
            metrics = {k: v[-1].sum() for k, v in auxs.items()}
            return server_vars, opt_state, s_logits, metrics

        return server_phase

    # ---- end-to-end eval ----------------------------------------------
    def _build_eval(self):
        def evaluate(client_vars0, server_vars, x, y, mask):
            # the reference evaluates the server model on features from
            # client 0's extractor (GKTServerTrainer eval path)
            def body(_, batch):
                bx, by, bm = batch
                _, feats = self.cb.apply_eval(client_vars0, bx)
                logits = self.sb.apply_eval(server_vars, feats)
                _, aux = masked_softmax_ce(logits, by, bm)
                return (), aux

            _, auxs = jax.lax.scan(body, (), (x, y, mask))
            return {k: v.sum() for k, v in auxs.items()}

        return evaluate

    # ---- driver --------------------------------------------------------
    def run_round(self) -> dict:
        cfg = self.cfg
        use_kd = jnp.asarray(
            1.0 if (self.round_idx > 0 and cfg.whether_distill_on_client) else 0.0
        )
        x = jnp.asarray(self.pack.x)
        y = jnp.asarray(self.pack.y)
        mask = jnp.asarray(self.pack.mask)
        (self.client_vars, self.client_opt_states, feats, c_logits, cm) = (
            self._client_phase(
                self.client_vars, self.client_opt_states, x, y, mask,
                self.server_logits, use_kd,
            )
        )
        (self.server_vars, self.server_opt_state, self.server_logits, sm) = (
            self._server_phase(
                self.server_vars, self.server_opt_state, feats, y, mask, c_logits
            )
        )
        out = {
            "round": self.round_idx,
            "client_loss_sum": float(cm["loss_sum"].sum()),
            "server_loss_sum": float(sm["loss_sum"]),
            "server_train_acc": float(sm["correct"]) / max(float(sm["count"]), 1.0),
        }
        self.round_idx += 1
        return out

    def evaluate_global(self) -> dict:
        tx, ty, tm = self._test_pack
        cv0 = jax.tree_util.tree_map(lambda l: l[0], self.client_vars)
        res = self._eval_fn(cv0, self.server_vars, jnp.asarray(tx),
                            jnp.asarray(ty), jnp.asarray(tm))
        count = max(float(res["count"]), 1.0)
        return {
            "test_acc": float(res["correct"]) / count,
            "test_loss": float(res["loss_sum"]) / count,
        }

    def run(self, rounds: Optional[int] = None) -> list:
        for _ in range(rounds if rounds is not None else self.cfg.comm_rounds):
            m = self.run_round()
            self.history.append(m)
        self.history[-1].update(self.evaluate_global())
        return self.history
