"""Decentralized online learning (DOL): streaming DSGD and PushSum.

Reference ``fedml_api/standalone/decentralized/`` — DSGD and PushSum
clients over a topology manager, streaming UCI SUSY / room-occupancy
samples, tracking online regret (``decentralized_fl_api.py:11-44``,
``topology_manager.py:5-124``).

TPU-native: the entire stream is one ``lax.scan`` — at step t every
client predicts on its incoming sample (loss BEFORE update = regret
contribution), takes a gradient step, then mixes:

- DSGD (symmetric W):      X ← W·X
- PushSum (column-stochastic P, handles asymmetric links): push
  numerator Z and weight u through P, estimate X = Z/u  — the
  bias-correction that makes directed gossip converge.

Linear/logistic models only (as in the reference); the scan is
jit-compiled end-to-end so a million-step stream is one device program.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class DOLResult:
    regret_curve: np.ndarray  # [T] running average loss
    final_params: np.ndarray  # [N, D(+1)]
    consensus_distance: float


def _logistic_loss_grad(theta, x, y):
    """Binary logistic loss and gradient; y in {0,1}; theta = [w, b]."""
    w, b = theta[:-1], theta[-1]
    z = x @ w + b
    loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    p = jax.nn.sigmoid(z)
    gw = (p - y) * x
    gb = p - y
    return loss, jnp.concatenate([gw, jnp.array([gb])])


def run_dsgd(
    xs: np.ndarray,  # [T, N, D] stream: sample for client i at step t
    ys: np.ndarray,  # [T, N]
    mixing: np.ndarray,  # [N, N] row-stochastic symmetric
    lr: float = 0.1,
) -> DOLResult:
    T, N, D = xs.shape
    W = jnp.asarray(mixing, jnp.float32)

    def step(theta, batch):
        x_t, y_t = batch  # [N, D], [N]
        loss, grad = jax.vmap(_logistic_loss_grad)(theta, x_t, y_t)
        theta = theta - lr * grad
        theta = W @ theta  # gossip mix
        return theta, loss.mean()

    theta0 = jnp.zeros((N, D + 1), jnp.float32)
    theta, losses = jax.lax.scan(step, theta0, (jnp.asarray(xs), jnp.asarray(ys)))
    running = jnp.cumsum(losses) / (jnp.arange(T) + 1)
    mean = theta.mean(axis=0, keepdims=True)
    return DOLResult(
        regret_curve=np.asarray(running),
        final_params=np.asarray(theta),
        consensus_distance=float(jnp.mean(jnp.sum((theta - mean) ** 2, axis=1))),
    )


def run_pushsum(
    xs: np.ndarray,
    ys: np.ndarray,
    mixing: np.ndarray,  # [N, N] COLUMN-stochastic (asymmetric ok)
    lr: float = 0.1,
) -> DOLResult:
    T, N, D = xs.shape
    P = jnp.asarray(mixing, jnp.float32)
    # column-stochastic: each node splits its mass among out-neighbors
    P = P / jnp.maximum(P.sum(axis=0, keepdims=True), 1e-12)

    def step(carry, batch):
        z, u = carry  # z: [N, D+1] numerators, u: [N] push-sum weights
        theta = z / u[:, None]
        x_t, y_t = batch
        loss, grad = jax.vmap(_logistic_loss_grad)(theta, x_t, y_t)
        z = z - lr * grad
        z = P @ z
        u = P @ u
        return (z, u), loss.mean()

    z0 = jnp.zeros((N, D + 1), jnp.float32)
    u0 = jnp.ones((N,), jnp.float32)
    (z, u), losses = jax.lax.scan(step, (z0, u0), (jnp.asarray(xs), jnp.asarray(ys)))
    theta = z / u[:, None]
    running = jnp.cumsum(losses) / (jnp.arange(T) + 1)
    mean = theta.mean(axis=0, keepdims=True)
    return DOLResult(
        regret_curve=np.asarray(running),
        final_params=np.asarray(theta),
        consensus_distance=float(jnp.mean(jnp.sum((theta - mean) ** 2, axis=1))),
    )


def make_stream(
    n_steps: int, n_clients: int, dim: int, seed: int = 0, noise: float = 0.1
) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic linearly-separable stream (UCI-shaped offline stand-in)."""
    rng = np.random.RandomState(seed)
    w_true = rng.normal(0, 1, dim)
    xs = rng.normal(0, 1, (n_steps, n_clients, dim)).astype(np.float32)
    logits = xs @ w_true + noise * rng.normal(0, 1, (n_steps, n_clients))
    ys = (logits > 0).astype(np.float32)
    return xs, ys
