"""FedNova — normalized averaging of heterogeneous local updates.

Reference (``fedml_api/standalone/fednova/fednova.py:79-155`` custom
optimizer + ``fednova_trainer.py:97-123`` server update): each client
accumulates a normalized gradient d_i = cum_grad / a_i where a_i is the
momentum-aware step-count coefficient, and the server applies
``w ← w − τ_eff · Σ p_i d_i`` with τ_eff = Σ p_i a_i and optional global
momentum (gmf).

TPU-native formulation: instead of threading a custom optimizer through
the client loop, d_i is recovered in closed form from the local delta —
for SGD(+momentum ρ, lr η) over τ_i effective steps,
``w_0 − w_τ = η · a_i · d_i`` with

    a_i = τ_i                                 (ρ = 0)
    a_i = (τ_i − ρ(1−ρ^τ_i)/(1−ρ)) / (1−ρ)    (ρ > 0)

τ_i is EXACT per client: the local-update operator reports the number of
optimizer steps it actually executed (pad-only batches are no-ops and do
not count, even after per-epoch shuffling redistributes real samples
across batches — ``core.client`` metrics["steps"]).

The closed form is only valid for vanilla SGD(+momentum): gradient
clipping or weight decay would break ``w_0 − w_τ = η a_i d_i``, so those
config knobs are rejected.

The whole round — local scans, per-client normalization, weighted
reduce, server momentum — is one compiled program, psum-ready on the
``clients`` mesh axis.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import (
    FedAvgConfig,
    FedAvgSimulation,
    ServerState,
)
from fedml_tpu.core import tree as treelib
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.types import FedDataset
from fedml_tpu.models.base import ModelBundle

PyTree = Any


def nova_coefficient(tau, rho: float):
    """a_i for SGD(+momentum) — Wang et al. 2020, momentum case."""
    tau = tau.astype(jnp.float32)
    if rho == 0.0:
        return jnp.maximum(tau, 1.0)
    geom = (1.0 - rho**tau) / (1.0 - rho)
    return jnp.maximum((tau - rho * geom) / (1.0 - rho), 1.0)


def make_fednova_round_fn(
    local_update,
    *,
    lr: float,
    momentum: float,
    gmf: float = 0.0,
    axis_name: Optional[str] = None,
    client_axis_impl: str = "map",
):
    def round_fn(state: ServerState, x, y, mask, num_samples, participation, slot_ids):
        k_round = jax.random.fold_in(state.key, state.round_idx)
        k_train = jax.random.fold_in(k_round, 0)
        client_rngs = jax.vmap(lambda i: jax.random.fold_in(k_train, i))(slot_ids)
        w0 = state.variables["params"]

        run_one = lambda cx, cy, cm, ck: local_update(state.variables, cx, cy, cm, ck)
        if client_axis_impl == "vmap":
            client_vars, client_metrics = jax.vmap(run_one)(x, y, mask, client_rngs)
        else:
            client_vars, client_metrics = jax.lax.map(
                lambda a: run_one(*a), (x, y, mask, client_rngs)
            )

        tau = client_metrics["steps"]  # [K] exact executed optimizer steps
        a = nova_coefficient(tau, momentum)  # [K]

        weights = participation * num_samples
        total = weights.sum()
        if axis_name is not None:
            total = jax.lax.psum(total, axis_name)
        p = weights / jnp.maximum(total, 1e-12)  # [K], sums to 1 globally

        # d_i = (w0 − w_i) / (lr · a_i); Σ p_i d_i and τ_eff = Σ p_i a_i
        d_sum = jax.tree_util.tree_map(
            lambda w0_l, wi_l: jnp.einsum(
                "k,k...->...",
                p / (lr * a),
                w0_l[None].astype(jnp.float32) - wi_l.astype(jnp.float32),
            ),
            w0,
            client_vars["params"],
        )
        tau_eff = (p * a).sum()
        if axis_name is not None:
            d_sum = jax.lax.psum(d_sum, axis_name)
            tau_eff = jax.lax.psum(tau_eff, axis_name)

        if gmf > 0.0:
            buf = treelib.tree_add(treelib.tree_scale(state.opt_state, gmf), d_sum)
            step_dir = buf
            new_opt_state = buf
        else:
            step_dir = d_sum
            new_opt_state = state.opt_state

        new_params = jax.tree_util.tree_map(
            lambda w, d: (w.astype(jnp.float32) - tau_eff * lr * d).astype(w.dtype),
            w0,
            step_dir,
        )
        new_vars = {**state.variables, "params": new_params}
        # non-param collections (e.g. batch_stats): plain weighted
        # average.  Same zero-participation guard as make_round_fn: with
        # total == 0 the p-weighted sum is all zeros and would ZERO the
        # running statistics — keep the old collection instead (params
        # are already safe: the nova step direction is 0).
        for coll in state.variables:
            if coll == "params":
                continue
            summed = jax.tree_util.tree_map(
                lambda leaf: jnp.einsum("k,k...->...", p, leaf.astype(jnp.float32)),
                client_vars[coll],
            )
            if axis_name is not None:
                summed = jax.lax.psum(summed, axis_name)
            new_vars[coll] = jax.tree_util.tree_map(
                lambda s, ref: jnp.where(
                    total > 0, s.astype(ref.dtype), ref
                ),
                summed, state.variables[coll]
            )

        train_metrics = {
            k: (
                jax.lax.psum((participation * v).sum(), axis_name)
                if axis_name
                else (participation * v).sum()
            )
            for k, v in client_metrics.items()
        }
        n_participants = participation.sum()
        if axis_name is not None:
            n_participants = jax.lax.psum(n_participants, axis_name)
        train_metrics["participants"] = n_participants
        new_state = ServerState(
            variables=new_vars,
            opt_state=new_opt_state,
            round_idx=state.round_idx + 1,
            key=state.key,
        )
        return new_state, train_metrics

    # same tag make_round_fn stamps: the fused drivers' shard_map ×
    # on-device-subsampling guard reads it off pre-built kernels
    round_fn.axis_name = axis_name
    return round_fn


class FedNovaSimulation(FedAvgSimulation):
    """Standalone FedNova driver (reference ``standalone/fednova/``),
    sharing the FedAvg simulation loop; only the round kernel differs."""

    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        gmf: float = 0.0,
        loss_fn: LossFn = masked_softmax_ce,
        **kwargs,
    ):
        if config.client_optimizer != "sgd":
            raise ValueError("FedNova requires the SGD client optimizer")
        if config.grad_clip is not None or config.weight_decay:
            raise ValueError(
                "FedNova's closed-form normalization assumes vanilla "
                "SGD(+momentum); grad_clip/weight_decay are unsupported"
            )
        self._gmf = gmf
        super().__init__(bundle, dataset, config, loss_fn=loss_fn, **kwargs)
        if gmf > 0.0:
            self.state = self.state._replace(
                opt_state=treelib.tree_zeros_like(self.state.variables["params"])
            )

    def _build_round_fn(self):
        return make_fednova_round_fn(
            self.local_update,
            lr=self.cfg.lr,
            momentum=self.cfg.momentum,
            gmf=self._gmf,
        )
