"""Hierarchical (two-tier) federated averaging: clients → groups → global.

Reference ``fedml_api/standalone/hierarchical_fl/`` (``trainer.py:43-69``,
``group.py:24-46``): each global round, every group starts from the
global model and runs ``group_comm_round`` rounds of in-group FedAvg;
the global model is then the sample-weighted average of group models.
(The reference version is broken — it imports a module that does not
exist, ``trainer.py:6`` — SURVEY.md §7; this is the working rebuild.)

TPU mapping (SURVEY.md §2.6): groups ↔ ICI slices, the global tier ↔
DCN — a nested (``group``, ``clients``) mesh does the intra-group psum
on ICI and the rare global average across slices.  This module is the
single-host simulation sharing the FedAvg round kernel; the MESH form
is ``fedml_tpu.parallel.spmd.make_hierarchical_spmd_round_fn`` (one
shard_map program, two-level psum), parity-certified against this
simulation in ``tests/test_spmd.py`` and the driver dryrun.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation, ServerState
from fedml_tpu.core import tree as treelib
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.types import FedDataset, pack_clients
from fedml_tpu.models.base import ModelBundle


def assign_groups(
    num_clients: int, num_groups: int, method: str = "random", seed: int = 0
) -> Dict[int, List[int]]:
    """Reference grouping: random equal split of clients into groups."""
    rng = np.random.RandomState(seed)
    ids = rng.permutation(num_clients) if method == "random" else np.arange(num_clients)
    return {
        g: part.tolist() for g, part in enumerate(np.array_split(ids, num_groups))
    }


class HierarchicalSimulation(FedAvgSimulation):
    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        num_groups: int = 2,
        group_comm_round: int = 2,
        groups: Optional[Dict[int, List[int]]] = None,
        group_method: str = "random",
        loss_fn: LossFn = masked_softmax_ce,
        **kwargs,
    ):
        super().__init__(bundle, dataset, config, loss_fn=loss_fn, **kwargs)
        self.groups = groups or assign_groups(
            config.num_clients, num_groups, group_method, seed=config.seed
        )
        self.group_comm_round = group_comm_round
        # group id -> device-resident packed block (see _group_pack)
        self._group_pack_cache = {}

    def _group_pack(self, g, ids):
        """Device-resident per-group block: groups are fixed at init, so
        each group's cohort packs ONCE with a round-independent seed —
        per-(round, group-round) stochasticity is the on-device per-epoch
        permutation keyed by the advancing gstate.round_idx (see
        FedAvgSimulation._device_pack for the rationale and measured
        transfer cost)."""
        hit = self._group_pack_cache.get(g)
        if hit is not None:
            return hit
        from fedml_tpu.core.types import device_resident_pack

        args, host_ns = device_resident_pack(
            self.dataset, ids, self.cfg.batch_size,
            steps_per_epoch=self.steps_per_epoch, seed=self.cfg.seed,
        )
        # host-side total: the group weighted average runs on host
        entry = (args, float(host_ns.sum()))
        self._group_pack_cache[g] = entry
        return entry

    def run_round(self) -> dict:
        """One GLOBAL round = each group runs ``group_comm_round`` in-group
        FedAvg rounds from the global model; then global weighted average."""
        round_idx = int(self.state.round_idx)
        group_vars, group_weights = [], []
        agg_metrics = {"loss_sum": 0.0, "correct": 0.0, "count": 0.0}

        for g, client_ids in self.groups.items():
            gstate = ServerState(
                variables=self.state.variables,
                opt_state=self.state.opt_state,
                round_idx=jnp.asarray(
                    round_idx * self.group_comm_round, jnp.int32
                ),
                key=jax.random.fold_in(self.state.key, 1000 + g),
            )
            ids = np.asarray(client_ids)
            with self.metrics.span("pack"):
                (px, py, pm, pns), group_total = self._group_pack(g, ids)
            with self.metrics.span("round"):
                for gr in range(self.group_comm_round):
                    gstate, metrics = self.round_fn(
                        gstate,
                        px, py, pm, pns,
                        jnp.ones(len(ids), jnp.float32),
                        jnp.asarray(ids, jnp.int32),
                    )
                    # metrics cover EVERY in-group round, not just the last
                    for k in agg_metrics:
                        agg_metrics[k] += float(metrics[k])
            # per-group traffic: every in-group round syncs the group
            # model to each member and collects each member's update
            self._record_sim_comm(len(ids), rounds=self.group_comm_round)
            group_vars.append(gstate.variables)
            group_weights.append(group_total)

        with self.metrics.span("agg"):
            # the group→global weighted average runs on HOST — this is
            # the hierarchy's own aggregation tier, reported as time_agg
            total = sum(group_weights)
            new_vars = treelib.tree_weighted_sum(
                group_vars, [w / total for w in group_weights]
            )
            new_vars = jax.tree_util.tree_map(
                lambda s, ref: s.astype(ref.dtype), new_vars,
                self.state.variables,
            )
        self.state = ServerState(
            variables=new_vars,
            opt_state=self.state.opt_state,
            round_idx=jnp.asarray(round_idx + 1, jnp.int32),
            key=self.state.key,
        )
        out = dict(agg_metrics)
        out["round"] = round_idx
        if out["count"] > 0:
            out["train_acc"] = out["correct"] / out["count"]
            out["train_loss"] = out["loss_sum"] / out["count"]
        return out
