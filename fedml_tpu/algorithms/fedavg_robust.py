"""FedAvg under backdoor attack with robust aggregation defenses.

Reference ``fedml_api/distributed/fedavg_robust/``: client rank 1 is the
attacker training on a poisoned loader (``FedAvgRobustTrainer.py:14-25``,
attack every ``attack_freq`` rounds); the aggregator applies
norm-difference clipping / weak DP (``FedAvgRobustAggregator.py:166-220``)
and reports both main-task and targeted (backdoor) accuracy (``:270+``).

Here: same FedAvg engine; the defense is the ``aggregate_transform``
hook (``fedml_tpu.core.robust``), the attack swaps the attacker slot's
packed data before device upload, and evaluation adds the triggered
test set.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.robust import make_robust_transform
from fedml_tpu.core.types import FedDataset, batch_eval_pack, pack_clients
from fedml_tpu.data.edge_case import PoisonedData, make_backdoor
from fedml_tpu.models.base import ModelBundle


class FedAvgRobustSimulation(FedAvgSimulation):
    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        defense_type: str = "norm_diff_clipping",  # or "weak_dp" / "none"
        norm_bound: float = 30.0,
        stddev: float = 0.025,
        attacker_client: int = 1,  # reference: rank 1 is the attacker
        attack_freq: int = 1,
        target_label: int = 0,
        poison_fraction: float = 0.3,
        poison: Optional[PoisonedData] = None,
        loss_fn: LossFn = masked_softmax_ce,
    ):
        transform = (
            None
            if defense_type in (None, "none")
            else make_robust_transform(
                defense_type, norm_bound=norm_bound, stddev=stddev
            )
        )
        super().__init__(
            bundle, dataset, config, loss_fn=loss_fn, aggregate_transform=transform
        )
        self.attacker_client = attacker_client
        self.attack_freq = max(1, attack_freq)
        self.poison = poison or make_backdoor(
            dataset,
            attacker_client,
            target_label=target_label,
            poison_fraction=poison_fraction,
            seed=config.seed,
        )
        self._backdoor_pack = batch_eval_pack(
            self.poison.backdoor_test_x,
            self.poison.backdoor_test_y,
            max(config.batch_size, 64),
        )

    def run_round(self) -> dict:
        round_idx = int(self.state.round_idx)
        ids = self._sample_ids(round_idx)
        pack = pack_clients(
            self.dataset,
            ids,
            self.cfg.batch_size,
            steps_per_epoch=self.steps_per_epoch,
            seed=self.cfg.seed + round_idx,
        )
        attacking = round_idx % self.attack_freq == 0
        if attacking and self.attacker_client in ids:
            slot = int(np.where(ids == self.attacker_client)[0][0])
            S, B = pack.x.shape[1], pack.x.shape[2]
            px, py, pm = batch_eval_pack(
                self.poison.train_x, self.poison.train_y, B
            )
            steps = min(S, px.shape[0])
            x = pack.x.copy(); y = pack.y.copy(); m = pack.mask.copy()
            x[slot], y[slot], m[slot] = 0, 0, 0.0
            x[slot, :steps] = px[:steps]
            y[slot, :steps] = py[:steps]
            m[slot, :steps] = pm[:steps]
            ns = pack.num_samples.copy()
            ns[slot] = float(pm[:steps].sum())
            pack = type(pack)(x=x, y=y, mask=m, num_samples=ns)

        participation = jnp.ones(len(ids), jnp.float32)
        self.state, metrics = self.round_fn(
            self.state,
            jnp.asarray(pack.x), jnp.asarray(pack.y), jnp.asarray(pack.mask),
            jnp.asarray(pack.num_samples), participation,
            jnp.asarray(ids, jnp.int32),
        )
        out = {k: float(v) for k, v in metrics.items()}
        out["round"] = round_idx
        out["attacking"] = bool(attacking and self.attacker_client in ids)
        if out.get("count", 0) > 0:
            out["train_acc"] = out["correct"] / out["count"]
            out["train_loss"] = out["loss_sum"] / out["count"]
        return out

    def evaluate_backdoor(self) -> dict:
        """Targeted-task accuracy: fraction of triggered samples classified
        as the attacker's target label (lower is better for the defense)."""
        x, y, m = self._backdoor_pack
        res = self.evaluator(
            self.state.variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m)
        )
        c = float(res["count"])
        return {"backdoor_acc": float(res["correct"]) / max(c, 1.0)}

    def _extra_eval(self) -> dict:
        return self.evaluate_backdoor()
