"""FedAvg under backdoor attack with robust aggregation defenses.

Reference ``fedml_api/distributed/fedavg_robust/``: client rank 1 is the
attacker training on a poisoned loader (``FedAvgRobustTrainer.py:14-25``,
attack every ``attack_freq`` rounds); the aggregator applies
norm-difference clipping / weak DP (``FedAvgRobustAggregator.py:166-220``)
and reports both main-task and targeted (backdoor) accuracy (``:270+``).

Here: same FedAvg engine; the defense is the ``aggregate_transform``
hook (``fedml_tpu.core.robust``), the attack swaps the attacker slot's
packed data before device upload, and evaluation adds the triggered
test set.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.robust import make_robust_transform
from fedml_tpu.core.types import FedDataset, batch_eval_pack
from fedml_tpu.data.edge_case import PoisonedData, make_backdoor
from fedml_tpu.models.base import ModelBundle


class FedAvgRobustSimulation(FedAvgSimulation):
    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        defense_type: str = "norm_diff_clipping",  # or "weak_dp" / "none"
        norm_bound: float = 30.0,
        stddev: float = 0.025,
        attacker_client: int = 1,  # reference: rank 1 is the attacker
        attack_freq: int = 1,
        target_label: int = 0,
        poison_fraction: float = 0.3,
        poison: Optional[PoisonedData] = None,
        loss_fn: LossFn = masked_softmax_ce,
        **kwargs,
    ):
        transform = (
            None
            if defense_type in (None, "none")
            else make_robust_transform(
                defense_type, norm_bound=norm_bound, stddev=stddev
            )
        )
        super().__init__(
            bundle, dataset, config, loss_fn=loss_fn,
            aggregate_transform=transform, **kwargs
        )
        self.attacker_client = attacker_client
        self.attack_freq = max(1, attack_freq)
        self.poison = poison or make_backdoor(
            dataset,
            attacker_client,
            target_label=target_label,
            poison_fraction=poison_fraction,
            seed=config.seed,
        )
        self._backdoor_pack = batch_eval_pack(
            self.poison.backdoor_test_x,
            self.poison.backdoor_test_y,
            max(config.batch_size, 64),
        )
        # the attacker's poisoned slot rows on device — see _poison_slot_rows
        self._poison_slot_cache: Optional[tuple] = None

    def _attacking(self, ids, round_idx: int) -> bool:
        return round_idx % self.attack_freq == 0 and self.attacker_client in ids

    def _poison_slot_rows(self) -> tuple:
        """The attacker's poisoned slot — [S, B, ...] device arrays plus
        the true sample count — built once (the poison is fixed for the
        run).  Only this ONE slot is cached; the clean cohort block stays
        the base class's single device-resident copy and the swap happens
        on device (`.at[slot].set`), so the robust path never pins a
        second full-cohort block in HBM."""
        if self._poison_slot_cache is not None:
            return self._poison_slot_cache
        import jax

        S, B = self.steps_per_epoch, self.cfg.batch_size
        px, py, pm = batch_eval_pack(
            self.poison.train_x, self.poison.train_y, B
        )
        steps = min(S, px.shape[0])
        x = np.zeros((S, B, *px.shape[2:]), px.dtype)
        y = np.zeros((S, B, *py.shape[2:]), py.dtype)
        m = np.zeros((S, B), np.float32)
        x[:steps], y[:steps], m[:steps] = px[:steps], py[:steps], pm[:steps]
        ns = float(pm[:steps].sum())
        rows = tuple(jax.device_put(jnp.asarray(a)) for a in (x, y, m)) + (ns,)
        jax.block_until_ready(rows[:3])
        self._poison_slot_cache = rows
        return rows

    def _cohort_block(self, ids, round_idx: int) -> tuple:
        """Attack rounds swap the attacker's slot for their poisoned
        mixture on device; non-attack rounds share the base class's
        device-resident clean block unchanged."""
        clean = super()._cohort_block(ids, round_idx)
        if not self._attacking(ids, round_idx):
            return clean
        px, py, pm, pns = self._poison_slot_rows()
        slot = int(np.where(np.asarray(ids) == self.attacker_client)[0][0])
        x, y, m, ns = clean
        return (
            x.at[slot].set(px.astype(x.dtype)),
            y.at[slot].set(py.astype(y.dtype)),
            m.at[slot].set(pm),
            ns.at[slot].set(pns),
        )

    def _annotate_round(self, out: dict, ids, round_idx: int) -> None:
        out["attacking"] = self._attacking(ids, round_idx)

    def evaluate_backdoor(self) -> dict:
        """Targeted-task accuracy: fraction of triggered samples classified
        as the attacker's target label (lower is better for the defense)."""
        x, y, m = self._backdoor_pack
        res = self.evaluator(
            self.state.variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m)
        )
        c = float(res["count"])
        return {"backdoor_acc": float(res["correct"]) / max(c, 1.0)}

    def _extra_eval(self) -> dict:
        return self.evaluate_backdoor()
