"""FedAvg — the canonical algorithm, TPU-native.

Reference call path (SURVEY.md §3.1/§3.2):
``FedAVGAggregator.aggregate`` (``FedAVGAggregator.py:58-87``) does a
per-key Python loop of sample-weighted state_dict averaging after N MPI
messages arrive; clients run ``MyModelTrainer.train`` epoch loops.

Here one round is ONE compiled program:

    clients' local scans (vmap over a packed client axis, shard_map over
    the ``clients`` mesh axis)  →  masked weighted tree-average
    (einsum over the packed axis + ``lax.psum`` over the mesh axis)  →
    server update hook.

Client subsampling is a participation mask folded into the weights, so
unsampled clients cost zero gradient and no control-flow divergence —
the BASELINE.json north-star design.  The same ``make_round_fn`` drives
both the standalone simulation (reference ``standalone/fedavg/fedavg_api.py``)
and the distributed SPMD path: ONE aggregation kernel for both modes,
fixing the reference's duplicated algorithm code (SURVEY.md §3.2).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.client import LocalUpdateFn, eval_summary, make_client_optimizer, make_evaluator, make_local_update
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.metrics import MetricsLogger
from fedml_tpu.core.types import (
    FedDataset,
    batch_eval_pack,
    cohort_steps_per_epoch,
    device_resident_pack,
    pack_clients,
)
from fedml_tpu.models.base import ModelBundle

PyTree = Any

# (old_variables, aggregated_variables, opt_state) -> (new_variables, opt_state)
ServerUpdateFn = Callable[[PyTree, PyTree, Any], Tuple[PyTree, Any]]


class ServerState(NamedTuple):
    variables: PyTree
    opt_state: Any
    round_idx: jax.Array
    key: jax.Array
    # error-feedback residual store (update-compression subsystem,
    # ``fedml_tpu/compress``): a [num_clients, ...] pytree of fp32
    # per-client quantization residuals, () when compression/EF is off.
    # Lives IN the round state so the fused scans carry it and
    # checkpoint/resume stays bit-identical under compression.
    residuals: Any = ()


def default_server_update(old, agg, opt_state):
    """Plain FedAvg: the aggregate replaces the global model."""
    del old
    return agg, opt_state


def resolve_compute_dtype(name):
    """'bf16'/'bfloat16'/'fp32'/None → jnp dtype or None (fp32 = off)."""
    if name is None or name in ("fp32", "float32"):
        return None
    if name in ("bf16", "bfloat16"):
        return jnp.bfloat16
    if name in ("fp16", "float16"):
        raise ValueError(
            "float16 compute needs loss scaling (its ~6e-5 normal minimum "
            "underflows gradients), which this path does not implement; "
            "use bf16 (same MXU rate, fp32-range exponent)"
        )
    raise ValueError(f"unknown compute dtype: {name!r}")


def make_round_fn(
    local_update: LocalUpdateFn,
    *,
    server_update: ServerUpdateFn = default_server_update,
    aggregate_transform: Optional[Callable] = None,
    axis_name: Optional[str] = None,
    client_axis_impl: str = "map",
    client_unroll: int = 1,
    codec=None,
    error_feedback: bool = False,
    aggregate_impl: Optional[Callable] = None,
):
    """Build the per-round function over a packed client block.

    round_fn(state, x, y, mask, num_samples, participation) with client
    leading dim K on the data args; ``participation`` is the [K] 0/1 mask.
    When ``axis_name`` is set the weighted sums are additionally psum'd
    across the device mesh (SPMD full-resident mode).

    ``aggregate_transform(old_variables, stacked_client_variables,
    weights, rng) -> stacked_client_variables`` is the hook robust
    aggregation plugs into (norm clipping / weak-DP noise run per-client
    before the sum, inside the same compiled program).

    ``client_unroll`` unrolls the sequential client loop (``lax.map``
    lowers to a while loop; its scalar-core bookkeeping is measurable
    next to small per-client bodies) — trades compiled-code size for
    fewer loop iterations, like the step-scan ``unroll`` inside
    ``make_local_update``.

    ``codec`` (a ``fedml_tpu.compress`` LeafCodec) simulates the lossy
    uplink INSIDE the compiled round: each client's update
    ``delta = trained - global`` goes through ``decode(encode(delta))``
    before aggregation — bit-identical to what a real transport would
    reconstruct, because the wire form runs the same jnp encode
    (``compress/codecs.py``).  Compression randomness is the third
    fold_in sub-stream of the round key (train=0, agg noise=1,
    compress=2), keyed per GLOBAL slot id, so R fused rounds stay
    bit-equal to R dispatched rounds.  ``error_feedback`` threads the
    EF recurrence through ``state.residuals`` ([num_clients, ...] fp32
    store, gathered/scattered by slot id) — required for convergence
    with biased codecs (top-k) and tighter tracking for quantizers.
    """
    if error_feedback and axis_name is not None:
        raise ValueError(
            "error_feedback is not defined under shard_map (axis_name="
            f"{axis_name!r}): the residual store is gathered by GLOBAL "
            "slot id, which a device-local block cannot index; compress "
            "on the host path instead"
        )
    if codec is not None:
        from fedml_tpu.compress import COMPRESS_STREAM, roundtrip_tree

    def round_fn(state: ServerState, x, y, mask, num_samples, participation, slot_ids):
        # slot_ids are GLOBAL client slot indices — under shard_map each
        # device sees only its local block, so a local arange would collide
        # RNG streams across devices.  Two independent sub-streams per
        # round (training vs aggregation noise) so per-client keys never
        # collide across uses.
        k_round = jax.random.fold_in(state.key, state.round_idx)
        k_train = jax.random.fold_in(k_round, 0)
        k_agg = jax.random.fold_in(k_round, 1)
        client_rngs = jax.vmap(lambda i: jax.random.fold_in(k_train, i))(slot_ids)
        # Model sync = SPMD replication (no explicit send).  Client-axis
        # mapping: sequential lax.map keeps each client's convs at full
        # MXU tile sizes (measured ~7x faster than vmap for ResNet-56 on
        # one v5e chip); vmap remains available for many tiny clients.
        run_one = lambda cx, cy, cm, ck: local_update(state.variables, cx, cy, cm, ck)
        if client_axis_impl == "vmap":
            client_vars, client_metrics = jax.vmap(run_one)(x, y, mask, client_rngs)
        elif client_unroll > 1:
            # lax.map is scan-without-carry; express it as such to get
            # scan's unroll knob (lax.map grew batch_size, not unroll)
            client_vars, client_metrics = jax.lax.scan(
                lambda c, args: (c, run_one(*args)),
                (), (x, y, mask, client_rngs), unroll=client_unroll,
            )[1]
        else:
            client_vars, client_metrics = jax.lax.map(
                lambda args: run_one(*args), (x, y, mask, client_rngs)
            )

        residuals = state.residuals
        if codec is not None:
            # lossy uplink: what the server aggregates is the DECODED
            # update, exactly what the wire form reconstructs.  EF folds
            # the per-client residual in before encoding and keeps the
            # new quantization error for the next round (participation-
            # masked: a client that did not report keeps its residual).
            k_comp = jax.random.fold_in(k_round, COMPRESS_STREAM)
            comp_rngs = jax.vmap(
                lambda i: jax.random.fold_in(k_comp, i)
            )(slot_ids)
            f32 = jnp.float32

            def lossy_one(cvars, rng, res_row):
                delta = jax.tree_util.tree_map(
                    lambda c, g: c.astype(f32) - g.astype(f32),
                    cvars, state.variables,
                )
                if error_feedback:
                    delta = jax.tree_util.tree_map(jnp.add, delta, res_row)
                dec = roundtrip_tree(codec, delta, rng)
                new_cvars = jax.tree_util.tree_map(
                    lambda g, d: (g.astype(f32) + d).astype(g.dtype),
                    state.variables, dec,
                )
                new_res = (
                    jax.tree_util.tree_map(jnp.subtract, delta, dec)
                    if error_feedback else ()
                )
                return new_cvars, new_res

            if error_feedback:
                res_rows = jax.tree_util.tree_map(
                    lambda r: r[slot_ids], state.residuals
                )
                client_vars, res_new = jax.vmap(lossy_one)(
                    client_vars, comp_rngs, res_rows
                )
                keep = lambda new, old: jnp.where(
                    participation.reshape(
                        (-1,) + (1,) * (new.ndim - 1)
                    ) > 0,
                    new, old,
                )
                res_rows = jax.tree_util.tree_map(keep, res_new, res_rows)
                residuals = jax.tree_util.tree_map(
                    lambda store, rows: store.at[slot_ids].set(rows),
                    state.residuals, res_rows,
                )
            else:
                client_vars, _ = jax.vmap(
                    lambda c, r: lossy_one(c, r, None)
                )(client_vars, comp_rngs)

        weights = participation * num_samples  # sample-weighted, masked
        if aggregate_transform is not None:
            # per-client keys from GLOBAL slot ids: independent noise per
            # client even under shard_map (a single replicated key would
            # stamp identical noise on every device's local block)
            agg_rngs = jax.vmap(lambda i: jax.random.fold_in(k_agg, i))(slot_ids)
            client_vars = aggregate_transform(
                state.variables, client_vars, weights, agg_rngs
            )

        if aggregate_impl is not None:
            # pluggable weighted-sum kernel: the partition-rule engine
            # (parallel/partition.py) substitutes a sequential lax.scan
            # here — on a dp-sharded mesh the GSPMD partitioner may
            # partial-sum the einsum's K axis per device, which
            # reassociates the fp32 reduction and breaks the
            # sharded-vs-replicated sha256 parity pins
            num = aggregate_impl(weights, client_vars)
        else:
            num = jax.tree_util.tree_map(
                lambda leaf: jnp.einsum(
                    "k,k...->...", weights, leaf.astype(jnp.float32)
                ),
                client_vars,
            )
        den = weights.sum()
        n_participants = participation.sum()
        if axis_name is not None:
            num = jax.lax.psum(num, axis_name)
            den = jax.lax.psum(den, axis_name)
            n_participants = jax.lax.psum(n_participants, axis_name)
        # zero-participation guard: with den == 0 (every client dropped
        # or deadline-missed this round) the weighted average is
        # undefined — 0/eps would ZERO the global model and the next
        # round's gradients would NaN-poison it.  A participant-less
        # round is a no-op update (the cross-device server's
        # dropped_all semantics), and the driver counts it as degraded.
        agg = jax.tree_util.tree_map(
            lambda s, ref: jnp.where(
                den > 0,
                (s / jnp.maximum(den, 1e-12)).astype(ref.dtype),
                ref,
            ),
            num,
            state.variables,
        )
        new_vars, new_opt = server_update(state.variables, agg, state.opt_state)

        train_metrics = {
            k: (
                jax.lax.psum((participation * v).sum(), axis_name)
                if axis_name
                else (participation * v).sum()
            )
            for k, v in client_metrics.items()
        }
        # realized cohort size: the drivers' degraded-round detector
        # (participants == 0 -> rounds.degraded counter, model unchanged)
        train_metrics["participants"] = n_participants
        new_state = ServerState(
            variables=new_vars,
            opt_state=new_opt,
            round_idx=state.round_idx + 1,
            key=state.key,
            residuals=residuals,
        )
        return new_state, train_metrics

    # the baked-in mesh axis travels WITH the kernel: a pre-built
    # shard_map kernel handed to a fused driver must still trip the
    # on-device-subsampling guard (ADVICE r5 — round_kw is empty there,
    # so the kwarg-based check alone cannot fire)
    round_fn.axis_name = axis_name
    return round_fn


def _resolve_round_fn(local_update, round_fn, round_kw,
                      on_device_sampling: bool = False):
    """Shared by both fused drivers: ``round_fn`` is a PRE-BUILT round
    kernel (the ``_build_round_fn`` subclass hook — FedNova's
    normalized aggregation etc.); the fused scans are kernel-agnostic,
    so any same-signature kernel fuses (VERDICT r4 weak #6: the fused
    fast paths used to refuse exactly the algorithms that need long
    runs).  Kernel-shaping kwargs must already be baked into it.

    ``on_device_sampling`` marks a caller that draws per-round
    participation masks on device (clients_per_round / drop_prob):
    under shard_map each device sees only its local client block, so
    such a draw would silently be per-device-local — the guard reads
    the axis_name either from ``round_kw`` or from the tag
    ``make_round_fn`` stamps on the kernel it returns."""
    baked_axis = round_kw.get("axis_name") or getattr(
        round_fn, "axis_name", None
    )
    if on_device_sampling and baked_axis:
        raise ValueError(
            "on-device clients_per_round/drop_prob are not defined under "
            f"shard_map (axis_name={baked_axis!r}: local block != global "
            "client axis); pass per-round masks from the host instead"
        )
    if round_fn is not None:
        if round_kw:
            raise ValueError(
                "round_fn is a pre-built kernel; kernel-shaping kwargs "
                f"{sorted(round_kw)} must be baked into it"
            )
        return round_fn
    return make_round_fn(local_update, **round_kw)


def make_multi_round_fn(
    local_update: Optional[LocalUpdateFn],
    rounds_per_call: int,
    *,
    clients_per_round: Optional[int] = None,
    drop_prob: float = 0.0,
    round_fn: Optional[Callable] = None,
    **round_kw,
):
    """Fuse ``rounds_per_call`` federated rounds into ONE compiled
    program: a ``lax.scan`` over the round kernel with zero host syncs
    in between (SURVEY.md §7 "avoid per-round host sync except
    metrics").

    This is the cross-silo resident-cohort execution mode — the
    BASELINE north-star regime (all clients' packed shards stay on
    device across rounds).  Per-round cohort subsampling and failure
    injection move on-device: ``clients_per_round`` re-draws a seeded
    uniform participation mask from the server key each round
    (``core/sampling.py`` semantics), and ``drop_prob`` composes
    ``inject_dropout`` on top.  Because the round kernel derives all
    randomness from ``fold_in(state.key, state.round_idx)``, R fused
    rounds produce bit-identical results to R sequential
    ``make_round_fn`` calls (pinned by
    ``tests/test_fedavg.py::test_multi_round_fused_matches_sequential``).

    Measured on one v5e chip this removes the ~40% device-idle gaps a
    per-round dispatch+readback loop spends on the host round-trip
    (PROFILE.md).  Returns ``(final_state, metrics)`` with each metric
    stacked ``[rounds_per_call, ...]``.

    Note: on-device subsampling and dropout need the FULL client axis in
    view, so ``clients_per_round``/``drop_prob`` are for the
    single-program path; under shard_map (``axis_name`` set) each device
    only sees its local block — a global exactly-K draw would need a
    collective, and the replicated key would stamp identical drop
    patterns on every device's block — so pass per-round masks from the
    host there instead.
    """
    from fedml_tpu.core.sampling import (
        eligible_participation_mask,
        inject_dropout,
    )

    if clients_per_round is not None and clients_per_round < 1:
        raise ValueError(
            f"clients_per_round must be >= 1, got {clients_per_round} "
            "(0 would zero every round's weighted average)"
        )
    rf = _resolve_round_fn(
        local_update, round_fn, round_kw,
        on_device_sampling=clients_per_round is not None or bool(drop_prob),
    )

    def multi_round_fn(
        state: ServerState, x, y, mask, num_samples, participation, slot_ids
    ):
        num_clients = participation.shape[0]

        def body(st, _):
            part = participation
            if (
                clients_per_round is not None
                and clients_per_round < num_clients
            ):
                # eligibility-aware draw: samples only among the caller's
                # participation>0 clients, never yielding an empty cohort
                # (which would zero the weighted average)
                part = eligible_participation_mask(
                    st.key, st.round_idx, participation, clients_per_round
                )
            if drop_prob:
                part = inject_dropout(st.key, st.round_idx, part, drop_prob)
            return rf(st, x, y, mask, num_samples, part, slot_ids)

        return jax.lax.scan(body, state, None, length=rounds_per_call)

    return multi_round_fn


def make_scheduled_multi_round_fn(
    local_update: Optional[LocalUpdateFn],
    *,
    drop_prob: float = 0.0,
    drop_seed: int = 0,
    round_fn: Optional[Callable] = None,
    **round_kw,
):
    """Fuse R rounds whose cohorts DIFFER per round: every data arg
    carries a leading ``[R]`` round axis and the scan consumes one
    cohort slice per round.

    This is the cross-DEVICE counterpart of ``make_multi_round_fn``
    (whose resident-cohort form assumes the same block every round —
    the cross-silo regime).  Sampled-cohort rounds (10 of 1000+ clients)
    can't keep everyone resident without 100x wasted compute, and the
    per-round dispatch loop pays a full host round-trip per round
    (measured 6.6 s/round for mnist_lr on the axon tunnel, almost all
    host overhead).  Here the HOST pre-samples the next R cohorts from
    the same ``host_sample_ids`` stream, packs them into one
    ``[R, K, ...]`` block, and one compiled program runs all R rounds —
    no wasted compute, no per-round host sync (VERDICT r3 weak #7).

    Bit-equivalence with the dispatch loop holds for the same reason
    ``make_multi_round_fn``'s does: the round kernel derives all
    randomness from ``fold_in(state.key, state.round_idx)``, and
    ``drop_prob`` reproduces ``run_round``'s exact
    ``inject_dropout(PRNGKey(drop_seed), round_idx, ...)`` draw
    (``tests/test_fedavg.py::test_run_fused_sampled_matches_run``).
    """
    from fedml_tpu.core.sampling import inject_dropout

    # drop_prob here draws from a host-replicated key per LOCAL slot —
    # the same per-device-local-mask hazard as the resident driver's
    # on-device subsampling, so the same guard applies
    rf = _resolve_round_fn(local_update, round_fn, round_kw,
                           on_device_sampling=bool(drop_prob))

    def scheduled_fn(
        state: ServerState, x, y, mask, num_samples, participation, slot_ids
    ):
        def body(st, per_round):
            px, py, pm, pns, ppart, pids = per_round
            if drop_prob:
                ppart = inject_dropout(
                    jax.random.PRNGKey(drop_seed), st.round_idx, ppart,
                    drop_prob,
                )
            return rf(st, px, py, pm, pns, ppart, pids)

        return jax.lax.scan(
            body, state, (x, y, mask, num_samples, participation, slot_ids)
        )

    return scheduled_fn


@dataclasses.dataclass
class FedAvgConfig:
    num_clients: int = 10
    clients_per_round: int = 10
    comm_rounds: int = 10
    epochs: int = 1
    batch_size: int = 10
    client_optimizer: str = "sgd"
    lr: float = 0.03
    momentum: float = 0.0
    # None = optimizer default (0 for sgd, the reference's 1e-4 torch
    # Adam default); an explicit 0.0 is honored as zero decay
    weight_decay: Optional[float] = None
    grad_clip: Optional[float] = None
    frequency_of_the_test: int = 5
    seed: int = 0
    prox_mu: float = 0.0  # FedProx is FedAvg with mu > 0
    # mixed precision: "bf16" runs forward/backward in bfloat16 on the MXU
    # while master params / optimizer state / aggregation stay float32
    compute_dtype: Optional[str] = None
    # failure injection (SURVEY §5.3): each sampled client independently
    # drops mid-round with this probability; masked-psum aggregation
    # excludes them exactly (tests/test_fedavg.py)
    drop_prob: float = 0.0
    # update compression (fedml_tpu/compress): codec name for the lossy
    # uplink simulated inside the compiled round — "int8"/"qsgd8",
    # "int4"/"qsgd4", "bf16", "topk<rate>"; None/"" = fp32 (off).
    # compress_ef threads the error-feedback residual store through
    # ServerState (REQUIRED for topk; recommended for quantizers).
    compress_codec: Optional[str] = None
    compress_ef: bool = False


class FedAvgSimulation:
    """Single-process simulation driver — the reference's standalone mode
    (``standalone/fedavg/fedavg_api.py:40-81``), sharing the distributed
    path's round kernel.

    Per round: seeded uniform sampling of K clients (host), pack their
    shards to fixed shape, run the compiled round, periodically evaluate.
    """

    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        loss_fn: LossFn = masked_softmax_ce,
        server_update: ServerUpdateFn = default_server_update,
        server_opt_init: Optional[Callable[[PyTree], Any]] = None,
        aggregate_transform: Optional[Callable] = None,
        local_update: Optional[LocalUpdateFn] = None,
        augment_fn: Optional[Callable] = None,
        client_lr: Optional[Any] = None,
        metrics: Optional[MetricsLogger] = None,
    ):
        """``client_lr`` overrides ``config.lr`` for the client optimizer
        and may be an optax schedule (count -> lr), e.g. FedNAS's
        per-epoch cosine — every other config knob (prox_mu, grad_clip,
        compute_dtype, augment_fn) keeps applying unchanged.
        ``metrics`` is the observability sink (spans + JSONL + telemetry);
        omitted, a file-less logger still feeds the process telemetry
        registry so counters/histograms accumulate either way."""
        self.bundle = bundle
        self.dataset = dataset
        self.cfg = config
        self.loss_fn = loss_fn
        self.metrics = metrics or MetricsLogger()
        optimizer = make_client_optimizer(
            config.client_optimizer,
            config.lr if client_lr is None else client_lr,
            momentum=config.momentum,
            weight_decay=config.weight_decay,
            grad_clip=config.grad_clip,
        )
        cdtype = resolve_compute_dtype(config.compute_dtype)
        self.local_update = local_update or make_local_update(
            bundle,
            optimizer,
            config.epochs,
            loss_fn,
            prox_mu=config.prox_mu,
            augment_fn=augment_fn,
            compute_dtype=cdtype,
        )
        self._server_update = server_update
        self._aggregate_transform = aggregate_transform
        # update compression (lossy uplink inside the compiled round):
        # resolve the codec once; subclasses that build their OWN round
        # kernel (FedNova, FedNAS) have no compression stage — refuse
        # loudly rather than silently training uncompressed
        from fedml_tpu.compress import encoded_nbytes, get_codec

        self._codec = get_codec(config.compress_codec)
        self._codec_ef = bool(config.compress_ef) and self._codec is not None
        if (
            self._codec is not None
            and type(self)._build_round_fn
            is not FedAvgSimulation._build_round_fn
        ):
            raise ValueError(
                f"{type(self).__name__} builds its own round kernel; "
                "compress_codec is only wired through the base FedAvg "
                "kernel (make_round_fn)"
            )
        # compile-event tracking per jit signature (obs layer): a cohort
        # geometry that varies per round shows up as jax.compiles{fn=
        # round_fn} climbing instead of sitting at 1-2 (recompile storm)
        from fedml_tpu.obs.jax_hooks import instrument_jit

        self.round_fn = instrument_jit(
            jax.jit(self._build_round_fn()), "round_fn",
            telemetry=self.metrics.telemetry,
        )
        self.evaluator = instrument_jit(
            make_evaluator(bundle, loss_fn), "evaluator",
            telemetry=self.metrics.telemetry,
        )

        key = jax.random.PRNGKey(config.seed)
        variables = bundle.init(key)
        opt_state = server_opt_init(variables) if server_opt_init else ()
        # EF residual store: one fp32 row per client (zeros at round 0);
        # sized by the FULL population so sampled cohorts gather/scatter
        # their rows by global slot id
        residuals = ()
        if self._codec_ef:
            residuals = jax.tree_util.tree_map(
                lambda l: jnp.zeros(
                    (config.num_clients,) + tuple(np.shape(l)), jnp.float32
                ),
                variables,
            )
        self.state = ServerState(
            variables=variables,
            opt_state=opt_state,
            round_idx=jnp.zeros((), jnp.int32),
            key=key,
            residuals=residuals,
        )
        # fixed pack geometry across rounds → one compilation
        self.steps_per_epoch = cohort_steps_per_epoch(
            dataset, config.batch_size
        )
        # datasets without a held-out split (stackoverflow real-h5
        # missing *_test.h5) still TRAIN; the refusal happens at eval
        # time with batch_eval_pack's actionable message
        self._test_pack = (
            None if dataset.test_x is None else batch_eval_pack(
                dataset.test_x, dataset.test_y, max(config.batch_size, 64)
            )
        )
        self.history = []
        # checkpoint/resume wiring (attach_checkpointing): periodic full
        # ServerState saves + fault-injection crash knob for resume tests
        self._ckpt_mgr = None
        self._ckpt_every = 0
        self._ckpt_last: Optional[int] = None
        self.crash_at_round: Optional[int] = None
        # (cohort key, device-resident packed block) — see _device_pack
        self._pack_cache: Optional[tuple] = None
        # logical model payload per participant per direction (fp32 wire
        # bytes) — the simulation's comm accounting (_record_sim_comm)
        self._model_nbytes = sum(
            int(getattr(l, "size", 1))
            * int(getattr(getattr(l, "dtype", None), "itemsize", 4) or 4)
            for l in jax.tree_util.tree_leaves(variables)
        )
        # exact encoded payload bytes per upload (static given shapes):
        # the compression-ratio accounting for simulated traffic
        self._enc_nbytes = (
            encoded_nbytes(self._codec, variables)
            if self._codec is not None else self._model_nbytes
        )

    def _build_round_fn(self):
        """Subclass hook: FedNova etc. swap in a different round kernel."""
        return make_round_fn(
            self.local_update,
            server_update=self._server_update,
            aggregate_transform=self._aggregate_transform,
            codec=self._codec,
            error_feedback=self._codec_ef,
        )

    # -- checkpoint/resume --------------------------------------------------
    def attach_checkpointing(self, manager, every: int = 1) -> None:
        """Wire periodic persistence: save the FULL round state pytree —
        (variables, server opt state, round_idx, rng key) — every
        ``every`` completed rounds and at the end of each run call.
        Because every source of randomness derives from
        ``fold_in(state.key, state.round_idx)``, a restored state
        continues BIT-identically to the uninterrupted run
        (``tests/test_checkpoint_metrics.py``)."""
        self._ckpt_mgr = manager
        self._ckpt_every = max(1, int(every))

    def resume(self) -> int:
        """Restore the latest readable checkpoint into ``self.state``;
        returns the number of already-completed rounds (0 = fresh)."""
        if self._ckpt_mgr is None or self._ckpt_mgr.latest_step() is None:
            return 0
        template = jax.tree_util.tree_map(np.asarray, self.state)
        restored = self._ckpt_mgr.restore(like=template)
        self.state = jax.tree_util.tree_map(jnp.asarray, restored)
        done = int(self.state.round_idx)
        self._ckpt_last = done
        self.metrics.telemetry.event("resume", round=done)
        return done

    def _maybe_checkpoint(self, final: bool = False) -> None:
        if self._ckpt_mgr is None:
            return
        step = int(self.state.round_idx)
        if step == self._ckpt_last:  # this step is already on disk
            return
        if final or step % self._ckpt_every == 0:
            with self.metrics.span("checkpoint"):
                self._ckpt_mgr.save(step, self.state)
            self._ckpt_last = step

    def _crash_if_scheduled(self) -> None:
        """Fault injection: hard-exit (as a SIGKILL would) right before
        the scheduled round trains — the crash-then-``--resume``
        bit-identity path of ``tools/chaos_run.py`` / ``experiments/run``."""
        if (
            self.crash_at_round is not None
            and int(self.state.round_idx) == self.crash_at_round
        ):
            import os

            os._exit(137)

    def _count_degraded(self, row: dict) -> None:
        """A round whose realized cohort was empty left the model
        untouched (the round kernel's den>0 guard) — count it on the
        same series the cross-device server uses."""
        if row.get("participants", 1.0) <= 0:
            self.metrics.telemetry.inc("rounds.degraded")

    def _extra_eval(self) -> dict:
        """Subclass hook: extra metrics at eval rounds (e.g. backdoor acc)."""
        return {}

    def _sample_ids(self, round_idx: int) -> np.ndarray:
        from fedml_tpu.core.sampling import host_sample_ids

        return host_sample_ids(
            self.cfg.seed, round_idx, self.cfg.num_clients,
            self.cfg.clients_per_round,
        )

    def _device_pack(self, ids) -> tuple:
        """Device-resident cohort data (HBM-resident client shards).

        A cohort's packed block is gathered ONCE and kept on device
        across rounds: the pack's base sample order carries no
        stochasticity (the local update re-permutes every epoch
        on-device from the (key, round, slot) stream), so re-packing
        per round would re-ship the whole cohort host→device each round
        for nothing — measured ~240 s/round vs ~65 s at the north-star
        CIFAR scale through the TPU tunnel.  This is the pod execution
        model: a chip's client shards live in HBM for the whole run.

        The cache holds ONE cohort: in the full-participation cross-silo
        regime the key never changes (always hits), and in the sampled
        regime the key changes nearly every round — keeping more entries
        would pin multi-GB device blocks with near-zero hit rate.
        """
        key = tuple(int(i) for i in ids)
        if self._pack_cache is not None and self._pack_cache[0] == key:
            return self._pack_cache[1]
        args, _ = device_resident_pack(
            self.dataset, ids, self.cfg.batch_size,
            steps_per_epoch=self.steps_per_epoch, seed=self.cfg.seed,
        )
        self._pack_cache = (key, args)
        return args

    def _cohort_block(self, ids, round_idx: int) -> tuple:
        """Subclass hook: the (x, y, mask, num_samples) device block for
        this round's cohort (e.g. the robust attacker's poisoned swap)."""
        del round_idx
        return self._device_pack(ids)

    def _annotate_round(self, out: dict, ids, round_idx: int) -> None:
        """Subclass hook: add per-round fields to the metrics row."""

    def _record_sim_comm(self, cohort: int, rounds: int = 1,
                         uploads: Optional[int] = None) -> None:
        """Logical federation traffic for simulated rounds: the server
        syncs the model to each sampled participant and receives one
        update back from each SURVIVING one (S2C_SYNC_MODEL down,
        C2S_SEND_MODEL up) — the bytes a real transport would move, on
        the SAME counter series the comm backends use, so
        ``tools/trace_summary.py`` renders one table for simulated and
        message-driven runs alike.  ``uploads`` defaults to the full
        cohort; dispatch rounds pass the realized participation count
        (a dropped client never sends its update), fused drivers the
        expectation (their drop draws happen on device)."""
        t = self.metrics.telemetry
        down = cohort * rounds
        up = down if uploads is None else uploads
        t.inc("comm.sent_msgs", down, msg_type="S2C_SYNC_MODEL")
        t.inc("comm.sent_bytes", self._model_nbytes * down,
              msg_type="S2C_SYNC_MODEL")
        t.inc("comm.recv_msgs", up, msg_type="C2S_SEND_MODEL")
        # uplink bytes follow the codec: a compressed round's C2S
        # traffic is the exact encoded payload size, and the raw/
        # compressed counter pair feeds trace_summary's ratio section
        t.inc("comm.recv_bytes", self._enc_nbytes * up,
              msg_type="C2S_SEND_MODEL")
        if self._codec is not None:
            t.inc("comm.raw_bytes", self._model_nbytes * up,
                  msg_type="C2S_SEND_MODEL")
            t.inc("comm.compressed_bytes", self._enc_nbytes * up,
                  msg_type="C2S_SEND_MODEL")

    def run_round(self) -> dict:
        round_idx = int(self.state.round_idx)
        with self.metrics.span("sample"):
            ids = self._sample_ids(round_idx)
        with self.metrics.span("pack"):
            x, y, mask, num_samples = self._cohort_block(ids, round_idx)
        participation = jnp.ones(len(ids), jnp.float32)
        if self.cfg.drop_prob > 0.0:
            from fedml_tpu.core.sampling import inject_dropout

            participation = inject_dropout(
                jax.random.PRNGKey(self.cfg.seed), round_idx, participation,
                self.cfg.drop_prob,
            )
        with self.metrics.span("round"):
            self.state, metrics = self.round_fn(
                self.state,
                x,
                y,
                mask,
                num_samples,
                participation,
                jnp.asarray(ids, jnp.int32),
            )
            # the float() readbacks force device completion, so the span
            # measures the real round, not the async enqueue
            out = {k: float(v) for k, v in metrics.items()}
        # realized upload count: dropped clients received the sync but
        # never sent a model back (participation already synced above)
        self._record_sim_comm(
            len(ids), uploads=int(round(float(participation.sum())))
        )
        out["round"] = round_idx
        if out.get("count", 0) > 0:
            out["train_acc"] = out["correct"] / out["count"]
            out["train_loss"] = out["loss_sum"] / out["count"]
        self._annotate_round(out, ids, round_idx)
        return out

    def evaluate_global(self) -> dict:
        if self._test_pack is None:
            # same refusal (and message) the pack itself raises —
            # deferred here so training-only use never hits it
            batch_eval_pack(self.dataset.test_x, self.dataset.test_y, 64)
        x, y, m = self._test_pack
        res = self.evaluator(
            self.state.variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m)
        )
        return eval_summary(res)

    def run(self, rounds: Optional[int] = None, log_fn=None) -> list:
        rounds = rounds if rounds is not None else self.cfg.comm_rounds
        for i in range(rounds):
            self._crash_if_scheduled()
            metrics = self.run_round()
            self._count_degraded(metrics)
            r = metrics["round"]
            # final-round eval keys on THIS call's last iteration, not the
            # absolute round index, so run(rounds=N) and resumed runs also
            # end with test metrics in their last history row
            if (
                r % self.cfg.frequency_of_the_test == 0
                or i == rounds - 1
            ):
                with self.metrics.span("eval"):
                    metrics.update(self.evaluate_global())
                metrics.update(self._extra_eval())
                # eval rounds are the natural cadence for the device
                # HBM high-water gauge (None-guarded on CPU backends)
                from fedml_tpu.obs.jax_hooks import record_device_memory

                record_device_memory(self.metrics.telemetry)
            # per-round spans (time_sample/pack/round/eval) land in the
            # history row AND the metrics.jsonl record stream
            metrics.update(self.metrics.pop_spans())
            self.metrics.log(metrics, step=r)
            self.history.append(metrics)
            if log_fn:
                log_fn(metrics)
            self._maybe_checkpoint()
        self._maybe_checkpoint(final=True)
        return self.history

    def run_fused(
        self,
        rounds: Optional[int] = None,
        log_fn=None,
        rounds_per_call: Optional[int] = None,
    ) -> list:
        """Full-participation driver on the framework's fast path: the
        rounds BETWEEN evals run as one ``make_multi_round_fn`` program
        (zero host syncs), so recorded wall-clock/round is the number
        ``bench.py`` demonstrates, not the per-round dispatch loop's
        (VERDICT r2 weak #2: 63 s/round dispatched vs ~35 s fused at
        north-star scale).

        Bit-equivalence with ``run()``: the round kernel derives ALL
        randomness from ``fold_in(state.key, state.round_idx)`` and the
        cohort block is device-resident and round-independent, so R
        fused rounds == R dispatched rounds exactly
        (``tests/test_fedavg.py::test_run_fused_matches_run``).

        Scope: full participation (the cohort == every client; on-device
        subsampling is the benchmark driver's job).  ``_build_round_fn``
        overrides (FedNova's normalized aggregation) ARE honored — the
        fused scan wraps whatever kernel the subclass builds; only
        per-round block re-poisoning (``_cohort_block``) must use
        ``run()`` (the resident block is packed once).
        """
        cfg = self.cfg
        if cfg.clients_per_round < cfg.num_clients:
            raise ValueError(
                "run_fused is the full-participation driver "
                f"(clients_per_round={cfg.clients_per_round} < "
                f"num_clients={cfg.num_clients}); use run()"
            )
        if getattr(type(self), "_cohort_block") is not getattr(
            FedAvgSimulation, "_cohort_block"
        ):
            raise ValueError(
                "run_fused cannot honor the _cohort_block override of "
                f"{type(self).__name__}; use run()"
            )
        rounds = rounds if rounds is not None else cfg.comm_rounds
        ids = np.arange(cfg.num_clients)
        x, y, mask, num_samples = self._cohort_block(ids, 0)
        participation = jnp.ones(len(ids), jnp.float32)
        slot_ids = jnp.arange(len(ids), dtype=jnp.int32)
        kernel = self._build_round_fn()
        fns: dict = {}

        from fedml_tpu.obs.jax_hooks import instrument_jit

        def fused(n):
            if n not in fns:
                fns[n] = instrument_jit(jax.jit(make_multi_round_fn(
                    None, n, drop_prob=cfg.drop_prob, round_fn=kernel,
                )), f"multi_round_fn[{n}]",
                    telemetry=self.metrics.telemetry)
            return fns[n]

        def run_chunk(base, n, chunk_ids):
            del base, chunk_ids
            self.state, stacked = fused(n)(
                self.state, x, y, mask, num_samples, participation,
                slot_ids,
            )
            return stacked

        return self._drive_chunks(
            rounds, rounds_per_call, run_chunk,
            ids_for_round=lambda r: ids, log_fn=log_fn,
        )

    def _drive_chunks(self, rounds, rounds_per_call, run_chunk,
                      *, ids_for_round, log_fn):
        """Shared chunking scaffold for the fused drivers: chunks end
        exactly on ``run()``'s eval rounds (r %% freq == 0, plus the
        final round) so the recorded history matches the dispatch loop
        row-for-row; ``rounds_per_call`` additionally caps a chunk
        (extra chunk boundaries without evals); 0/None = uncapped."""
        freq = self.cfg.frequency_of_the_test
        base0 = int(self.state.round_idx)
        eval_rounds = sorted(
            {r for r in range(base0, base0 + rounds) if r % freq == 0}
            | {base0 + rounds - 1}
        )
        done = 0
        while done < rounds:
            base = base0 + done
            next_eval = next(r for r in eval_rounds if r >= base)
            n = next_eval - base + 1
            if rounds_per_call:
                n = min(n, rounds_per_call)
            with self.metrics.span("sample"):
                chunk_ids = [ids_for_round(base + i) for i in range(n)]
            # run_chunk dispatches the fused program (its own span("pack")
            # covers host-side block building in the sampled driver); the
            # device work itself completes under the float() readbacks
            # below, so span("round") brackets those — one span per fused
            # chunk of n rounds, attached to the chunk's last row
            stacked = run_chunk(base, n, chunk_ids)
            with self.metrics.span("round"):
                rows = []
                for i in range(n):
                    out = {k: float(v[i]) for k, v in stacked.items()}
                    out["round"] = base + i
                    if out.get("count", 0) > 0:
                        out["train_acc"] = out["correct"] / out["count"]
                        out["train_loss"] = out["loss_sum"] / out["count"]
                    self._annotate_round(out, chunk_ids[i], base + i)
                    self._count_degraded(out)
                    rows.append(out)
            # fused drivers draw dropout ON DEVICE: the host can't see
            # the realized masks, so uploads use the expectation
            cohort = len(chunk_ids[0])
            self._record_sim_comm(
                cohort, rounds=n,
                uploads=int(round(cohort * n * (1.0 - self.cfg.drop_prob))),
            )
            if base + n - 1 in eval_rounds:
                with self.metrics.span("eval"):
                    rows[-1].update(self.evaluate_global())
                rows[-1].update(self._extra_eval())
                from fedml_tpu.obs.jax_hooks import record_device_memory

                record_device_memory(self.metrics.telemetry)
            # chunk-level spans ride the chunk's LAST row (one fused call
            # serves n rounds; per-round attribution does not exist here)
            rows[-1].update(self.metrics.pop_spans())
            self.history.extend(rows)
            for r in rows:
                self.metrics.log(r, step=r.get("round"))
                if log_fn:
                    log_fn(r)
            done += n
            # chunk boundaries are the fused drivers' checkpoint cadence
            # (mid-chunk state never exists on the host)
            self._maybe_checkpoint()
        self._maybe_checkpoint(final=True)
        return self.history

    def run_fused_sampled(
        self,
        rounds: Optional[int] = None,
        log_fn=None,
        rounds_per_call: int = 25,
    ) -> list:
        """Sampled-cohort (cross-device) driver on a fused fast path:
        the host pre-draws the next chunk's cohorts from the SAME
        ``host_sample_ids`` stream ``run()`` uses, packs them as one
        ``[R, K, ...]`` block, and ``make_scheduled_multi_round_fn``
        runs the whole chunk in one device call — removing the
        per-round host round-trip that dominates cross-device rounds
        (measured 6.6 s/round for mnist_lr through the axon tunnel;
        VERDICT r3 weak #7).  Bit-identical to ``run()``
        (``tests/test_fedavg.py::test_run_fused_sampled_matches_run``).

        Scope: every kernel family — BOTH subclass hooks are honored:
        ``_cohort_block`` overrides (the robust attacker's per-round
        poison swap) because blocks are built per round through the
        hook, and ``_build_round_fn`` overrides (FedNova) because the
        scheduled scan wraps whatever kernel the subclass builds
        (pinned by ``tests/test_algorithms.py::
        test_fednova_fused_drivers_match_run``).
        """
        cfg = self.cfg
        rounds = rounds if rounds is not None else cfg.comm_rounds
        # ONE jitted program serves every chunk length: the scheduled fn
        # scans the data's leading [R] axis, so jit specializes per
        # input shape on its own (unlike run_fused, where R is baked
        # into make_multi_round_fn's program)
        from fedml_tpu.obs.jax_hooks import instrument_jit

        fused = instrument_jit(jax.jit(make_scheduled_multi_round_fn(
            None, drop_prob=cfg.drop_prob, drop_seed=cfg.seed,
            round_fn=self._build_round_fn(),
        )), "scheduled_round_fn", telemetry=self.metrics.telemetry)

        def run_chunk(base, n, chunk_ids):
            with self.metrics.span("pack"):
                blocks = [self._cohort_block(ids, base + i)
                          for i, ids in enumerate(chunk_ids)]
                stacked_args = tuple(
                    jnp.stack([jnp.asarray(b[j]) for b in blocks])
                    for j in range(4)
                )
            part = jnp.ones((n, len(chunk_ids[0])), jnp.float32)
            sids = jnp.asarray(np.stack(chunk_ids), jnp.int32)
            self.state, stacked = fused(
                self.state, *stacked_args, part, sids
            )
            return stacked

        return self._drive_chunks(
            rounds, rounds_per_call, run_chunk,
            ids_for_round=self._sample_ids, log_fn=log_fn,
        )
