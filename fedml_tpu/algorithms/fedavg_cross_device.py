"""Cross-device FedAvg over the host message plane.

This is the TPU rebuild of the reference's distributed FedAvg
choreography (SURVEY.md §3.1): ``FedAvgServerManager`` /
``FedAvgClientManager`` exchanging INIT_CONFIG / SEND_MODEL /
SYNC_MODEL messages (``FedAvgServerManager.py:20-103``,
``FedAvgClientManager.py:18-75``) — for the loosely-coupled setting
where participants are NOT chips on one slice (the MQTT/mobile role).
Compute still runs through the same jit local-update operator; only
coordination travels as messages, over any ``CommBackend``
(inproc for simulation, TCP hub for real cross-process runs).

The tightly-coupled path (all clients on one TPU slice) should NOT use
this module — use the compiled SPMD round (``fedml_tpu.parallel.spmd``),
where "messages" are XLA collectives.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.comm.backend import CommBackend, NodeManager
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_LOCAL_METRICS,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_C2S_SEND_MODEL,
    MSG_TYPE_S2C_FINISH,
    MSG_TYPE_S2C_INIT_CONFIG,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
    tree_from_wire,
    tree_to_wire,
)
from fedml_tpu.core import tree as treelib
from fedml_tpu.core.client import LocalUpdateFn
from fedml_tpu.core.types import FedDataset, pack_clients
from fedml_tpu.obs.telemetry import get_telemetry

SERVER = 0


class FedAvgServerManager(NodeManager):
    """Rank-0 coordinator: sample → broadcast → collect → aggregate.

    Straggler tolerance (beyond the reference, whose server waits
    forever and whose only failure path is ``MPI.COMM_WORLD.Abort()``,
    ``server_manager.py:55-58``): with ``round_timeout`` set, a round
    that hasn't gathered its full cohort by the deadline aggregates
    with whoever arrived — the sample-weighted average is well-defined
    over any non-empty subset, exactly the compiled engine's
    participation-mask semantics (``core/sampling.py inject_dropout``) —
    and the dropouts are logged.  Replies carry their round index, so a
    straggler's late upload from a closed round is discarded instead of
    corrupting the next aggregation.
    """

    def __init__(
        self,
        backend: CommBackend,
        init_variables,
        *,
        num_clients: int,
        clients_per_round: int,
        comm_rounds: int,
        seed: int = 0,
        steps_per_epoch: Optional[int] = None,
        round_timeout: Optional[float] = None,
    ):
        import threading

        # cohort-wide pack geometry: shipped to clients so a client's
        # fixed-shape pack is IDENTICAL to its slice of the simulation's
        # cohort pack (heterogeneous sizes would otherwise change batch
        # counts and, with stateful optimizers, the trajectory)
        self.steps_per_epoch = steps_per_epoch
        self.variables = init_variables
        self.num_clients = num_clients
        self.clients_per_round = min(clients_per_round, num_clients)
        self.comm_rounds = comm_rounds
        self.seed = seed
        self.round_idx = 0
        self.pending: Dict[int, dict] = {}
        self.round_log = []
        self.round_timeout = round_timeout
        self.zero_participant_rounds = 0
        # _on_model runs on the backend reader thread, the deadline on a
        # Timer thread: one lock serializes round completion, and the
        # timer is generation-checked so a stale deadline (its round
        # completed normally) is a no-op
        self._round_lock = threading.Lock()
        self._deadline_timer: Optional[threading.Timer] = None
        super().__init__(backend)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL, self._on_model
        )

    # -- protocol --
    def start(self):
        wire = tree_to_wire(self.variables)  # encode once, fan out N times
        for node in self._sampled_nodes():
            self.send_message(
                self._model_msg(MSG_TYPE_S2C_INIT_CONFIG, node, node - 1, wire)
            )
        self._arm_deadline()

    def _arm_deadline(self):
        if self.round_timeout is None:
            return
        import threading

        t = threading.Timer(
            self.round_timeout, self._on_deadline, args=(self.round_idx,)
        )
        t.daemon = True
        self._deadline_timer = t
        t.start()

    def _on_deadline(self, round_gen: int):
        with self._round_lock:
            if round_gen != self.round_idx or self.round_idx >= self.comm_rounds:
                return  # stale timer: that round already closed
            if not self.pending:
                # nobody arrived: the global model is unchanged, the
                # round still closes (an all-dropped round under the
                # mask semantics is a no-op update)
                self._close_round(dropped_all=True)
                return
            self._close_round()

    def _sampled_nodes(self):
        """Seeded uniform sampling every round (the fork's hardcoded
        formula, FedAvgServerManager.py:66-75, is deliberately absent)."""
        if self.clients_per_round >= self.num_clients:
            ids = np.arange(self.num_clients)
        else:
            rng = np.random.RandomState(self.seed * 100003 + self.round_idx)
            ids = np.sort(
                rng.choice(self.num_clients, self.clients_per_round, replace=False)
            )
        return [int(i) + 1 for i in ids]  # node id = client id + 1

    def _model_msg(self, msg_type: str, node: int, slot: int, wire) -> Message:
        m = Message(msg_type, SERVER, node)
        m.add_params(MSG_ARG_KEY_MODEL_PARAMS, wire)
        m.add_params(MSG_ARG_KEY_CLIENT_INDEX, node - 1)
        m.add_params(MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        m.add_params("slot", slot)  # global client id → rng stream id (matches SPMD slot_ids)
        if self.steps_per_epoch is not None:
            m.add_params("steps_per_epoch", self.steps_per_epoch)
        return m

    def _on_model(self, msg: Message):
        with self._round_lock:
            # discard a straggler's upload from an already-closed round:
            # aggregating it into the CURRENT round would double-count
            # its stale parameters (missing round index = legacy client,
            # accepted as current)
            reply_round = msg.get(MSG_ARG_KEY_ROUND_INDEX)
            if reply_round is not None and reply_round != self.round_idx:
                self.round_log.append(
                    {"round": self.round_idx, "stale_from": msg.sender,
                     "stale_round": reply_round}
                )
                return
            self.pending[msg.sender] = {
                "variables": tree_from_wire(
                    msg.get(MSG_ARG_KEY_MODEL_PARAMS), self.variables
                ),
                "n": msg.get(MSG_ARG_KEY_NUM_SAMPLES),
                "metrics": msg.get(MSG_ARG_KEY_LOCAL_METRICS) or {},
            }
            if len(self.pending) < self.clients_per_round:
                return
            self._close_round()

    def _close_round(self, dropped_all: bool = False):
        """Aggregate whatever arrived and advance (caller holds the
        round lock).  Weighted average over any non-empty subset ==
        the compiled round's participation-mask aggregation."""
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        sampled = set(self._sampled_nodes())
        time_agg = 0.0
        if not dropped_all:
            # aggregate: sample-weighted average (FedAVGAggregator.py:58-87)
            t0 = time.perf_counter()
            entries = list(self.pending.values())
            total = sum(e["n"] for e in entries)
            self.variables = treelib.tree_weighted_sum(
                [e["variables"] for e in entries],
                [e["n"] / total for e in entries],
            )
            time_agg = time.perf_counter() - t0
            # same span series the simulation drivers feed (obs layer):
            # the reference's FedAVGAggregator.py:59,85-86 aggregate timer
            get_telemetry().observe("span.agg_s", time_agg)
        # wall-clock close stamp: deltas between consecutive recs are
        # the per-round wall time a federation artifact reports
        rec = {"round": self.round_idx, "participants": sorted(self.pending),
               "time_agg": round(time_agg, 6), "t": round(time.time(), 3)}
        dropped = sorted(sampled - set(self.pending))
        if dropped:
            rec["dropped"] = dropped  # deadline expired without them
        if not self.pending:
            # a zero-participant round is a silent no-op update; a run
            # where EVERY round is one (deadline shorter than client
            # train time — all uploads arrive a round late and are
            # stale-rejected) would otherwise "finish" with the init
            # model and rc=0.  Count them so callers can fail loudly.
            import logging

            self.zero_participant_rounds += 1
            logging.warning(
                "round %d closed with ZERO participants (deadline %.1fs; "
                "sampled %s) — global model unchanged this round",
                self.round_idx, self.round_timeout or -1.0, sorted(sampled),
            )
        self.round_log.append(rec)
        self.pending.clear()
        self.round_idx += 1
        if self.round_idx >= self.comm_rounds:
            for node in range(1, self.num_clients + 1):
                self.send_message(Message(MSG_TYPE_S2C_FINISH, SERVER, node))
            self.finish()
            return
        wire = tree_to_wire(self.variables)
        for node in self._sampled_nodes():
            self.send_message(
                self._model_msg(MSG_TYPE_S2C_SYNC_MODEL, node, node - 1, wire)
            )
        self._arm_deadline()


class FedAvgClientManager(NodeManager):
    """One federated participant: train on INIT/SYNC, upload, repeat."""

    def __init__(
        self,
        backend: CommBackend,
        local_update: LocalUpdateFn,
        dataset: FedDataset,
        *,
        batch_size: int,
        template_variables,
        seed: int = 0,
        train_delay: float = 0.0,
    ):
        self.local_update = jax.jit(local_update.fn)
        self.dataset = dataset
        self.batch_size = batch_size
        self.template = template_variables
        self.seed = seed
        self.rounds_trained = 0
        # artificial pre-training sleep: straggler injection for the
        # server's round-deadline path (tests/test_distributed_process)
        self.train_delay = train_delay
        super().__init__(backend)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_sync(self, msg: Message):
        if self.train_delay:
            import time

            time.sleep(self.train_delay)
        variables = tree_from_wire(msg.get(MSG_ARG_KEY_MODEL_PARAMS), self.template)
        client_idx = msg.get(MSG_ARG_KEY_CLIENT_INDEX)
        round_idx = msg.get(MSG_ARG_KEY_ROUND_INDEX)
        # round-independent pack seed, matching FedAvgSimulation's
        # device-resident cohort blocks: the pack base order carries no
        # stochasticity (the local update re-permutes per epoch from the
        # (key, round, slot) stream), and per-client pack seeding is
        # id-keyed, so this single-client pack is bit-identical to the
        # client's row in the simulation's cohort pack
        pack = pack_clients(
            self.dataset, [client_idx], self.batch_size,
            steps_per_epoch=msg.get("steps_per_epoch"),
            seed=self.seed,
        )
        # identical stream to the compiled round engine: key→round→train→slot
        slot = msg.get("slot", client_idx)
        k_round = jax.random.fold_in(jax.random.PRNGKey(self.seed), round_idx)
        rng = jax.random.fold_in(jax.random.fold_in(k_round, 0), slot)
        new_vars, metrics = self.local_update(
            variables,
            jnp.asarray(pack.x[0]), jnp.asarray(pack.y[0]),
            jnp.asarray(pack.mask[0]), rng,
        )
        self.rounds_trained += 1
        reply = Message(MSG_TYPE_C2S_SEND_MODEL, self.backend.node_id, SERVER)
        # echo the round: the server rejects uploads from closed rounds
        reply.add_params(MSG_ARG_KEY_ROUND_INDEX, round_idx)
        reply.add_params(MSG_ARG_KEY_MODEL_PARAMS, tree_to_wire(new_vars))
        reply.add_params(MSG_ARG_KEY_NUM_SAMPLES, float(pack.num_samples[0]))
        reply.add_params(
            MSG_ARG_KEY_LOCAL_METRICS, {k: float(v) for k, v in metrics.items()}
        )
        self.send_message(reply)

    def _on_finish(self, msg: Message):
        self.finish()
