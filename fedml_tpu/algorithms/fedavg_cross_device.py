"""Cross-device FedAvg over the host message plane.

This is the TPU rebuild of the reference's distributed FedAvg
choreography (SURVEY.md §3.1): ``FedAvgServerManager`` /
``FedAvgClientManager`` exchanging INIT_CONFIG / SEND_MODEL /
SYNC_MODEL messages (``FedAvgServerManager.py:20-103``,
``FedAvgClientManager.py:18-75``) — for the loosely-coupled setting
where participants are NOT chips on one slice (the MQTT/mobile role).
Compute still runs through the same jit local-update operator; only
coordination travels as messages, over any ``CommBackend``
(inproc for simulation, TCP hub for real cross-process runs).

The tightly-coupled path (all clients on one TPU slice) should NOT use
this module — use the compiled SPMD round (``fedml_tpu.parallel.spmd``),
where "messages" are XLA collectives.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.analysis.locks import assert_held, make_lock
from fedml_tpu.comm.backend import CommBackend, NodeManager
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_CONTRIBUTORS,
    MSG_ARG_KEY_DELTA_BASE,
    MSG_ARG_KEY_LOCAL_METRICS,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_C2S_RESYNC,
    MSG_TYPE_C2S_SEND_MODEL,
    MSG_TYPE_C2S_TELEMETRY,
    MSG_TYPE_E2S_PARTIAL,
    MSG_TYPE_S2C_FINISH,
    MSG_TYPE_S2C_INIT_CONFIG,
    MSG_TYPE_S2C_SYNC_MODEL,
    WIRETREE_KEY,
    Message,
    tree_from_wire,
    tree_is_delta,
    tree_to_wire,
)
from fedml_tpu.core import tree as treelib

# envelope key for codec negotiation: the server announces the uplink
# codec on S2C_INIT_CONFIG/S2C_SYNC_MODEL; clients echo it on their
# C2S_SEND_MODEL wiretree ("codec" + "delta" inside the wire pytree).
# Absent key = legacy fp32 full-model uploads — old peers interop.
MSG_ARG_KEY_CODEC = "codec"
from fedml_tpu.core.client import LocalUpdateFn
from fedml_tpu.core.staleness import STALENESS_POLICIES, staleness_weight
from fedml_tpu.core.types import FedDataset, pack_clients
from fedml_tpu.obs import flight
from fedml_tpu.obs.telemetry import get_telemetry

SERVER = 0


def encode_client_upload(codec_name: str, new_vars, synced_vars, template,
                         *, seed: int, round_idx: int, slot: int, ef=None):
    """Build one client's upload wiretree — THE shared encode for the
    single-process client manager and the muxer's vmapped cohort engine
    (``algorithms/fedavg_mux``), so the two paths cannot drift and
    muxed-vs-per-process uploads stay byte-identical by construction.

    No codec: the full-precision v2 wiretree.  With a codec: the
    codec-encoded DELTA (trained - synced), the error-feedback residual
    folded in and the new quantization error absorbed back into ``ef``.
    The encode key is the engine's exact compression stream —
    ``fold_in(fold_in(fold_in(seed_key, round), COMPRESS_STREAM),
    slot)`` — so encoded bytes are a pure function of
    (seed, round, slot): bit-identical across processes, muxers, and
    re-runs.

    Returns ``(wire, raw_nbytes, encoded_nbytes)`` — the byte pair is
    ``(None, None)`` on the uncompressed path (nothing was encoded).
    """
    from fedml_tpu.compress import COMPRESS_STREAM, get_codec

    codec = get_codec(codec_name)
    if codec is None:
        return tree_to_wire(new_vars), None, None
    delta = jax.tree_util.tree_map(
        lambda n, s: np.asarray(n, np.float32) - np.asarray(s, np.float32),
        new_vars, synced_vars,
    )
    if ef is not None:
        delta = ef.fold_in(delta)
    k_round = jax.random.fold_in(jax.random.PRNGKey(seed), round_idx)
    key = jax.random.fold_in(
        jax.random.fold_in(k_round, COMPRESS_STREAM), slot
    )
    wire = tree_to_wire(delta, codec=codec, key=key, delta=True)
    if ef is not None:
        ef.absorb(delta, tree_from_wire(wire, template))
    raw = sum(
        int(np.asarray(l).size) * 4
        for l in jax.tree_util.tree_leaves(delta)
    )
    comp = sum(
        int(np.asarray(v).nbytes)
        for leaf in wire["leaves"] for v in leaf["enc"].values()
    )
    return wire, raw, comp


def ef_for(store: dict, key, codec_name: str, enabled: bool):
    """One error-feedback residual per uplink stream (client / virtual
    client), created lazily and ONLY when a lossy codec is negotiated —
    the single gating rule both the per-process manager and the muxer
    use.  Gating drift between the two call sites would silently break
    muxed-vs-per-process byte-identity, so it lives here, next to the
    shared encode."""
    from fedml_tpu.compress import ErrorFeedback, get_codec

    if not enabled or get_codec(codec_name) is None:
        return None
    ef = store.get(key)
    if ef is None:
        ef = store[key] = ErrorFeedback()
    return ef


def apply_bcast_delta(base_tree, delta_tree):
    """ONE addition formula for the delta-broadcast chain, shared by
    the server (advancing its canonical model), the per-process client,
    and the muxer's cohort manager.  The chain's byte-identity claim —
    every receiver reconstructs EXACTLY the model the server holds,
    and a delta run's final model equals a full-broadcast run's at the
    same chain codec — rests on all ends computing ``base + delta``
    with the same ops in the same order, so the formula lives here,
    once: fp32 add, cast back to the base leaf's dtype."""
    return jax.tree_util.tree_map(
        lambda b, d: np.asarray(
            np.asarray(b, np.float32) + np.asarray(d, np.float32),
            np.asarray(b).dtype,
        ),
        base_tree, delta_tree,
    )


def encode_bcast_delta(codec_name: str, update_tree, *, seed: int,
                       round_idx: int) -> dict:
    """Encode one chain update as a delta-flagged wiretree.  ``qsgd8``
    (the default) is the int8 lever; ``none`` ships raw fp32 leaves
    (the lossless arm — same bytes as full, proves the protocol).  The
    encode key is the dedicated broadcast stream
    ``fold_in(fold_in(seed_key, round), BCAST_STREAM)`` — disjoint from
    every per-client upload stream, and a pure function of
    (seed, round): the encoded bytes are bit-identical across re-runs,
    which is what lets the server decode ITS OWN encoding and adopt the
    reconstruction as the canonical next model."""
    from fedml_tpu.compress import (
        BCAST_STREAM,
        get_codec,
        wire_encode_tree,
    )

    codec = get_codec(codec_name)
    if codec is None:
        return {
            WIRETREE_KEY: 2, "codec": "none", "delta": True,
            "leaves": [
                np.ascontiguousarray(np.asarray(l, np.float32))
                for l in jax.tree_util.tree_leaves(update_tree)
            ],
        }
    k_round = jax.random.fold_in(jax.random.PRNGKey(seed), round_idx)
    key = jax.random.fold_in(k_round, BCAST_STREAM)
    return {
        WIRETREE_KEY: 2, "codec": codec.name, "delta": True,
        "leaves": wire_encode_tree(codec, update_tree, key),
    }


def reconstruct_sync_model(msg: Message, template, bases,
                           window: int):
    """THE shared receive-side half of the delta broadcast — used by
    the per-process client AND the muxer's cohort manager, so the
    reconstruction/caching steps (like the addition formula above)
    cannot drift between topologies.

    Full frames decode directly; delta frames apply the shipped chain
    updates in order onto the cached base.  When the server announces
    delta mode (``delta_window`` param), the round's model is cached in
    ``bases`` (ordered round -> model, evicted beyond window+1) as
    OWNED arrays: the full-frame decode yields views into a transport
    buffer (an shm slab region is reclaimed when delivery ends), so
    that path copies; the delta path's ``apply_bcast_delta`` output is
    freshly allocated already and is cached as-is.

    Returns ``(variables, window)`` — ``variables`` is None when the
    delta's base is not cached (the caller requests a resync), and
    ``window`` echoes the announced cache depth (or the one passed
    in)."""
    base_round = msg.get(MSG_ARG_KEY_DELTA_BASE)
    round_idx = msg.get(MSG_ARG_KEY_ROUND_INDEX)
    payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
    if base_round is None:
        variables = tree_from_wire(payload, template)
        aliased = True  # np views into the frame's transport buffer
    else:
        base = bases.get(base_round)
        if base is None:
            return None, window
        variables = base
        for wire in payload or []:
            variables = apply_bcast_delta(
                variables, tree_from_wire(wire, template)
            )
        # fp32 add + cast allocated fresh arrays (an empty delta list
        # degenerates to the cached — already owned — base itself)
        aliased = False
    announced = msg.get("delta_window")
    if announced is not None:
        window = max(int(announced), 1)
        if aliased:
            variables = jax.tree_util.tree_map(
                lambda l: np.array(l, copy=True), variables
            )
        bases[round_idx] = variables
        while len(bases) > window + 1:
            bases.popitem(last=False)
    return variables, window


def request_resync(send, node_id: int, round_idx) -> None:
    """THE shared resync request (the send-side twin of
    ``reconstruct_sync_model``'s None return): one C2S_RESYNC frame
    echoing the round whose delta base was missing.  Per-process
    clients and the muxer's cohort walkback both build the request
    HERE, so a protocol change (an extra param, a renamed key) cannot
    desynchronize the two topologies' recovery paths."""
    resync = Message(MSG_TYPE_C2S_RESYNC, node_id, SERVER)
    resync.add_params(MSG_ARG_KEY_ROUND_INDEX, round_idx)
    send(resync)


class UploadRejected(Exception):
    """A client upload failed the decode or the non-finite firewall.
    ``kind`` is the canonical ``faults.observed{kind=}`` label."""

    def __init__(self, kind: str):
        super().__init__(kind)
        self.kind = kind


def decode_validated_upload(msg: Message, base):
    """THE shared upload intake — the root server's decode path AND the
    edge hub's (``algorithms/edge_hub``), so the two tiers cannot drift:
    an upload the root would have rejected must never survive by hiding
    inside an edge partial, and vice versa.

    Decodes the wire payload against ``base`` (applying delta-upload
    semantics), then runs the corrupt-payload firewall: one NaN leaf
    folded into the weighted sum would poison the global model for
    every round after, at either tier.

    Returns ``(variables, n)``; raises :class:`UploadRejected` with the
    canonical fault kind on any bad upload."""
    try:
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        variables = tree_from_wire(payload, base)
        if tree_is_delta(payload):
            # codec-encoded UPDATE: decoded leaves are fp32 deltas; the
            # upload's model is base + delta (what the client's
            # error-feedback recurrence assumes the aggregator sees)
            variables = jax.tree_util.tree_map(
                lambda b, d: np.asarray(b, np.float32) + d,
                base, variables,
            )
    except Exception as e:
        # an undecodable payload (truncated/garbled frame) is a fault
        # observation, not an aggregator crash
        raise UploadRejected("undecodable_upload") from e
    n = msg.get(MSG_ARG_KEY_NUM_SAMPLES)
    if n is None or not np.isfinite(n) or n <= 0 or not all(
        np.isfinite(np.asarray(l)).all()
        for l in jax.tree_util.tree_leaves(variables)
    ):
        raise UploadRejected("corrupt_upload")
    return variables, float(n)


def bcast_wire_nbytes(wire: dict) -> int:
    """Encoded payload bytes of one broadcast wire (codec enc arrays,
    or raw leaves for the ``none`` arm) — what
    ``comm.delta_bcast_bytes`` counts."""
    total = 0
    for leaf in wire.get("leaves") or []:
        if isinstance(leaf, dict) and "enc" in leaf:
            total += sum(int(np.asarray(v).nbytes)
                         for v in leaf["enc"].values())
        else:
            total += int(np.asarray(leaf).nbytes)
    return total


class FedAvgServerManager(NodeManager):
    """Rank-0 coordinator: sample → broadcast → collect → aggregate.

    Straggler tolerance (beyond the reference, whose server waits
    forever and whose only failure path is ``MPI.COMM_WORLD.Abort()``,
    ``server_manager.py:55-58``): with ``round_timeout`` set, a round
    that hasn't gathered its full cohort by the deadline aggregates
    with whoever arrived — the sample-weighted average is well-defined
    over any non-empty subset, exactly the compiled engine's
    participation-mask semantics (``core/sampling.py inject_dropout``) —
    and the dropouts are logged.  Replies carry their round index, so a
    straggler's late upload from a closed round is discarded instead of
    corrupting the next aggregation.

    Threading: ``_on_model`` runs on the backend reader thread, the
    deadline on a Timer thread, ``start`` on the caller's — the round
    state they share is declared in ``_GUARDED_BY`` below and enforced
    by the fedlint lock-discipline rule (methods the callers enter with
    the lock already held carry ``# fedlint: holds=_round_lock`` and
    verify it at runtime via ``locks.assert_held``).

    Fault tolerance on top of that (the chaos-layer contract,
    ``fedml_tpu/faults``):

    - ``spares`` over-samples the cohort: ``clients_per_round + spares``
      nodes get the sync and the round closes at the FIRST
      ``clients_per_round`` uploads (or at the deadline with whoever
      arrived).  The sample-weighted average renormalizes over the
      realized reporters, so no weight correction is needed — spares
      that report after the close are stale-rejected like any late
      frame.
    - uploads carrying non-finite parameters or weights (a corrupted
      payload) are rejected BEFORE aggregation and counted
      (``faults.observed{kind=corrupt_upload}``) — one flipped frame
      must not NaN-poison the global model.
    - degraded rounds (closed by deadline missing reporters, or with
      nobody at all) increment the same ``rounds.degraded`` counter the
      simulation drivers use — ONE series for both transports and both
      execution modes (the unified deadline semantics).

    These semantics are transport-independent: the deadline timer,
    stale rejection, and degraded accounting behave identically over
    the inproc bus and the TCP hub (pinned by ``tests/test_comm.py``
    and ``tests/test_faults.py``).
    """

    # reader-thread / timer-thread shared round state.  round_idx and
    # variables are deliberately NOT listed: both are written only
    # under the lock, but read on pre-thread paths (start) and in
    # lock-held helper chains the lexical checker cannot follow — the
    # holds= contracts on the mutating methods cover them.
    _GUARDED_BY = {
        "pending": "_round_lock",
        "_acked": "_ack_lock",
        "_delta_log": "_ack_lock",
        "_model_log": "_round_lock",
        "_agg_acc": "_round_lock",
        "_agg_n": "_round_lock",
        "_conn_acc": "_round_lock",
        "_conn_n": "_round_lock",
        "round_log": "_round_lock",
        "rejected_uploads": "_round_lock",
        "zero_participant_rounds": "_round_lock",
        "_last_decode_wait_s": "_round_lock",
        "_last_decode_s": "_round_lock",
        "_bcast_task_s": "_round_lock",
        "_bytes_mark": "_round_lock",
    }

    def __init__(
        self,
        backend: CommBackend,
        init_variables,
        *,
        num_clients: int,
        clients_per_round: int,
        comm_rounds: int,
        seed: int = 0,
        steps_per_epoch: Optional[int] = None,
        round_timeout: Optional[float] = None,
        spares: int = 0,
        codec: str = "none",
        multicast: bool = True,
        streaming_agg: bool = True,
        decode_workers: int = 0,
        stats_plane: bool = True,
        slo_spec=None,
        status_dir: Optional[str] = None,
        stats_interval: float = 1.0,
        defense=None,
        bcast: str = "full",
        bcast_codec: str = "",
        delta_base_window: int = 4,
        round_mode: str = "sync",
        cut_size: int = 0,
        max_staleness: int = 2,
        stale_policy: str = "poly",
        stale_alpha: float = 0.5,
    ):
        from fedml_tpu.compress import get_codec

        # uplink compression negotiation: broadcast messages carry the
        # codec name; clients encode their update delta with it and the
        # server decodes before the exact fp32 weighted average (the
        # renormalization over realized reporters is unchanged — decode
        # happens BEFORE aggregation, so the average itself stays exact)
        self.codec_name = codec or "none"
        self._codec = get_codec(self.codec_name)

        # cohort-wide pack geometry: shipped to clients so a client's
        # fixed-shape pack is IDENTICAL to its slice of the simulation's
        # cohort pack (heterogeneous sizes would otherwise change batch
        # counts and, with stateful optimizers, the trajectory)
        self.steps_per_epoch = steps_per_epoch
        self.variables = init_variables
        self.num_clients = num_clients
        self.clients_per_round = min(clients_per_round, num_clients)
        # over-sampling: broadcast to K + spares nodes, aggregate the
        # first K reporters (or whoever beat the deadline) — the
        # FL-at-scale system design's straggler hedge
        self.spares = max(0, int(spares))
        self.broadcast_size = min(
            self.clients_per_round + self.spares, num_clients
        )
        self.comm_rounds = comm_rounds
        self.seed = seed
        self.round_idx = 0
        # wire hot-path knobs (both default ON; the legacy settings are
        # the measurement baseline arm and the old-peer compat mode):
        # - multicast: ONE shared sync envelope per round fanned out by
        #   the transport (hub mcast frame on tcp, per-receiver clones
        #   elsewhere) instead of K per-node re-encoded unicasts;
        # - streaming_agg: uploads fold into a running (sum n·model,
        #   sum n) accumulator on arrival — pending holds metadata
        #   only, peak memory O(model) instead of O(K·model), and the
        #   close-time aggregation stall collapses to one normalize.
        self.multicast = bool(multicast)
        self.streaming_agg = bool(streaming_agg)
        # robust aggregation (fedml_tpu/robust): per-upload screening
        # (norm clip / outlier reject / client-level DP) on the decode
        # path, per-CONNECTION contribution caps on the streaming fold,
        # and the buffered median/trimmed-mean close.  ``defense`` is a
        # DefenseConfig (or its dict form, or None = undefended — the
        # exact pre-defense code path, byte-identical).
        from fedml_tpu.robust import DefenseConfig, RobustAggregator

        if isinstance(defense, dict):
            defense = DefenseConfig(**defense)
        self.defense = defense if (defense is not None
                                   and defense.enabled) else None
        self._robust = (RobustAggregator(self.defense, seed=seed)
                        if self.defense is not None else None)
        # buffered estimators need ALL K uploads at close — they buffer
        # decoded trees like the legacy path even when streaming_agg is
        # on (memory O(K·model) is inherent to a coordinate-wise
        # estimator; the streaming defenses exist for the O(1) fold)
        self._defense_buffered = (self.defense is not None
                                  and self.defense.buffered)
        self._conn_cap = (self.defense.conn_cap
                          if self.defense is not None else 0.0)
        if self._conn_cap > 0 and not self.streaming_agg:
            # the cap is enforced by the streaming fold's per-conn
            # accumulators; on the legacy buffered path it would be
            # silently inert — refuse, don't run undefended
            raise ValueError(
                "conn_cap requires the streaming hot path "
                "(streaming_agg=True / --hotpath fast)"
            )
        # async buffered rounds (``--round-mode async``): FedBuff-style
        # fold-on-arrival — the round is CUT at every ``cut_size``
        # arrivals (or the cut deadline) instead of barrier-closed, and
        # an upload computed against base round b < r folds in at
        # weight w(r-b)·n (``core/staleness``) instead of being
        # stale-rejected.  ``max_staleness`` keeps the reject firewall
        # as the hard outer bound.  Requires the streaming fold: the
        # O(1) accumulator is WHAT arrivals fold into mid-round — the
        # legacy buffered path and buffered robust estimators have no
        # partial-round state to discount into.
        if round_mode not in ("sync", "async"):
            raise ValueError(
                f"unknown round_mode {round_mode!r} (sync|async)"
            )
        self.round_mode = round_mode
        self._async = round_mode == "async"
        if self._async and (not self.streaming_agg
                            or self._defense_buffered):
            raise ValueError(
                "round_mode='async' requires the streaming fold "
                "(streaming_agg=True and a streaming-composable "
                "defense) — buffered closes cannot discount a "
                "partial-round accumulator"
            )
        if stale_policy not in STALENESS_POLICIES:
            raise ValueError(
                f"unknown stale_policy {stale_policy!r} "
                f"(one of {STALENESS_POLICIES})"
            )
        self.max_staleness = max(0, int(max_staleness))
        self.stale_policy = stale_policy
        self.stale_alpha = float(stale_alpha)
        # cut target: K arrivals per round cut (0 = the sync cohort
        # size).  Capped at the broadcast size — a larger cut could
        # never fill before the deadline.
        if self._async and cut_size > 0:
            self._cut_target = min(int(cut_size), self.broadcast_size)
        else:
            self._cut_target = self.clients_per_round
        # delta/dedup broadcast (``--bcast delta``): consecutive rounds'
        # models differ by exactly one aggregated update, so the sync
        # ships the int8-encoded UPDATE against each connection's
        # last-acked round instead of the full model.  The server
        # maintains the broadcast model as a QUANTIZED CHAIN: at every
        # close it encodes the aggregate update with ``bcast_codec``
        # (default qsgd8 in delta mode), decodes its own encoding, and
        # adopts base + decode as the canonical next model — the
        # quantization error rides an EF residual into the next round's
        # update (PR-4 recurrence, downlink edition).  Every receiver
        # applying the same decoded deltas in the same order holds the
        # server's model BIT-FOR-BIT, and a ``--bcast full`` run at the
        # same chain codec broadcasts the identical model sequence —
        # the delta-vs-full byte-identity pin.  ``--bcast full`` with
        # no explicit chain codec is the exact legacy path.
        if bcast not in ("full", "delta"):
            raise ValueError(f"unknown bcast mode {bcast!r} (full|delta)")
        if bcast == "delta" and not self.multicast:
            # delta envelopes are shared multicast frames (receivers
            # derive identity from their node id); the legacy per-node
            # unicast arm has no delta form — refuse, don't run full
            raise ValueError(
                "bcast='delta' requires the multicast hot path "
                "(--hotpath fast)"
            )
        self.bcast = bcast
        self.bcast_codec_name = (
            bcast_codec or ("qsgd8" if bcast == "delta" else "none")
        )
        # chain quantization is a property of the CLOSE, independent of
        # the wire form: on whenever delta mode is on, or when a chain
        # codec was explicitly requested for a full-broadcast arm (the
        # digest-pin comparison arm)
        self._chain = (bcast == "delta"
                       or self.bcast_codec_name not in ("", "none"))
        self.delta_base_window = max(1, int(delta_base_window))
        self._chain_resid = None  # downlink EF residual (fp32 tree)
        # node id -> last round whose sync the node provably received
        # (it echoed the round index on an upload); the delta log maps
        # round r -> the encoded update taking M_{r-1} to M_r, bounded
        # to the last ``delta_base_window`` rounds
        from collections import OrderedDict

        self._acked: Dict[int, int] = {}
        self._delta_log: "OrderedDict[int, dict]" = OrderedDict()
        # async mode's base-model log: round r -> the model round r
        # BROADCAST, bounded to the staleness window — a stale upload
        # decodes (and robust-screens) against the base its client
        # actually trained from, not the current model.  Round 0's
        # base is the init.
        self._model_log: "OrderedDict[int, object]" = OrderedDict()
        if self._async:
            self._model_log[0] = init_variables
        # ((round_idx, id(variables)), wire): the current model encoded
        # at most once per round however many full sends need it
        self._full_wire_cache = None
        # reader threads update acks while the (possibly off-thread)
        # broadcast groups by them: leaf lock, ordered round_lock ->
        # _ack_lock at every site that holds both
        self._ack_lock = make_lock("FedAvgServerManager._ack_lock")
        # deferred chain advance (encode thread): set while no advance
        # is pending; cleared at a close that hands the advance to
        # _broadcast_async, re-set there.  Off-thread readers of the
        # post-advance model (_on_resync's _full_wire) wait on it
        # OUTSIDE the round lock.
        self._chain_done = threading.Event()
        self._chain_done.set()
        self._agg_acc = None
        self._agg_n = 0.0
        # edge-partial decode template: tree_from_wire casts decoded
        # leaves to the template's dtype, and a partial's num tree IS
        # the edge's fp64 streaming accumulator — decoding against the
        # fp32 model would downcast mid-wire and break the tree-vs-flat
        # byte-identity pin
        self._f64_template = jax.tree_util.tree_map(
            lambda l: np.asarray(l, np.float64), init_variables
        )
        # per-connection num/den accumulators (conn caps only):
        # O(connections · model) — connections, not clients, is the
        # muxed federation's small axis
        self._conn_acc: Dict[str, object] = {}
        self._conn_n: Dict[str, float] = {}
        self.pending: Dict[int, dict] = {}
        self.round_log = []
        self.round_timeout = round_timeout
        self.zero_participant_rounds = 0
        self.rejected_uploads = 0  # non-finite (corrupt) models/weights
        self._round_open_t = time.perf_counter()
        # _on_model runs on the backend reader thread, the deadline on a
        # Timer thread: one lock serializes round completion, and the
        # timer is generation-checked so a stale deadline (its round
        # completed normally) is a no-op
        self._round_lock = make_lock("FedAvgServerManager._round_lock")
        self._deadline_timer: Optional[threading.Timer] = None
        # decode/fold pipeline (``decode_workers > 0``): upload decode +
        # the finite firewall move OFF the backend reader thread onto a
        # small worker pool feeding the streaming fold, so decode of
        # client i's upload overlaps the wire receive of client i+1 —
        # and the next broadcast's encode+send runs on its own thread
        # (double-buffered) so stale/spare uploads never queue behind an
        # O(model) serialize.  0 (default) = the synchronous path: the
        # inproc bus's deterministic drain contract requires handlers to
        # complete inline, so only the real TCP entry points turn this
        # on (``distributed_fedavg --decode-workers``).
        self.decode_workers = max(0, int(decode_workers))
        if self.decode_workers:
            from concurrent.futures import ThreadPoolExecutor

            self._decode_pool = ThreadPoolExecutor(
                max_workers=self.decode_workers,
                thread_name_prefix="fed-decode",
            )
            self._encode_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="fed-bcast"
            )
        else:
            self._decode_pool = None
            self._encode_pool = None
        # per-round pipeline evidence, surfaced on the round_close
        # event/record: the CLOSING upload's decode queue wait + decode
        # time, and the previous broadcast's off-thread encode+send span
        self._last_decode_wait_s = 0.0
        self._last_decode_s = 0.0
        self._bcast_task_s = 0.0
        # in-band stats plane (obs/digest + obs/slo): the server is the
        # rollup point — clients/muxers ship one digest frame per report
        # interval per CONNECTION, the rollup merges them (associative
        # digest algebra), and the SLO engine evaluates the declared
        # objectives at every round close.  ``status_dir`` additionally
        # turns on the live ``status.json`` snapshot (atomic write each
        # interval + each close) and the final ``slo_report.json`` — a
        # killed or wedged run leaves evidence mid-flight.
        self.stats_plane = bool(stats_plane)
        self.status_dir = status_dir
        self.stats_interval = max(0.1, float(stats_interval))
        self._stats_done = threading.Event()
        self._status_thread: Optional[threading.Thread] = None
        self._bytes_mark = 0.0
        if self.stats_plane:
            from fedml_tpu.obs.digest import DigestRollup, DigestSource
            from fedml_tpu.obs.slo import SloEngine, SloSpec

            if isinstance(slo_spec, dict):
                slo_spec = SloSpec.from_obj(slo_spec)
            slo_spec = slo_spec or SloSpec()
            if slo_spec.stale_after_s is None:
                # unset staleness threshold: scale it from the report
                # interval (5 missed heartbeats), floored at the module
                # default — a 30 s interval must not flag every live
                # stream stale between frames
                import dataclasses

                from fedml_tpu.obs.digest import DEFAULT_STALE_AFTER_S

                slo_spec = dataclasses.replace(
                    slo_spec,
                    stale_after_s=max(DEFAULT_STALE_AFTER_S,
                                      5.0 * max(0.1, float(stats_interval))),
                )
            self.slo = SloEngine(slo_spec)
            self.rollup = DigestRollup()
            # the server's own registry is just another digest source —
            # folded locally (no frame to itself) so the merged rollup
            # covers the whole federation including rank 0
            self._local_digests = DigestSource(SERVER)
            # serializes next()+ingest as ONE step: the status thread
            # and a round-close fold interleaving between the two calls
            # would hand the rollup a newer seq first and the older
            # delta (possibly the closing round's wall sample) would be
            # skipped as a duplicate.  Leaf lock: nothing is acquired
            # inside the guarded region except the source's and
            # rollup's own internal locks.
            self._local_fold_lock = make_lock(
                "FedAvgServerManager._local_fold_lock")
        else:
            self.slo = self.rollup = self._local_digests = None
        super().__init__(backend)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL, self._on_model
        )
        # hierarchical aggregation: an edge hub's pre-folded
        # (sum n·model, sum n) pair for the current round
        self.register_message_receive_handler(
            MSG_TYPE_E2S_PARTIAL, self._on_partial
        )
        # registered even with the stats plane OFF: a half-configured
        # federation (clients reporting, server arm disabled) must drop
        # digest frames quietly, not spam unhandled-frame warnings
        self.register_message_receive_handler(
            MSG_TYPE_C2S_TELEMETRY, self._on_telemetry
        )
        # delta-broadcast recovery: a rejoining client asking for a
        # full-model resend (registered unconditionally for the same
        # quiet-interop reason)
        self.register_message_receive_handler(
            MSG_TYPE_C2S_RESYNC, self._on_resync
        )

    # -- stats plane --------------------------------------------------------
    def _on_telemetry(self, msg: Message) -> None:
        """One digest frame off the wire → the rollup.  ``ingest``
        validates + counts and never raises, so a corrupted digest can
        cost at most its own frame — never a reader thread, never a
        round."""
        if self.rollup is None:
            return
        from fedml_tpu.obs.digest import DIGEST_KEY

        self.rollup.ingest(msg.get(DIGEST_KEY))

    def _expected_nodes(self):
        return list(range(1, self.num_clients + 1))

    def _comm_bytes_total(self) -> float:
        snap = get_telemetry().snapshot()["counters"]
        return sum(v for k, v in snap.items()
                   if k.startswith(("comm.sent_bytes", "comm.recv_bytes")))

    def _fold_local_digest(self) -> None:
        if self.rollup is not None:
            with self._local_fold_lock:
                self.rollup.ingest(self._local_digests.next())

    def _write_status(self, finished: bool = False) -> None:
        if self.rollup is None or not self.status_dir:
            return
        import os

        from fedml_tpu.obs import slo as slolib

        try:
            os.makedirs(self.status_dir, exist_ok=True)
            status = slolib.build_status(
                self.slo, self.rollup, round_idx=self.round_idx,
                rounds_total=self.comm_rounds,
                expected_nodes=self._expected_nodes(), finished=finished,
            )
            slolib.write_json_atomic(
                os.path.join(self.status_dir, "status.json"), status
            )
        except OSError:
            logging.exception("status.json write failed")

    def _status_loop(self) -> None:
        while not self._stats_done.wait(self.stats_interval):
            try:
                self._fold_local_digest()
                self._write_status()
            except Exception:
                # the health plane is best-effort: a snapshot bug must
                # not silently kill the only thread writing status.json
                logging.exception("status snapshot failed")

    def _slo_close(self, t_close: float) -> None:  # fedlint: holds=_round_lock
        """Round-close half of the stats plane (caller holds the round
        lock): feed the SLO histograms, fold the local registry delta
        into the rollup so the evaluation sees CURRENT numbers, then
        evaluate every declared objective."""
        wall = max(0.0, t_close - self._round_open_t)
        total_bytes = self._comm_bytes_total()
        round_bytes = max(0.0, total_bytes - self._bytes_mark)
        self._bytes_mark = total_bytes
        self.slo.observe_round(
            self.round_idx, wall_s=wall, round_bytes=round_bytes,
            participants=len(self.pending), target=self._cut_target,
        )
        self._fold_local_digest()
        self.slo.evaluate(
            self.round_idx, self.rollup.snapshot(),
            self.rollup.sources(stale_after=self.slo.spec.stale_after_s),
            expected_nodes=self._expected_nodes(),
        )
        self._write_status()

    def _stats_finish(self) -> None:
        """Final fold + ``slo_report.json`` + terminal ``status.json``
        (idempotent — finish can be reached from the last close AND the
        fail-fast broadcast path)."""
        if self.rollup is None or self._stats_done.is_set():
            return
        self._stats_done.set()
        if self._status_thread is not None:
            self._status_thread.join(timeout=5)
        self._fold_local_digest()
        if self.status_dir:
            import os

            from fedml_tpu.obs import slo as slolib

            try:
                report = self.slo.report(
                    self.rollup.snapshot(),
                    self.rollup.sources(
                        stale_after=self.slo.spec.stale_after_s),
                    expected_nodes=self._expected_nodes(),
                    extra={"rounds_completed": self.round_idx,
                           "clients": self.num_clients,
                           "clients_per_round": self.clients_per_round},
                )
                slolib.write_json_atomic(
                    os.path.join(self.status_dir, "slo_report.json"), report
                )
            except Exception:
                # finish() must complete whatever the health plane's
                # state — a report bug losing the FINISH broadcast
                # would be worse than a missing report
                logging.exception("slo_report.json write failed")
            self._write_status(finished=True)

    def stats_summary(self) -> dict:
        """Machine-readable stats-plane outcome for the entry point's
        stdout JSON (what campaigns assert streams == connections on)."""
        if self.rollup is None:
            return {"enabled": False}
        snap = self.rollup.stats()
        sources = self.rollup.sources(
            stale_after=self.slo.spec.stale_after_s)
        _, missing = self.slo.coverage(
            self.rollup.snapshot(), sources, self._expected_nodes())
        violations_total, _ = self.slo.violation_state()
        return {
            "enabled": True,
            "streams": snap["streams"],
            # hub-ingested streams: every source EXCEPT the server's own
            # locally-folded registry — under muxing this equals the
            # number of client-side CONNECTIONS, never the client count
            # (the stats plane's O(connections) cost-model assertion)
            "streams_remote": snap["streams"]
            - (1 if str(SERVER) in sources else 0),
            "frames": snap["frames"],
            "rejected": snap["rejected"],
            "duplicates": snap["duplicates"],
            "stale_streams": sorted(
                s for s, st in sources.items() if st.get("stale")),
            "missing_nodes_total": len(missing),
            "slo_violations": violations_total,
            "slo_ok": violations_total == 0,
        }

    # -- protocol --
    def start(self):
        if self._conn_cap > 0:
            # arm connection attribution BEFORE the first broadcast
            # (synchronous pre-run fetch): without it, round 0's
            # uploads would race the async conn_map reply and a fast
            # cohort could fold as unmapped singletons — exactly the
            # window a malicious muxer wants.  Raises on a hub that
            # cannot answer: a cap the operator configured must never
            # silently degrade to uncapped.
            fetch = getattr(self.backend, "fetch_conn_map", None)
            if fetch is not None:
                self._robust.set_conn_map(fetch())
        self._round_open_t = time.perf_counter()
        if self.rollup is not None:
            with self._round_lock:
                self._bytes_mark = self._comm_bytes_total()
            if self.status_dir and self._status_thread is None:
                self._status_thread = threading.Thread(
                    target=self._status_loop, daemon=True,
                    name="fed-status",
                )
                self._status_thread.start()
        self._broadcast_model(MSG_TYPE_S2C_INIT_CONFIG)
        self._arm_deadline()

    def _broadcast_model(self, msg_type: str) -> None:
        """Ship this round's model to the sampled cohort.

        Multicast (default): ONE shared envelope — per-node identity
        (client_idx/slot) is derived by each receiver from its node id,
        so the frame bytes are identical for every node and the
        transport serializes them exactly once (``send_multicast``;
        native hub fan-out on tcp wire>=2, per-receiver clones of the
        same payload objects elsewhere).  A transport error after the
        backend's own bounded retries makes the WHOLE cohort stragglers
        for this round — the deadline covers it, same contract as the
        per-node ``_send_or_log``.

        Legacy (``multicast=False``): the per-node unicast loop with
        explicit client_idx/slot params — the measurement baseline and
        the interop mode for pre-multicast peers that cannot derive
        identity from their node id.
        """
        nodes = self._sampled_nodes()
        if self._conn_cap > 0:
            # refresh connection attribution once per round (async
            # reply; uploads take a client-train time to come back, so
            # the map is current by the first fold).  Best-effort —
            # unattributed nodes degrade to singleton connections.
            req = getattr(self.backend, "request_conn_map", None)
            if req is not None:
                req()
        if self.bcast == "delta":
            self._broadcast_delta(msg_type, nodes)
            return
        wire = self._full_wire()  # encode once per (round, model)
        if not self.multicast:
            for node in nodes:
                self._send_or_log(
                    self._model_msg(msg_type, node, node - 1, wire)
                )
            return
        msg = self._model_msg(msg_type, None, None, wire)
        try:
            self.backend.send_multicast(msg, nodes)
        except OSError:
            if self.round_timeout is None:
                raise  # no deadline to cover the lost round: fail fast
            get_telemetry().inc("comm.send_failed", msg_type=msg_type)
            logging.warning(
                "round %d: could not deliver %s multicast to %s (will "
                "rely on the round deadline)", self.round_idx, msg_type,
                nodes,
            )

    def _broadcast_delta(self, msg_type: str, nodes) -> None:
        """Delta-mode fan-out: group the cohort by last-acked round and
        ship each group the encoded chain updates it is missing (acked
        b ⇒ deltas b+1..r, applied in order — the telescoped sum IS the
        current model, bit-for-bit).  Nodes with no ack, or whose base
        aged past the window, get the full model — counted, the
        rejoin/behind fallback the protocol promises."""
        r = self.round_idx
        with self._ack_lock:
            acked = dict(self._acked)
            log = dict(self._delta_log)
        tel = get_telemetry()
        groups: Dict[int, list] = {}
        full_nodes: list = []
        for node in nodes:
            b = acked.get(node)
            if b is None or b >= r:
                # no ack yet (cold start / post-resync) — or an ack at
                # or past this round (an empty-delta no-op we ship as
                # full for simplicity; only reachable in teardown races)
                if b is None:
                    tel.inc("comm.delta_full_fallbacks", reason="no_ack")
                full_nodes.append(node)
            elif all(k in log for k in range(b + 1, r + 1)):
                groups.setdefault(b, []).append(node)
            else:
                # the base aged out of the bounded delta log: stale-base
                # eviction forces a full resend
                tel.inc("comm.delta_full_fallbacks", reason="window")
                full_nodes.append(node)
        for b in sorted(groups):
            wires = [log[k] for k in range(b + 1, r + 1)]
            # the shared multicast sync envelope (_model_msg) with the
            # params swapped for the chain wires + their base round —
            # one envelope builder, delta and full frames cannot drift
            msg = self._model_msg(msg_type, None, None, wires)
            msg.add_params(MSG_ARG_KEY_DELTA_BASE, b)
            tel.inc("comm.delta_bcast_bytes",
                    sum(bcast_wire_nbytes(w) for w in wires))
            self._mcast_or_log(msg, groups[b], msg_type)
        if full_nodes:
            msg = self._model_msg(msg_type, None, None, self._full_wire())
            self._mcast_or_log(msg, full_nodes, msg_type)

    def _mcast_or_log(self, msg: Message, receivers, msg_type: str) -> None:
        """One multicast with the broadcast path's failure contract: a
        transport error after the backend's bounded retries makes these
        receivers deadline stragglers (or fails fast with no deadline)."""
        try:
            self.backend.send_multicast(msg, receivers)
        except OSError:
            if self.round_timeout is None:
                raise
            get_telemetry().inc("comm.send_failed", msg_type=msg_type)
            logging.warning(
                "round %d: could not deliver %s multicast to %s (will "
                "rely on the round deadline)", self.round_idx, msg_type,
                receivers,
            )

    def _full_wire(self):
        """The current model's wire form, encoded at most once per
        (round, model) pair — shared by the full-broadcast path, the
        delta mode's full-fallback group, and every resync unicast in
        the round (a rejoined muxer's whole cohort resyncs at once;
        without the memo that is O(cohort x model) encode work under
        the round lock).  Callable with OR without ``_round_lock``
        (the encode thread broadcasts unlocked): the key re-derives
        per call and the cache swap is one atomic tuple assignment, so
        a racing caller at worst duplicates one encode — never serves
        a stale wire for a different (round, model)."""
        key = (self.round_idx, id(self.variables))
        cached = self._full_wire_cache
        if cached is None or cached[0] != key:
            cached = (key, tree_to_wire(self.variables))
            self._full_wire_cache = cached
        return cached[1]

    def _advance_chain(self, prev_model,  # fedlint: holds=_round_lock
                       next_round: Optional[int] = None) -> None:
        """Close-time half of the delta broadcast (caller holds the
        round lock): U = aggregate − M_r + residual, encoded on the
        seeded broadcast stream; M_{r+1} := M_r + decode(encode(U));
        residual := U − decode(encode(U)).  The encoded wire lands in
        the bounded delta log under round r+1 (the sync that ships it
        first).  ``next_round`` must be passed EXPLICITLY when the call
        is deferred past the close's ``round_idx`` increment (the
        encode-thread path): deriving it from ``self.round_idx`` there
        would land the wire one slot too high AND shift the encode
        seed — the server's chain state then silently skews from every
        receiver's."""
        assert_held(self._round_lock, "FedAvgServerManager._advance_chain")
        if next_round is None:
            next_round = self.round_idx + 1
        raw = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a, np.float32) - np.asarray(b, np.float32),
            self.variables, prev_model,
        )
        if self._chain_resid is not None:
            raw = jax.tree_util.tree_map(
                lambda u, r: u + r, raw, self._chain_resid
            )
        wire = encode_bcast_delta(
            self.bcast_codec_name, raw, seed=self.seed,
            round_idx=next_round,
        )
        decoded = tree_from_wire(wire, self.variables)
        self._chain_resid = jax.tree_util.tree_map(
            lambda u, d: u - np.asarray(d, np.float32), raw, decoded
        )
        self.variables = apply_bcast_delta(prev_model, decoded)
        if self._async:
            # chain mode's model-log record: the advanced model IS what
            # round next_round broadcasts (the close-path record is
            # skipped when a chain is on — a deferred advance would
            # otherwise log the pre-advance model)
            self._model_log[next_round] = self.variables
            while len(self._model_log) > self.max_staleness + 1:
                self._model_log.popitem(last=False)
        with self._ack_lock:
            self._delta_log[next_round] = wire
            while len(self._delta_log) > self.delta_base_window:
                self._delta_log.popitem(last=False)

    def _arm_deadline(self):
        if self.round_timeout is None:
            return
        t = threading.Timer(
            self.round_timeout, self._on_deadline, args=(self.round_idx,)
        )
        t.daemon = True
        self._deadline_timer = t
        t.start()

    def _on_deadline(self, round_gen: int):
        try:
            arrived = None
            with self._round_lock:
                if round_gen != self.round_idx or self.round_idx >= self.comm_rounds:
                    return  # stale timer: that round already closed
                arrived = len(self.pending)
                if not self.pending:
                    # nobody arrived: the global model is unchanged, the
                    # round still closes (an all-dropped round under the
                    # mask semantics is a no-op update)
                    self._close_round(dropped_all=True)
                else:
                    self._close_round()
            # black-box trigger outside the round lock (the dump does
            # file IO): a deadline firing IS the overrun — the timer
            # only exists while the round is still open
            flight.trigger("deadline_overrun", round_idx=round_gen,
                           reason=f"arrived={arrived}")
        except Exception:
            # a Timer-thread exception dies silently; without the
            # re-arm below the round would stay open forever (no later
            # timer exists).  Re-arm UNCONDITIONALLY: if _close_round
            # raised after round_idx += 1 (mid-broadcast), the timer it
            # never reached must cover the NEW round — and a duplicate
            # timer for an already-closed round is generation-checked
            # into a no-op anyway.
            logging.exception("round %d deadline close failed", round_gen)
            with self._round_lock:
                self._arm_deadline()

    def _sampled_nodes(self):
        """Seeded uniform sampling every round (the fork's hardcoded
        formula, FedAvgServerManager.py:66-75, is deliberately absent).
        With ``spares`` the draw is ``clients_per_round + spares`` wide —
        the extra nodes hedge stragglers; aggregation still targets the
        first ``clients_per_round`` reporters."""
        if self.broadcast_size >= self.num_clients:
            ids = np.arange(self.num_clients)
        else:
            rng = np.random.RandomState(self.seed * 100003 + self.round_idx)
            ids = np.sort(
                rng.choice(self.num_clients, self.broadcast_size, replace=False)
            )
        return [int(i) + 1 for i in ids]  # node id = client id + 1

    def _model_msg(self, msg_type: str, node, slot, wire) -> Message:
        """Sync envelope.  ``node=None`` builds the SHARED multicast
        form: receiver -1, no client_idx/slot params — each client
        derives both from its own node id (node = client + 1 and slot
        always equaled node - 1), so one immutable frame serves the
        whole cohort."""
        m = Message(msg_type, SERVER, -1 if node is None else node)
        m.add_params(MSG_ARG_KEY_MODEL_PARAMS, wire)
        if node is not None:
            m.add_params(MSG_ARG_KEY_CLIENT_INDEX, node - 1)
            m.add_params("slot", slot)  # global client id → rng stream id (matches SPMD slot_ids)
        m.add_params(MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        if self._codec is not None:
            m.add_params(MSG_ARG_KEY_CODEC, self.codec_name)
        if self.steps_per_epoch is not None:
            m.add_params("steps_per_epoch", self.steps_per_epoch)
        if self.bcast == "delta":
            # full frames in delta mode still announce the cache depth:
            # the receiver starts (or keeps) caching reconstructed
            # rounds so later deltas have their base on hand
            m.add_params("delta_window", self.delta_base_window)
        return m

    def _is_stale(self, msg: Message, reply_round) -> bool:  # fedlint: holds=_round_lock
        """Caller holds the round lock.  Discard a straggler's upload
        from an already-closed round: aggregating it into the CURRENT
        round would double-count its stale parameters (missing round
        index = legacy client, accepted as current).

        Async mode keeps this as the hard OUTER bound only: an upload
        up to ``max_staleness`` rounds behind folds in discounted
        (``_stale_weight``); beyond the window — or claiming a FUTURE
        round — it is rejected exactly like the sync barrier would."""
        assert_held(self._round_lock, "FedAvgServerManager._is_stale")
        if (self._async and reply_round is not None
                and reply_round != self.round_idx):
            d = self.round_idx - int(reply_round)
            if 0 < d <= self.max_staleness:
                return False  # in-window: discounted at fold, not dropped
        if reply_round is not None and reply_round != self.round_idx:
            self.round_log.append(
                {"round": self.round_idx, "stale_from": msg.sender,
                 "stale_round": reply_round}
            )
            # counted (not just logged) so the SLO engine's stale-upload
            # budget reads one series instead of parsing the round_log
            get_telemetry().inc("faults.observed", kind="stale_upload",
                                msg_type=MSG_TYPE_C2S_SEND_MODEL)
            return True
        return False

    def _stale_weight(self, delta: int) -> float:
        """w(r-b) for an in-window async upload — the shared np|jnp
        formula from ``core/staleness`` at xp=np (host fold path).
        delta<=0 short-circuits to exactly 1.0 so the current-round
        fast path never even builds an array."""
        if delta <= 0:
            return 1.0
        return float(staleness_weight(
            delta, self.stale_policy, alpha=self.stale_alpha,
            window=self.max_staleness, xp=np,
        ))

    def _staleness_delta(self, reply_round) -> int:  # fedlint: holds=_round_lock
        """Round gap of an accepted upload (0 = current / legacy
        no-echo), read under the round lock so it is consistent with
        the cut this fold lands in."""
        assert_held(self._round_lock,
                    "FedAvgServerManager._staleness_delta")
        if not self._async or reply_round is None:
            return 0
        return max(0, self.round_idx - int(reply_round))

    def _on_resync(self, msg: Message) -> None:
        """A client received a delta against a base it no longer holds
        (fresh process, rejoined muxer, cache aged out): clear its ack
        so the NEXT broadcast goes full, and unicast the current
        round's full model right away so it can still make this round.
        The wire is the memoized per-round encode (``_full_wire``) —
        a rejoined muxer's whole cohort resyncs at once, and per-node
        encodes under the round lock would serialize O(cohort x model)
        work in front of upload folding."""
        # deferred chain advance: _full_wire below must serve the
        # ADVANCED model.  Wait OUTSIDE the round lock — the encode
        # thread takes it for the advance, so waiting inside would
        # deadlock the very thread we are waiting on.
        if not self._chain_done.wait(timeout=30.0):
            logging.warning(
                "resync from node %d: deferred chain advance still "
                "pending after 30s — serving the current model",
                msg.sender,
            )
        with self._round_lock:
            with self._ack_lock:
                self._acked.pop(msg.sender, None)
            if self.bcast != "delta" or self.round_idx >= self.comm_rounds:
                return
            get_telemetry().inc("comm.delta_full_fallbacks",
                                reason="resync")
            m = self._model_msg(
                MSG_TYPE_S2C_SYNC_MODEL, msg.sender, msg.sender - 1,
                self._full_wire(),
            )
        # send OUTSIDE the round lock (every other model send does):
        # the socket write plus its bounded retry backoffs would
        # otherwise stall upload folding and the deadline timer for
        # the whole cohort's resync walkback.  If the round closes
        # between build and send the client just gets last round's
        # sync — its upload is stale-rejected and the cleared ack
        # makes the next broadcast full, same as any in-flight sync
        # racing a close.
        self._send_or_log(m)

    def _on_model(self, msg: Message):
        reply_round = msg.get(MSG_ARG_KEY_ROUND_INDEX)
        with self._round_lock:
            if self.bcast == "delta" and reply_round is not None:
                # implicit ack: an upload echoing round r proves the
                # node RECEIVED round r's sync, i.e. holds the chain
                # model M_r — even a stale or later-rejected upload
                # proves that much (a lying ack only costs the liar a
                # resync round trip)
                with self._ack_lock:
                    prev = self._acked.get(msg.sender)
                    if prev is None or int(reply_round) > prev:
                        self._acked[msg.sender] = int(reply_round)
            if self._is_stale(msg, reply_round):
                return
            # delta uploads reconstruct against the model THIS round
            # broadcast — capture it under the lock (a concurrent round
            # close would swap self.variables; the post-decode stale
            # re-check then discards anything decoded against it).
            # Async: an in-window stale upload decodes against the base
            # its client trained from (the model log keeps the last
            # max_staleness+1 broadcast models).
            if self._async and reply_round is not None:
                base = self._model_log.get(int(reply_round),
                                           self.variables)
            else:
                base = self.variables
        if self._decode_pool is not None:
            # pipeline: hand decode+fold to the worker pool and free
            # the reader thread for the next frame — decode of upload i
            # overlaps the wire receive of upload i+1.  A slab-backed
            # payload (shm lane) is pinned across the thread handoff so
            # the ring cannot reclaim it before the decode ran.
            unpin = msg.pin_payload()
            self._decode_pool.submit(
                self._decode_and_fold_pinned, msg, base, reply_round,
                time.perf_counter(), unpin,
            )
            return
        self._decode_and_fold(msg, base, reply_round, None)

    def _decode_and_fold_pinned(self, msg, base, reply_round, t_submit,
                                unpin) -> None:
        try:
            self._decode_and_fold(msg, base, reply_round, t_submit)
        finally:
            unpin()

    def _decode_and_fold(self, msg: Message, base, reply_round,
                         t_submit: Optional[float]) -> None:
        try:
            self._decode_and_fold_inner(msg, base, reply_round, t_submit)
        except Exception:
            # a pool task's exception dies in its Future — the upload
            # would silently vanish and the round hang to its deadline
            # with no attributable cause.  Log + count like any other
            # bad upload.
            logging.exception("upload decode/fold failed for node %d",
                              msg.sender)
            self._reject_upload(msg.sender, "undecodable_upload")

    def _decode_and_fold_inner(self, msg: Message, base, reply_round,
                               t_submit: Optional[float]) -> None:
        wait_s = 0.0
        t_start = time.perf_counter()
        if t_submit is not None:
            # decode queue wait: reader-thread submit -> pool pickup —
            # the pipeline's backpressure signal (grows when K uploads
            # outpace the decode workers)
            wait_s = t_start - t_submit
            get_telemetry().observe("span.decode_wait_s", wait_s)
        # decode + validate OUTSIDE the round lock: both are O(model)
        # (multi-MB decode, full-tree finite scan) and K near-
        # simultaneous uploads would otherwise serialize behind one
        # lock with the deadline timer blocked at the back of the queue
        try:
            # shared decode + corrupt-payload firewall (the edge hub
            # runs the identical intake on its tier)
            variables, n = decode_validated_upload(msg, base)
        except UploadRejected as bad:
            self._reject_upload(msg.sender, bad.kind)
            return
        conn_key = None
        if self._robust is not None:
            # robust screening — the norm-space extension of the
            # non-finite firewall above, same altitude (O(model), host
            # numpy, OUTSIDE the round lock): outlier-score reject,
            # norm clip against the broadcast base, client-level DP.
            # Per-upload math depends only on (upload, base, seed,
            # round, slot) — never on arrival order — so defended
            # same-seed runs agree whatever the interleaving.
            screened, defense_flags = self._robust.screen(
                variables, base,
                round_idx=(reply_round if reply_round is not None
                           else self.round_idx),
                slot=msg.sender - 1,
            )
            if screened is None:
                self._reject_upload(msg.sender, "outlier_upload")
                return
            variables = screened
            if self._conn_cap > 0:
                # connection attribution for the contribution cap: the
                # hub's conn_map introspection is the authority (a
                # muxer cannot claim independent connections for its
                # virtual cohort); nodes outside the map count as their
                # own singleton connection
                fn = getattr(self.backend, "conn_map", None)
                if callable(fn):
                    self._robust.set_conn_map(fn())
                conn_key = self._robust.conn_key(msg.sender)
        decode_s = time.perf_counter() - t_start
        get_telemetry().observe("span.decode_s", decode_s)
        with self._round_lock:
            # re-check: the round may have closed (deadline, or the
            # K-th other reporter) while this upload was decoding
            if self._is_stale(msg, reply_round):
                return
            # pipeline evidence for the round that this upload counts
            # toward: the LAST accepted upload's numbers are the
            # closing chain's (round_close reads them under this lock)
            self._last_decode_wait_s = wait_s
            self._last_decode_s = decode_s
            if msg.sender in self.pending:
                # duplicate upload (chaos duplicate / redelivery): the
                # buffered path overwrote the entry idempotently, but a
                # streaming fold cannot un-fold the first copy — ignore
                # the dupe (copies are byte-identical, so ignore ==
                # overwrite) and count the observation in both modes
                get_telemetry().inc("faults.observed",
                                    kind="duplicate_upload",
                                    msg_type=MSG_TYPE_C2S_SEND_MODEL)
                return
            # async staleness discount (post-duplicate-check, so a
            # redelivered copy never double-counts the async series):
            # an in-window upload d rounds behind folds at weight
            # w(d)·n.  d==0 keeps n UNTOUCHED (no multiply) — the
            # fp-exactness the async≡sync byte-identity pin rests on.
            d = self._staleness_delta(reply_round)
            if self._async:
                tel = get_telemetry()
                tel.observe("async.upload_staleness", float(d))
                if d > 0:
                    w = self._stale_weight(d)
                    tel.inc("async.stale_weighted_uploads")
                    if w != 1.0:
                        n_w = float(np.float64(w) * np.float64(n))
                        tel.inc("async.discarded_weight", n - n_w)
                        n = n_w
                tel.inc("async.folded_weight", n)
            meta = {"n": n,
                    "metrics": msg.get(MSG_ARG_KEY_LOCAL_METRICS) or {}}
            if self._robust is not None:
                # defense telemetry counts ACCEPTED uploads only —
                # after the duplicate check above, so a redelivered
                # copy's screening never double-counts
                self._robust.note_upload(defense_flags)
            if self.streaming_agg and not self._defense_buffered:
                # fold NOW, under the round lock (a concurrent close
                # swaps the accumulator; the stale re-check above makes
                # this fold belong to the open round): pending keeps
                # METADATA only, so peak memory stays O(model) however
                # large the cohort, and the close-time aggregation
                # stall collapses into these per-arrival folds
                t0 = time.perf_counter()
                if conn_key is not None:
                    # contribution caps: one num/den accumulator per
                    # PHYSICAL connection (O(conns · model)); the close
                    # rescales any conn over its weight-fraction cap
                    self._conn_acc[conn_key] = treelib.tree_fold_weighted(
                        self._conn_acc.get(conn_key), variables, n
                    )
                    self._conn_n[conn_key] = (
                        self._conn_n.get(conn_key, 0.0) + float(n)
                    )
                else:
                    self._agg_acc = treelib.tree_fold_weighted(
                        self._agg_acc, variables, n
                    )
                self._agg_n += float(n)
                get_telemetry().observe("span.agg_fold_s",
                                        time.perf_counter() - t0)
            else:
                # buffered: the legacy baseline arm, or a robust
                # estimator (median/trimmed-mean) that needs all K
                # decoded trees at close.  A slab-backed upload's
                # decoded views would outlive its pin (the close can be
                # a whole deadline away) — own the bytes here.
                if msg._region is not None:
                    variables = jax.tree_util.tree_map(
                        lambda l: np.array(l, copy=True), variables
                    )
                meta["variables"] = variables
            self.pending[msg.sender] = meta
            if len(self.pending) < self._cut_target:
                return
            try:
                self._close_round()
            except Exception:
                # same wedge prevention as _on_deadline: a close that
                # raised mid-broadcast (round_idx already advanced, no
                # deadline armed) must leave a timer behind, or nothing
                # ever closes the new round
                logging.exception("round close from upload path failed")
                self._arm_deadline()

    def _reject_upload(self, sender: int, kind: str) -> None:
        """Log + count a bad upload (takes the round lock itself); the
        round stays open — the deadline/other reporters close it."""
        get_telemetry().inc("faults.observed", kind=kind,
                            msg_type=MSG_TYPE_C2S_SEND_MODEL)
        flight.note("faults", "observed", what=kind, sender=sender)
        with self._round_lock:
            self.rejected_uploads += 1
            round_idx = self.round_idx
            self.round_log.append(
                {"round": round_idx, "rejected_from": sender,
                 "kind": kind}
            )
        # dump outside the round lock (file IO): a corrupt/outlier/
        # undecodable upload is exactly the moment the black box must
        # preserve — the offending frame's metadata is still in the ring
        flight.trigger("reject", round_idx=round_idx,
                       reason=f"{kind} from node {sender}")
        logging.warning(
            "round %d: rejected %s from node %d (excluded from "
            "aggregation)", round_idx, kind, sender,
        )

    def _on_partial(self, msg: Message) -> None:
        """One edge hub's pre-folded (sum n·model, sum n) pair
        (``MSG_TYPE_E2S_PARTIAL``, from ``algorithms/edge_hub``).

        The num/den formulation composes exactly with the flat
        streaming fold: the edge's accumulator is the same fp64
        ``tree_fold_weighted`` sum the root would have built from the
        raw uploads, and fp64 addition of those partial sums is exact
        at training magnitudes — so a tree run's final model is
        byte-identical to the same-seed flat run's (the tiered twin of
        PR 10's muxed-vs-per-process pin).

        An edge may flush MORE than one partial per round: ack-group
        sync frames can extend its expected cohort after a first flush,
        and late local stragglers flush as singletons after its local
        deadline.  Contributor sets must be disjoint — an overlap with
        already-counted reporters drops the whole partial, counted."""
        reply_round = msg.get(MSG_ARG_KEY_ROUND_INDEX)
        if not self.streaming_agg or self._defense_buffered:
            # a buffered estimator (median/trimmed-mean) needs the
            # per-client trees; a pre-folded pair cannot feed it, and
            # silently folding would undefend the round.  Refuse loudly.
            get_telemetry().inc("faults.observed",
                                kind="partial_unsupported",
                                msg_type=MSG_TYPE_E2S_PARTIAL)
            logging.warning(
                "E2S_PARTIAL from node %d dropped: root is not on the "
                "streaming fold (legacy hotpath or buffered defense) — "
                "the tree topology requires --hotpath fast and a "
                "streaming-composable defense", msg.sender,
            )
            return
        with self._round_lock:
            if self.bcast == "delta" and reply_round is not None:
                # implicit acks for every contributor: a partial
                # echoing round r proves those nodes received round r's
                # sync (same inference as _on_model's — the edge only
                # folds uploads that echoed its current round)
                contrib_keys = msg.get(MSG_ARG_KEY_CONTRIBUTORS) or {}
                with self._ack_lock:
                    for node_s in contrib_keys:
                        try:
                            node = int(node_s)
                        except (TypeError, ValueError):
                            continue
                        prev = self._acked.get(node)
                        if prev is None or int(reply_round) > prev:
                            self._acked[node] = int(reply_round)
            if self._is_stale(msg, reply_round):
                return
        if self._decode_pool is not None:
            # same pipeline as raw uploads: decode + validation off the
            # reader thread, slab-backed payloads pinned across the
            # handoff
            unpin = msg.pin_payload()
            self._decode_pool.submit(
                self._fold_partial_pinned, msg, reply_round,
                time.perf_counter(), unpin,
            )
            return
        self._fold_partial(msg, reply_round, None)

    def _fold_partial_pinned(self, msg, reply_round, t_submit,
                             unpin) -> None:
        try:
            self._fold_partial(msg, reply_round, t_submit)
        finally:
            unpin()

    def _fold_partial(self, msg: Message, reply_round,
                      t_submit: Optional[float]) -> None:
        try:
            self._fold_partial_inner(msg, reply_round, t_submit)
        except Exception:
            # same contract as _decode_and_fold: a pool task's
            # exception dies in its Future — log + count, never hang
            # the round silently
            logging.exception("partial decode/fold failed for edge "
                              "uplink %d", msg.sender)
            self._reject_upload(msg.sender, "undecodable_partial")

    def _fold_partial_inner(self, msg: Message, reply_round,
                            t_submit: Optional[float]) -> None:
        wait_s = 0.0
        t_start = time.perf_counter()
        if t_submit is not None:
            wait_s = t_start - t_submit
            get_telemetry().observe("span.decode_wait_s", wait_s)
        try:
            # decode against the fp64 template (NOT self.variables):
            # the num tree is the edge's fp64 accumulator and must stay
            # fp64 through the wire for the exact composition
            num = tree_from_wire(msg.get(MSG_ARG_KEY_MODEL_PARAMS),
                                 self._f64_template)
        except Exception:
            self._reject_upload(msg.sender, "undecodable_partial")
            return
        den = msg.get(MSG_ARG_KEY_NUM_SAMPLES)
        contrib: Dict[int, float] = {}
        ok = den is not None and np.isfinite(den) and den > 0
        try:
            for node_s, n_i in (msg.get(MSG_ARG_KEY_CONTRIBUTORS)
                                or {}).items():
                w = float(n_i)
                ok = ok and bool(np.isfinite(w)) and w > 0
                contrib[int(node_s)] = w
        except (TypeError, ValueError):
            ok = False
        # the corrupt-payload firewall, tier-2 edition: the edge
        # screened each upload, but the partial itself crossed a wire
        if not ok or not contrib or not all(
            np.isfinite(np.asarray(l)).all()
            for l in jax.tree_util.tree_leaves(num)
        ):
            self._reject_upload(msg.sender, "corrupt_partial")
            return
        if msg._region is not None:
            # slab-backed frame: decoded fp64 leaves are views into the
            # shm ring; adopted directly as the accumulator (first
            # partial of the round) they would outlive the pin — own
            # the bytes here
            num = jax.tree_util.tree_map(
                lambda l: np.array(l, copy=True), num
            )
        decode_s = time.perf_counter() - t_start
        tel = get_telemetry()
        tel.observe("span.decode_s", decode_s)
        with self._round_lock:
            # re-check: the round may have closed while decoding
            if self._is_stale(msg, reply_round):
                return
            self._last_decode_wait_s = wait_s
            self._last_decode_s = decode_s
            if any(n in self.pending for n in contrib):
                # contributor overlap with already-counted reporters
                # (edge redelivery after a reconnect): the streaming
                # fold cannot un-fold the first copy — drop the whole
                # partial, counted
                tel.inc("faults.observed", kind="duplicate_upload",
                        msg_type=MSG_TYPE_E2S_PARTIAL)
                return
            # async per-tier discount: the edge tagged this partial
            # with ITS base round, so a partial d rounds behind scales
            # num AND den by w(d) — the num/den ratio (the models) is
            # untouched; only this tier's vote shrinks.  Gated on
            # w != 1.0 so the current-round path stays fp-exact.
            d = self._staleness_delta(reply_round)
            if self._async:
                tel.observe("async.upload_staleness", float(d))
                if d > 0:
                    w = self._stale_weight(d)
                    tel.inc("async.stale_weighted_uploads", len(contrib))
                    if w != 1.0:
                        w64 = np.float64(w)
                        num = jax.tree_util.tree_map(
                            lambda l: l * w64, num
                        )
                        den_w = float(w64 * np.float64(den))
                        tel.inc("async.discarded_weight", den - den_w)
                        den = den_w
                        contrib = {k: float(w64 * np.float64(v))
                                   for k, v in contrib.items()}
                tel.inc("async.folded_weight", den)
            t0 = time.perf_counter()
            if self._conn_cap > 0:
                # contribution caps over the tree: each partial carries
                # its edge-LOCAL connection group, so the cap keeps the
                # flat run's granularity (physical client/muxer conns).
                # An untagged partial degrades to one group per edge —
                # the whole edge link capped as a unit, never uncapped.
                key = msg.get("conn_group")
                key = str(key) if key else f"edge:{msg.sender}"
                acc = self._conn_acc.get(key)
                self._conn_acc[key] = (
                    num if acc is None
                    else jax.tree_util.tree_map(np.add, acc, num)
                )
                self._conn_n[key] = (self._conn_n.get(key, 0.0)
                                     + float(den))
            else:
                # numpy fp64 add — the same arithmetic domain as
                # tree_fold_weighted's accumulator, NOT jnp (a jit add
                # would leave the exact-composition contract)
                self._agg_acc = (
                    num if self._agg_acc is None
                    else jax.tree_util.tree_map(np.add, self._agg_acc,
                                                num)
                )
            self._agg_n += float(den)
            tel.observe("span.agg_fold_s", time.perf_counter() - t0)
            tel.inc("edge.partials_folded")
            for node in sorted(contrib):
                self.pending[node] = {"n": contrib[node], "metrics": {}}
            if len(self.pending) < self._cut_target:
                return
            try:
                self._close_round()
            except Exception:
                # same wedge prevention as the upload path
                logging.exception("round close from partial path failed")
                self._arm_deadline()

    def _close_round(self, dropped_all: bool = False):  # fedlint: holds=_round_lock
        """Aggregate whatever arrived and advance (caller holds the
        round lock).  Weighted average over any non-empty subset ==
        the compiled round's participation-mask aggregation."""
        assert_held(self._round_lock, "FedAvgServerManager._close_round")
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        # the model this round BROADCAST (M_r): the chain advance below
        # and the delta log are defined against it — capture before the
        # aggregation overwrites self.variables
        prev_model = self.variables
        sampled = set(self._sampled_nodes())
        time_agg = 0.0
        capped_conns = 0
        cap_infeasible = False
        streaming_close = self.streaming_agg and not self._defense_buffered
        entries = list(self.pending.values())
        total = (self._agg_n if streaming_close
                 else sum(e["n"] for e in entries))
        if total <= 0:
            # every reporter was rejected or weightless: same no-op
            # semantics as nobody arriving (a 0-weight average is
            # undefined — the compiled engine's den>0 guard, host-side)
            dropped_all = True
        if not dropped_all:
            # aggregate: sample-weighted average (FedAVGAggregator.py:58-87);
            # renormalization over the REALIZED reporters is the weight
            # correction over-sampled/deadline-cut cohorts need — each
            # weight is n_i / sum(n_arrived), never n_i / sum(n_sampled)
            t0 = time.perf_counter()
            if streaming_close and self._conn_n:
                # contribution-capped close: rescale any connection
                # over its weight-fraction cap (water-filling — capped
                # conns land at EXACTLY conn_cap of the rescaled
                # total), then combine the per-conn num/den pairs in
                # sorted-key order (arrival-order independent)
                from fedml_tpu.robust import cap_connection_weights

                scales, cap_infeasible = cap_connection_weights(
                    self._conn_n, self._conn_cap
                )
                num, den = None, 0.0
                for key in sorted(self._conn_acc):
                    scaled = treelib.tree_scale(self._conn_acc[key],
                                                scales[key])
                    num = scaled if num is None else treelib.tree_add(
                        num, scaled)
                    den += scales[key] * self._conn_n[key]
                capped_conns = sum(1 for v in scales.values() if v < 1.0)
                self.variables = treelib.tree_finalize_weighted_mean(
                    num, den, self.variables
                )
            elif streaming_close:
                # the whole cohort already folded in on arrival — the
                # close "stall" is one O(model) normalize, the engine's
                # exact num/den formulation (sum n·x then /sum n)
                self.variables = treelib.tree_finalize_weighted_mean(
                    self._agg_acc, total, self.variables
                )
            elif self._defense_buffered:
                # buffered robust close: the usual weighted mean for
                # the non-params collections, with the params
                # collection replaced by the coordinate-wise robust
                # center over the decoded cohort (core.robust's
                # estimator, numpy host-side — same formula the
                # compiled transform runs)
                self.variables = self._robust.buffered_close(
                    [e["variables"] for e in entries],
                    [e["n"] / total for e in entries],
                )
            else:
                self.variables = treelib.tree_weighted_sum(
                    [e["variables"] for e in entries],
                    [e["n"] / total for e in entries],
                )
            time_agg = time.perf_counter() - t0
            # same span series the simulation drivers feed (obs layer):
            # the reference's FedAVGAggregator.py:59,85-86 aggregate timer
            get_telemetry().observe("span.agg_s", time_agg)
        # quantized-chain advance (delta mode, and the full-mode
        # digest-pin arm at an explicit chain codec): encode the
        # aggregate update (+ the EF residual), decode OUR OWN
        # encoding, and adopt base + decode as the canonical next
        # model — every receiver of the delta reconstructs exactly
        # this, and the quantization error is carried, not lost.
        # On a dropped_all round the update is just the pending
        # residual (the chain still advances deterministically).
        # With the encode thread available AND a broadcast still to
        # come, the O(model) encode+decode moves OFF the round lock
        # onto _broadcast_async (PR 13 leftover) — ordering stays
        # safe because the next round's uploads reconstruct against
        # the model that broadcast ships, and the broadcast runs on
        # the same thread AFTER the advance.  The FINAL close has no
        # broadcast to ride, so it advances synchronously (the final
        # model must adopt the last quantization step either way).
        defer_chain = (self._chain and self._encode_pool is not None
                       and self.round_idx + 1 < self.comm_rounds)
        if self._chain and not defer_chain:
            self._advance_chain(prev_model)
        # wall-clock close stamp: deltas between consecutive recs are
        # the per-round wall time a federation artifact reports; the
        # monotonic open/close pair shares the hop-stamp clock
        # (perf_counter), so fed_timeline can place round boundaries on
        # the merged timeline without touching time.time at all
        t_close_m = time.perf_counter()
        rec = {"round": self.round_idx, "participants": sorted(self.pending),
               "time_agg": round(time_agg, 6),
               # observability stamp for run artifacts, never read by any
               # decision path (round logic runs on perf_counter spans)
               "t": round(time.time(), 3),  # fedlint: disable=determinism -- wall stamp in the round_log artifact record only; no control flow reads it
               "t_open_m": round(self._round_open_t, 6),
               "t_close_m": round(t_close_m, 6)}
        missing = sorted(sampled - set(self.pending))
        if len(self.pending) >= self._cut_target:
            # the round closed at its K-report target: unreported nodes
            # are over-sampled spares whose hedge wasn't needed — NOT
            # dropouts (logging them as 'dropped' would make a healthy
            # spared run indistinguishable from a mild drop fault)
            dropped = []
            if missing:
                rec["spared"] = missing
        else:
            dropped = missing
            if dropped:
                rec["dropped"] = dropped  # deadline expired without them
        tel = get_telemetry()
        # server-side round wall time (open -> close): the recovery-span
        # series a chaos soak reads next to span.reconnect_s
        tel.observe("span.server_round_s",
                    max(0.0, time.perf_counter() - self._round_open_t))
        if self._async:
            # every async close is a CUT (K arrivals or the cut
            # deadline) — the series fed_timeline/fed_slo read the
            # cut cadence from
            tel.inc("async.cut_rounds")
        if len(self.pending) < self._cut_target:
            # degraded: fewer reporters than the aggregation target
            # (deadline cut, crashes, dropped frames) — same counter
            # series the simulation drivers increment, so one number
            # covers both transports (unified deadline semantics)
            tel.inc("rounds.degraded")
            tel.event("degraded_round", round=self.round_idx,
                      arrived=len(self.pending), dropped=dropped)
        if not self.pending:
            # a zero-participant round is a silent no-op update; a run
            # where EVERY round is one (deadline shorter than client
            # train time — all uploads arrive a round late and are
            # stale-rejected) would otherwise "finish" with the init
            # model and rc=0.  Count them so callers can fail loudly.
            self.zero_participant_rounds += 1
            logging.warning(
                "round %d closed with ZERO participants (deadline %.1fs; "
                "sampled %s) — global model unchanged this round",
                self.round_idx, self.round_timeout or -1.0, sorted(sampled),
            )
        # pipeline evidence riding the round boundary: the closing
        # upload's decode queue wait + decode span, and the PREVIOUS
        # broadcast's off-thread encode+send span (it opened this
        # round) — what fed_timeline reads for its decode_wait /
        # encode_overlap phases
        rec["decode_wait_s"] = round(self._last_decode_wait_s, 6)
        rec["decode_s"] = round(self._last_decode_s, 6)
        rec["encode_overlap_s"] = round(self._bcast_task_s, 6)
        if self._robust is not None:
            # per-round defense activity (clipped / outlier-rejected /
            # DP-noised / capped conns) next to participants and spans
            # — what trace_summary's defense section and fed_slo read
            rec["defense"] = self._robust.note_round(
                capped=capped_conns, cap_infeasible=cap_infeasible
            )
        # the same record as a telemetry event: the server's
        # metrics-node0.jsonl then carries round boundaries next to its
        # trace_hop chains, so the timeline merger reads ONE stream
        tel.event("round_close", round=self.round_idx,
                  participants=len(self.pending), time_agg=rec["time_agg"],
                  t_open_m=rec["t_open_m"], t_close_m=rec["t_close_m"],
                  decode_wait_s=rec["decode_wait_s"],
                  decode_s=rec["decode_s"],
                  encode_overlap_s=rec["encode_overlap_s"],
                  **({"defense": rec["defense"]} if "defense" in rec
                     else {}))
        if self.slo is not None:
            # stats plane: SLO histograms + evaluation against the
            # merged rollup, while this round's state is still in hand
            try:
                self._slo_close(t_close_m)
            except Exception:
                # the health plane must never be able to fail a round
                logging.exception("SLO close-evaluation failed")
        self.round_log.append(rec)
        self.pending.clear()
        self._agg_acc, self._agg_n = None, 0.0
        self._conn_acc, self._conn_n = {}, {}
        self._last_decode_wait_s = self._last_decode_s = 0.0
        self.round_idx += 1
        if self._async and not self._chain:
            # base-model log for the new round's broadcast (chain mode
            # records inside _advance_chain — see there)
            self._model_log[self.round_idx] = self.variables
            while len(self._model_log) > self.max_staleness + 1:
                self._model_log.popitem(last=False)
        if self.round_idx >= self.comm_rounds:
            nodes = list(range(1, self.num_clients + 1))
            if self.multicast:
                try:
                    self.backend.send_multicast(
                        Message(MSG_TYPE_S2C_FINISH, SERVER, -1), nodes
                    )
                except OSError:
                    # the federation is over either way; undelivered
                    # FINISH frames only leave clients to their own
                    # timeouts (same stance as _send_or_log)
                    get_telemetry().inc("comm.send_failed",
                                        msg_type=MSG_TYPE_S2C_FINISH)
            else:
                for node in nodes:
                    self._send_or_log(
                        Message(MSG_TYPE_S2C_FINISH, SERVER, node)
                    )
            self.finish()
            return
        self._round_open_t = time.perf_counter()
        if self._encode_pool is not None:
            # double-buffered broadcast: the O(model) wire encode + hub
            # write runs on the dedicated encode thread so the caller
            # (a decode worker or the deadline timer) releases the
            # round lock immediately — residual/stale uploads decode
            # while the next sync serializes.  Safe lock-free reads:
            # self.variables/round_idx only change at the NEXT close,
            # which cannot happen before this broadcast reaches clients.
            if defer_chain:
                self._chain_done.clear()
            self._encode_pool.submit(
                self._broadcast_async, self.round_idx,
                prev_model if defer_chain else None,
            )
        else:
            self._broadcast_model(MSG_TYPE_S2C_SYNC_MODEL)
            self._arm_deadline()

    def _broadcast_async(self, round_gen: int, chain_prev=None) -> None:
        """Encode-thread body: (optionally) run the deferred chain
        advance, broadcast the new round's sync, record the overlapped
        span, then arm the deadline (the deadline must not start
        ticking before the sync is on the wire — same ordering as the
        synchronous path)."""
        if chain_prev is not None:
            # deferred _advance_chain (close handed us M_r): takes the
            # round lock itself — the close-path caller released it
            # when it submitted this task.  _chain_done gates the rare
            # off-thread readers of the post-advance model (resync
            # unicasts) that can run in the close→advance gap.
            try:
                # round_gen is the post-increment round index — exactly
                # the next_round this advance is producing the wire for
                with self._round_lock:
                    self._advance_chain(chain_prev, round_gen)
            except Exception:
                logging.exception(
                    "round %d: deferred chain advance failed "
                    "(broadcasting the unadvanced model)", round_gen,
                )
            finally:
                self._chain_done.set()
        t0 = time.perf_counter()
        try:
            self._broadcast_model(MSG_TYPE_S2C_SYNC_MODEL)
        except Exception:
            # _broadcast_model already downgrades OSError under a
            # deadline; anything else must still not kill the encode
            # thread — the deadline below keeps the federation moving
            logging.exception("round %d: async broadcast failed",
                              round_gen)
            if self.round_timeout is None:
                # fail-fast contract (_broadcast_model's own
                # no-deadline branch): with no deadline nothing can
                # ever recover a lost sync, and an exception on this
                # pool thread dies in its Future — re-raising would be
                # a SILENT permanent hang.  Tear the federation down
                # visibly instead.
                logging.critical(
                    "round %d: broadcast lost with no round deadline — "
                    "shutting the federation down (fail-fast)", round_gen,
                )
                self.finish()
                return
        dt = time.perf_counter() - t0
        get_telemetry().observe("span.encode_overlap_s", dt)
        with self._round_lock:
            self._bcast_task_s = dt
            if round_gen == self.round_idx:
                self._arm_deadline()

    def finish(self) -> None:
        # stats plane last words: final local fold, slo_report.json +
        # terminal status.json (idempotent — the fail-fast broadcast
        # path reaches here too)
        self._stats_finish()
        # non-blocking shutdown: the final _close_round runs ON a
        # decode worker when pipelining, and a wait=True here would
        # join the thread into itself.  Workers drain naturally; any
        # still-queued stale decode lands on a stopped backend's
        # rejection path.
        for pool in (self._decode_pool, self._encode_pool):
            if pool is not None:
                pool.shutdown(wait=False)
        super().finish()

    def _send_or_log(self, msg: Message) -> None:
        """Broadcast sends must not abort the round loop: a sync the
        transport cannot deliver right now (hub restarting, socket
        mid-reconnect — AFTER the backend's own bounded retries) just
        makes that node a straggler this round; the deadline covers it.

        Swallowing is only safe when a deadline EXISTS to cover the
        lost frame (or the federation is already over — FINISH): with
        ``round_timeout=None`` a dropped sync would hang the round
        forever, so there the legacy fail-fast raise is preserved."""
        try:
            self.send_message(msg)
        except OSError:
            if self.round_timeout is None and msg.type != MSG_TYPE_S2C_FINISH:
                raise
            get_telemetry().inc("comm.send_failed", msg_type=msg.type)
            logging.warning(
                "round %d: could not deliver %s to node %d (will rely "
                "on the round deadline)", self.round_idx, msg.type,
                msg.receiver,
            )


class FedAvgClientManager(NodeManager):
    """One federated participant: train on INIT/SYNC, upload, repeat."""

    def __init__(
        self,
        backend: CommBackend,
        local_update: LocalUpdateFn,
        dataset: FedDataset,
        *,
        batch_size: int,
        template_variables,
        seed: int = 0,
        train_delay: float = 0.0,
        crash_at_round: Optional[int] = None,
        error_feedback: bool = True,
        traffic=None,
    ):
        # open-loop traffic model (faults/traffic.TrafficModel): this
        # client draws a seeded per-round arrival decision — offline
        # rounds are skipped (churn), delays sleep before training so
        # the upload arrives on the device's own clock.  None = the
        # closed-loop behavior, unchanged.
        self.traffic = traffic
        self.local_update = jax.jit(local_update.fn)
        self.dataset = dataset
        self.batch_size = batch_size
        self.template = template_variables
        self.seed = seed
        self.rounds_trained = 0
        # uplink compression (server-negotiated via the sync's codec
        # key): EF keeps the per-round quantization error and folds it
        # into the next update — on by default for lossy codecs
        self.error_feedback = error_feedback
        self._ef = {}  # ef_for store; one entry (this client's stream)
        # delta-broadcast base cache: round -> OWNED copy of the
        # reconstructed chain model (populated only when the server
        # announces delta mode via the sync's delta_window param).
        # Owned copies serve two contracts at once: any in-window delta
        # base is on hand across rounds, and nothing cached can alias a
        # transport buffer (an shm slab region is reclaimed the moment
        # delivery ends)
        from collections import OrderedDict

        self._bases: "OrderedDict[int, object]" = OrderedDict()
        self._base_window = 4
        # sha256 over every encoded upload's payload buffers, in send
        # order — the reproducibility probe a federation re-run compares
        # (same seed => identical digest)
        import hashlib

        self._upload_hash = hashlib.sha256()
        # artificial pre-training sleep: straggler injection for the
        # server's round-deadline path (tests/test_distributed_process)
        self.train_delay = train_delay
        # deterministic process crash: hard-exit (no cleanup, no FINISH)
        # when the sync for THIS round arrives — the chaos layer's
        # SIGKILL-at-round-r, reproducible across runs
        self.crash_at_round = crash_at_round
        # in-band stats plane: the entry point attaches a DigestReporter
        # here so FINISH stops it with one final delta flush (the
        # rollup then covers this client's whole run)
        self.stats_reporter = None
        super().__init__(backend)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_sync(self, msg: Message):
        if (
            self.crash_at_round is not None
            and msg.get(MSG_ARG_KEY_ROUND_INDEX) == self.crash_at_round
        ):
            import os

            # the black box flushes on the way down (forced, bypassing
            # the rate limit) — a REAL SIGKILL leaves only the
            # faulthandler log, but this injected crash models a
            # process that still gets its last instruction through
            flight.trigger("crash", reason="crash_at_round",
                           round_idx=self.crash_at_round, force=True)
            # os._exit: skip atexit/finally — the process dies exactly
            # like a SIGKILL'd one, mid-protocol, socket left dangling
            os._exit(137)
        if self.train_delay:
            import time

            time.sleep(self.train_delay)
        if self.traffic is not None:
            r = msg.get(MSG_ARG_KEY_ROUND_INDEX)
            if r is not None:
                tel = get_telemetry()
                d = self.traffic.decide(self.backend.node_id, int(r))
                if d["offline"]:
                    # churned out this round: no training, no upload —
                    # the server's deadline (or async cut) covers it
                    tel.inc("traffic.offline_rounds")
                    return
                if d["straggler"]:
                    tel.inc("traffic.straggler_draws")
                if d["delay_s"] > 0.0:
                    import time

                    tel.inc("traffic.delayed_uploads")
                    tel.observe("traffic.upload_delay_s", d["delay_s"])
                    time.sleep(d["delay_s"])
        variables = self._reconstruct_sync(msg)
        if variables is None:
            return  # inapplicable delta: resync requested, round skipped
        client_idx = msg.get(MSG_ARG_KEY_CLIENT_INDEX)
        if client_idx is None:
            # multicast sync: ONE shared envelope for the whole cohort —
            # identity is derived from the node id (node = client + 1),
            # exactly what the per-node unicast params always carried
            client_idx = self.backend.node_id - 1
        round_idx = msg.get(MSG_ARG_KEY_ROUND_INDEX)
        # round-independent pack seed, matching FedAvgSimulation's
        # device-resident cohort blocks: the pack base order carries no
        # stochasticity (the local update re-permutes per epoch from the
        # (key, round, slot) stream), and per-client pack seeding is
        # id-keyed, so this single-client pack is bit-identical to the
        # client's row in the simulation's cohort pack
        pack = pack_clients(
            self.dataset, [client_idx], self.batch_size,
            steps_per_epoch=msg.get("steps_per_epoch"),
            seed=self.seed,
        )
        # identical stream to the compiled round engine: key→round→train→slot
        slot = msg.get("slot", client_idx)
        k_round = jax.random.fold_in(jax.random.PRNGKey(self.seed), round_idx)
        rng = jax.random.fold_in(jax.random.fold_in(k_round, 0), slot)
        new_vars, metrics = self.local_update(
            variables,
            jnp.asarray(pack.x[0]), jnp.asarray(pack.y[0]),
            jnp.asarray(pack.mask[0]), rng,
        )
        self.rounds_trained += 1
        reply = Message(MSG_TYPE_C2S_SEND_MODEL, self.backend.node_id, SERVER)
        # echo the round: the server rejects uploads from closed rounds
        reply.add_params(MSG_ARG_KEY_ROUND_INDEX, round_idx)
        codec_name = msg.get(MSG_ARG_KEY_CODEC) or "none"
        wire = self._encode_upload(
            codec_name, new_vars, variables, round_idx, slot
        )
        reply.add_params(MSG_ARG_KEY_MODEL_PARAMS, wire)
        reply.add_params(MSG_ARG_KEY_NUM_SAMPLES, float(pack.num_samples[0]))
        reply.add_params(
            MSG_ARG_KEY_LOCAL_METRICS, {k: float(v) for k, v in metrics.items()}
        )
        self.send_message(reply)

    def _reconstruct_sync(self, msg: Message):
        """One sync envelope → this round's model, via the SHARED
        ``reconstruct_sync_model`` (the muxer uses the same function —
        reconstruction cannot drift between topologies).  A delta whose
        base is not cached (fresh process, aged-out round) triggers a
        RESYNC request and returns None: this round is skipped and the
        server's unicast full resend (or the next full fallback)
        re-seeds the cache."""
        variables, self._base_window = reconstruct_sync_model(
            msg, self.template, self._bases, self._base_window
        )
        if variables is None:
            get_telemetry().inc("comm.delta_resyncs")
            logging.warning(
                "node %d: delta sync for round %s against unknown "
                "base %s — requesting full resync",
                self.backend.node_id, msg.get(MSG_ARG_KEY_ROUND_INDEX),
                msg.get(MSG_ARG_KEY_DELTA_BASE),
            )
            request_resync(self.send_message, self.backend.node_id,
                           msg.get(MSG_ARG_KEY_ROUND_INDEX))
        return variables

    def _encode_upload(self, codec_name: str, new_vars, synced_vars,
                       round_idx: int, slot: int):
        """Encode this client's upload via the SHARED
        ``encode_client_upload`` (the muxer's cohort engine calls the
        same function — byte-identity by construction) and fold it into
        the reproducibility digest.  Since the muxer landed, the digest
        covers EVERY upload — full-precision wiretrees included — so a
        same-seed muxed-vs-per-process comparison pins the fp32 path
        too, not just the codec one."""
        from fedml_tpu.compress import wire_tree_digest
        from fedml_tpu.obs import comm_obs

        wire, raw, comp = encode_client_upload(
            codec_name, new_vars, synced_vars, self.template,
            seed=self.seed, round_idx=round_idx, slot=slot,
            ef=ef_for(self._ef, 0, codec_name, self.error_feedback),
        )
        if raw is not None:
            comm_obs.record_compression(MSG_TYPE_C2S_SEND_MODEL, raw, comp)
        self._upload_hash.update(wire_tree_digest(wire).encode())
        return wire

    @property
    def upload_digest(self) -> str:
        """Accumulated sha256 of every encoded upload this client sent."""
        return self._upload_hash.hexdigest()

    def _on_finish(self, msg: Message):
        if self.stats_reporter is not None:
            # final flush BEFORE the backend stops: the last digest
            # frame rides the still-open connection
            self.stats_reporter.stop()
        self.finish()
