"""SplitNN — ring-relay split learning, TPU-native.

Reference (SURVEY.md §2 row 16, §3.3): the client holds the bottom half
of the network, the server the top.  Per mini-batch the client sends
activations (``split_nn/client.py:24-30``), the server forwards them,
computes CE loss, backprops, and returns ``acts.grad``
(``server.py:40-59``); the client finishes the backward pass
(``client.py:32-34``).  Exactly one client is active at a time; a
semaphore token passes control around the ring
(``client_manager.py:29-34``, ``message_define.py:6-16``), and the
server rotates its active node + runs validation after each client's
epoch (``server.py:61-75``).  Both halves use SGD(lr=0.1, momentum=0.9,
wd=5e-4) (``client.py:18-19``, ``server.py:19-20``).

TPU-native design: control crossing a process boundary twice per
mini-batch is the worst possible fit for an accelerator, so on-device
the boundary is *compiled away*: one jitted step computes the bottom
forward as a ``jax.vjp``, the top forward/backward by autodiff, and
feeds the activation cotangent — the exact tensor the reference ships
back over MPI — straight into the bottom's vjp.  XLA fuses the whole
thing; the "message" is an HBM-resident tensor.  The same step
functions, split at that boundary (``bottom_forward`` /
``top_step`` / ``bottom_backward``), also drive the message-mode
managers below for true two-process deployments over the comm backend.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.comm.backend import CommBackend, NodeManager
from fedml_tpu.comm.message import (
    MSG_TYPE_C2C_SEMAPHORE,
    MSG_TYPE_C2S_SEND_ACTS,
    MSG_TYPE_S2C_FINISH,
    MSG_TYPE_S2C_SEND_GRADS,
    Message,
    tree_from_wire,
    tree_to_wire,
)
from fedml_tpu.core.losses import softmax_ce_logits
from fedml_tpu.models.base import ModelBundle

PyTree = Any
SERVER = 0


def split_optimizer(lr: float = 0.1, momentum: float = 0.9, weight_decay: float = 5e-4):
    """Reference optimizer for both halves (``client.py:18-19``)."""
    from fedml_tpu.core.client import make_client_optimizer

    return make_client_optimizer(
        "sgd", lr, momentum=momentum, weight_decay=weight_decay
    )


class HalfState(NamedTuple):
    params: PyTree
    opt_state: Any


def _apply(bundle: ModelBundle, params: PyTree, x: jax.Array, train: bool):
    return bundle.module.apply({"params": params}, x, train=train)


def make_split_steps(
    bottom: ModelBundle,
    top: ModelBundle,
    opt: Optional[optax.GradientTransformation] = None,
):
    """Build (fused_step, bottom_forward, top_step, bottom_backward, evaluate).

    ``fused_step`` is the on-device pipeline: both halves in one compiled
    program.  The other three are the protocol-split pieces for
    message-mode; feeding ``top_step``'s returned activation gradient to
    ``bottom_backward`` reproduces ``fused_step`` exactly.
    """
    opt = opt or split_optimizer()

    def bottom_forward(bstate: HalfState, x):
        return _apply(bottom, bstate.params, x, True)

    def top_step(tstate: HalfState, acts, y):
        """Server side: forward top, CE loss, step, return act-grads."""

        def loss_fn(tparams, acts):
            logits = _apply(top, tparams, acts, True)
            return softmax_ce_logits(logits, y).mean(), logits

        (loss, logits), (gt, gacts) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True
        )(tstate.params, acts)
        updates, opt_state = opt.update(gt, tstate.opt_state, tstate.params)
        new_t = HalfState(optax.apply_updates(tstate.params, updates), opt_state)
        correct = (jnp.argmax(logits, -1) == y).sum()
        return new_t, gacts, {"loss": loss, "correct": correct,
                              "count": jnp.asarray(y.shape[0], jnp.float32)}

    def bottom_backward(bstate: HalfState, x, gacts):
        """Client side: backprop bottom with the server's act-grads."""
        _, vjp = jax.vjp(lambda p: _apply(bottom, p, x, True), bstate.params)
        (gb,) = vjp(gacts)
        updates, opt_state = opt.update(gb, bstate.opt_state, bstate.params)
        return HalfState(optax.apply_updates(bstate.params, updates), opt_state)

    def fused_step(bstate: HalfState, tstate: HalfState, x, y):
        acts, vjp = jax.vjp(lambda p: _apply(bottom, p, x, True), bstate.params)
        new_t, gacts, metrics = top_step(tstate, acts, y)
        (gb,) = vjp(gacts)
        updates, opt_state = opt.update(gb, bstate.opt_state, bstate.params)
        new_b = HalfState(optax.apply_updates(bstate.params, updates), opt_state)
        return new_b, new_t, metrics

    def evaluate(bstate: HalfState, tstate: HalfState, x, y):
        logits = _apply(top, tstate.params, _apply(bottom, bstate.params, x, False), False)
        loss = softmax_ce_logits(logits, y).mean()
        correct = (jnp.argmax(logits, -1) == y).sum()
        return {"loss": loss, "correct": correct,
                "count": jnp.asarray(y.shape[0], jnp.float32)}

    return fused_step, bottom_forward, top_step, bottom_backward, evaluate


def init_half(bundle: ModelBundle, rng, opt=None) -> HalfState:
    opt = opt or split_optimizer()
    params = bundle.init(rng)["params"]
    return HalfState(params, opt.init(params))


@dataclasses.dataclass
class SplitNNSimulation:
    """Single-process ring driver (the reference's mpirun deployment,
    compiled: SplitNNAPI.py:15-40 + the §3.3 call stack).

    Each client owns a private bottom; the server owns the shared top.
    Ring order = client id; after each client's epoch the server
    validates and control passes on (``server.py:61-75``).
    """

    bottom: ModelBundle
    top: ModelBundle
    client_data: Sequence[Tuple[np.ndarray, np.ndarray]]  # per-client (x, y)
    test_data: Tuple[np.ndarray, np.ndarray]
    batch_size: int = 64
    lr: float = 0.1
    seed: int = 0

    def __post_init__(self):
        self.opt = split_optimizer(self.lr)
        fused, _, _, _, evaluate = make_split_steps(self.bottom, self.top, self.opt)
        self._step = jax.jit(fused)
        self._eval = jax.jit(evaluate)
        key = jax.random.PRNGKey(self.seed)
        self.client_states: List[HalfState] = [
            init_half(self.bottom, jax.random.fold_in(key, i + 1), self.opt)
            for i in range(len(self.client_data))
        ]
        self.server_state = init_half(self.top, key, self.opt)
        self.history: List[dict] = []
        self.epoch = 0

    def run_epoch(self) -> List[dict]:
        """One ring pass: every client trains one local epoch in turn."""
        out = []
        for cid, (x, y) in enumerate(self.client_data):
            rng = np.random.RandomState(self.seed * 7919 + self.epoch * 131 + cid)
            order = rng.permutation(len(x))
            tot = {"loss": 0.0, "correct": 0.0, "count": 0.0}
            bstate = self.client_states[cid]
            for lo in range(0, len(x) - self.batch_size + 1, self.batch_size):
                sl = order[lo : lo + self.batch_size]
                bstate, self.server_state, m = self._step(
                    bstate, self.server_state, jnp.asarray(x[sl]), jnp.asarray(y[sl])
                )
                tot["loss"] += float(m["loss"]) * float(m["count"])
                tot["correct"] += float(m["correct"])
                tot["count"] += float(m["count"])
            self.client_states[cid] = bstate
            val = self.validate(cid)
            rec = {
                "epoch": self.epoch, "client": cid,
                "train_acc": tot["correct"] / max(tot["count"], 1),
                "train_loss": tot["loss"] / max(tot["count"], 1),
                **{f"val_{k}": v for k, v in val.items()},
            }
            out.append(rec)
            self.history.append(rec)
        self.epoch += 1
        return out

    def validate(self, cid: int) -> dict:
        x, y = self.test_data
        m = {"loss": 0.0, "correct": 0.0, "count": 0.0}
        for lo in range(0, len(x), 256):
            r = self._eval(
                self.client_states[cid], self.server_state,
                jnp.asarray(x[lo : lo + 256]), jnp.asarray(y[lo : lo + 256]),
            )
            m["loss"] += float(r["loss"]) * float(r["count"])
            m["correct"] += float(r["correct"])
            m["count"] += float(r["count"])
        return {"acc": m["correct"] / m["count"], "loss": m["loss"] / m["count"]}


# --- message-mode managers (two-process deployments over CommBackend) -------

class SplitNNServerManager(NodeManager):
    """Holds the top half; answers every C2S_SEND_ACTS with S2C_SEND_GRADS
    (reference ``server_manager.py:31-36``)."""

    def __init__(self, backend: CommBackend, top: ModelBundle, *,
                 acts_template, lr: float = 0.1, seed: int = 0):
        self.opt = split_optimizer(lr)
        _, _, top_step, _, _ = make_split_steps(
            _DUMMY_BOTTOM, top, self.opt
        )
        self._top_step = jax.jit(top_step)
        self.state = init_half(top, jax.random.PRNGKey(seed), self.opt)
        self.acts_template = acts_template
        self.batches_seen = 0
        super().__init__(backend)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_C2S_SEND_ACTS, self._on_acts)
        self.register_message_receive_handler(MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_finish(self, msg: Message):
        self.finish()

    def _on_acts(self, msg: Message):
        acts = tree_from_wire(msg.get("acts"), self.acts_template)
        y = np.asarray(msg.get("labels"), dtype=np.int32)
        self.state, gacts, metrics = self._top_step(
            self.state, jnp.asarray(acts), jnp.asarray(y)
        )
        self.batches_seen += 1
        reply = Message(MSG_TYPE_S2C_SEND_GRADS, SERVER, msg.sender)
        reply.add_params("grads", tree_to_wire(gacts))
        reply.add_params("loss", float(metrics["loss"]))
        self.send_message(reply)


class SplitNNClientManager(NodeManager):
    """Holds a bottom half + its private data; drives its epoch batch by
    batch, then passes the semaphore to the next ring node
    (reference ``client_manager.py:29-74``)."""

    def __init__(self, backend: CommBackend, bottom: ModelBundle, x, y, *,
                 node_id: int, next_node: int, batch_size: int = 64,
                 lr: float = 0.1, active: bool = False, seed: int = 0,
                 total_hops: int = 1):
        self.opt = split_optimizer(lr)
        _, bottom_forward, _, bottom_backward, _ = make_split_steps(
            bottom, _DUMMY_TOP, self.opt
        )
        self._fwd = jax.jit(bottom_forward)
        self._bwd = jax.jit(bottom_backward)
        self.state = init_half(bottom, jax.random.PRNGKey(seed + node_id), self.opt)
        self.x, self.y = x, y
        self.batch_size = batch_size
        self.next_node = next_node
        self.active_at_start = active
        self.batch_idx = 0
        # ring budget: each epoch a client runs consumes one hop; the
        # token retires when its countdown reaches zero (replaces the
        # reference's MAX_EPOCH_PER_NODE bookkeeping, client.py:16)
        self.hops_left = total_hops
        self._acts_template = None
        self._cur_x = None
        super().__init__(backend)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_TYPE_S2C_SEND_GRADS, self._on_grads)
        self.register_message_receive_handler(MSG_TYPE_C2C_SEMAPHORE, self._on_token)

    def start_if_active(self):
        if self.active_at_start:
            self._send_next_batch()

    def _on_token(self, msg: Message):
        self.hops_left = msg.get("hops_left")
        if self.hops_left <= 0:
            # retire flood: finish, and keep it circling until it reaches
            # the node that originated it — so every ring member AND the
            # server get a shutdown, not just the first retiree (a real
            # two-process deployment would otherwise hang on readline)
            origin = msg.get("origin")
            if self.next_node != origin:
                self._forward_retire(origin)
            self.finish()
            return
        self.batch_idx = 0
        self._send_next_batch()

    def _forward_retire(self, origin: int):
        token = Message(MSG_TYPE_C2C_SEMAPHORE, self.backend.node_id, self.next_node)
        token.add_params("hops_left", 0)
        token.add_params("origin", origin)
        self.send_message(token)

    def _send_next_batch(self):
        lo = self.batch_idx * self.batch_size
        if lo + self.batch_size > len(self.x):
            hops = self.hops_left - 1
            if hops <= 0:
                # token budget spent: tell the server, start the retire
                # flood around the ring, then finish ourselves
                self.send_message(
                    Message(MSG_TYPE_S2C_FINISH, self.backend.node_id, SERVER)
                )
                if self.next_node != self.backend.node_id:
                    self._forward_retire(self.backend.node_id)
                self.finish()
                return
            # epoch done: pass the ring token (client_manager.py:72-74)
            token = Message(
                MSG_TYPE_C2C_SEMAPHORE, self.backend.node_id, self.next_node
            )
            token.add_params("hops_left", hops)
            self.send_message(token)
            return
        sl = slice(lo, lo + self.batch_size)
        self._cur_x = jnp.asarray(self.x[sl])
        acts = self._fwd(self.state, self._cur_x)
        self._acts_template = acts
        m = Message(MSG_TYPE_C2S_SEND_ACTS, self.backend.node_id, SERVER)
        m.add_params("acts", tree_to_wire(acts))
        m.add_params("labels", np.asarray(self.y[sl]).tolist())
        self.send_message(m)

    def _on_grads(self, msg: Message):
        gacts = jnp.asarray(tree_from_wire(msg.get("grads"), self._acts_template))
        self.state = self._bwd(self.state, self._cur_x, gacts)
        self.batch_idx += 1
        self._send_next_batch()


class _Identity:
    """Placeholder half for managers that only own one side."""

    class module:  # noqa: N801 — duck-typed ModelBundle.module
        @staticmethod
        def apply(variables, x, train=False):
            return x


_DUMMY_BOTTOM = _Identity()
_DUMMY_TOP = _Identity()
