"""FedNAS — federated DARTS architecture search, TPU-native.

Reference (SURVEY.md §2.2 row 14): clients alternate architecture
(alpha) and weight optimization per batch — ``Architect.step_v2``
updates alphas with grad = ∇α L_valid + λ_train·∇α L_train
(``darts/architect.py:58-103``), then SGD steps the weights with
grad-clip 5 (``FedNASTrainer.local_search``, ``FedNASTrainer.py:82-115``);
the server averages BOTH weights and alphas sample-weighted
(``FedNASAggregator.py:56-87``).  Two stages: ``search`` then ``train``
on the derived genotype (``main_fednas.py:44-45``).

TPU-native: one jitted round = lax.map over the packed client axis;
each client runs a scan over (epochs × batches) where every step does
the alpha Adam update followed by the weight SGD update — the bilevel
alternation becomes two value_and_grads inside one fused step, no
Python in the loop.  Weights and alphas are separate pytrees (see
``models/darts/search.py``), so "average both" is the same masked
weighted tree-mean applied twice.  The train stage reuses the FedAvg
engine on the fixed network — no duplicated round logic.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import FedAvgConfig, FedAvgSimulation
from fedml_tpu.core.losses import masked_softmax_ce
from fedml_tpu.core.types import FedDataset, batch_eval_pack, cohort_steps_per_epoch, pack_clients
from fedml_tpu.models.darts.genotypes import Genotype, genotype_from_alphas
from fedml_tpu.models.darts.network import darts_network
from fedml_tpu.models.darts.search import SearchBundle

PyTree = Any


@dataclasses.dataclass
class FedNASConfig:
    num_clients: int = 4
    clients_per_round: int = 4
    comm_rounds: int = 5
    epochs: int = 1               # local search epochs per round
    batch_size: int = 8
    lr: float = 0.025             # weight SGD (reference --learning_rate)
    lr_min: float = 0.001         # cosine floor (reference --learning_rate_min)
    momentum: float = 0.9
    weight_decay: float = 3e-4    # reference --weight_decay
    grad_clip: float = 5.0        # reference --grad_clip
    arch_lr: float = 3e-4         # reference --arch_learning_rate
    arch_weight_decay: float = 1e-3
    lambda_train_regularizer: float = 1.0   # reference --lambda_train_regularizer
    # 1 = the reference FedNAS client's Architect.step_v2 alternation
    # (∇α L_val + λ·∇α L_train); 2 = the DARTS second-order UNROLLED
    # architect (``darts/architect.py:32-93``): the alpha gradient of
    # the validation loss at w' = one SGD(+momentum+wd) step of the
    # train loss — exact here via jax.grad through the unrolled step,
    # where the reference needs a finite-difference Hessian-vector
    # approximation (eq. 8) because torch can't differentiate through
    # its optimizer's in-place update
    arch_order: int = 1
    seed: int = 0


def cosine_epoch_schedule(lr: float, lr_min: float, epochs: int,
                          steps_per_epoch: int):
    """The reference's weight-LR schedule, exactly: a FRESH
    ``CosineAnnealingLR(T_max=epochs, eta_min=learning_rate_min)`` per
    round, stepped once per local EPOCH (``FedNASTrainer.py:52-72``) —
    the LR is constant within an epoch, and the optimizer state (and
    hence the step count) resets every round, so the two schedules
    align: ``lr_e = lr_min + (lr - lr_min) (1 + cos(pi e / E)) / 2``.
    """
    if epochs <= 1:
        return lr  # the scheduler never steps within a 1-epoch session

    def schedule(count):
        epoch = jnp.minimum(count // steps_per_epoch, epochs)
        return lr_min + 0.5 * (lr - lr_min) * (
            1.0 + jnp.cos(jnp.pi * epoch / epochs)
        )

    return schedule


def darts_unrolled_alpha_grad(train_loss_fn, val_loss_fn, params, alphas,
                              *, eta, momentum=0.0, weight_decay=0.0,
                              buf=None):
    """Second-order DARTS architect gradient (paper eq. 7; reference
    ``darts/architect.py:32-93``): ∇α L_val(w′(α), α) with

        w′(α) = w − η · (momentum·buf + ∇w L_train(w, α) + wd·w)

    — the reference's ``_compute_unrolled_model`` exactly.  The total
    derivative (the direct ∇α term plus the implicit
    −η·∇²αw L_train·∇w′ L_val term, which the reference approximates by
    central finite differences around w ± R·∇w′L_val,
    ``_hessian_vector_product:229-246``) is one exact ``jax.grad``
    through the unrolled step — no Hessian-vector approximation and no
    ``_construct_model_from_theta`` flatten/unflatten gymnastics.

    ``train_loss_fn(params, alphas)`` and ``val_loss_fn(params, alphas)``
    must be scalar-valued; ``buf`` is the weight optimizer's momentum
    buffer pytree (None = zeros, torch's fresh-optimizer except-path).
    """
    if buf is None:
        buf = jax.tree_util.tree_map(jnp.zeros_like, params)

    def val_at_unrolled(alphas_):
        gw = jax.grad(train_loss_fn)(params, alphas_)
        new_p = jax.tree_util.tree_map(
            lambda w, g, b: w - eta * (momentum * b + g + weight_decay * w),
            params, gw, buf,
        )
        return val_loss_fn(new_p, alphas_)

    return jax.grad(val_at_unrolled)(alphas)


class SearchState(NamedTuple):
    variables: PyTree    # network weights (+batch_stats)
    alphas: PyTree       # {"alphas_normal", "alphas_reduce"}
    round_idx: jax.Array
    key: jax.Array


class FedNASSearch:
    """Search-stage driver: federated bilevel optimization of
    (weights, alphas)."""

    def __init__(self, bundle: SearchBundle, dataset: FedDataset,
                 config: FedNASConfig):
        self.bundle = bundle
        self.ds = dataset
        self.cfg = config

        key = jax.random.PRNGKey(config.seed)
        self.state = SearchState(
            variables=bundle.init(key),
            alphas=bundle.init_alphas(jax.random.fold_in(key, 1)),
            round_idx=jnp.zeros((), jnp.int32),
            key=key,
        )
        self.steps = cohort_steps_per_epoch(dataset, config.batch_size)
        self._round_fn = jax.jit(self._build_round_fn())
        self._test_pack = batch_eval_pack(
            dataset.test_x, dataset.test_y, max(config.batch_size, 64)
        )
        self._eval_fn = jax.jit(self._build_eval())
        self.history = []

    def _build_round_fn(self):
        from fedml_tpu.core.client import make_client_optimizer

        cfg = self.cfg
        if cfg.arch_order not in (1, 2):
            raise ValueError(f"arch_order must be 1 or 2, got "
                             f"{cfg.arch_order}")
        bundle = self.bundle
        sched = cosine_epoch_schedule(cfg.lr, cfg.lr_min, cfg.epochs,
                                      self.steps)
        w_opt = make_client_optimizer(
            "sgd", sched,
            momentum=cfg.momentum,
            weight_decay=cfg.weight_decay, grad_clip=cfg.grad_clip,
        )
        # reference Architect: Adam(arch_lr, betas=(0.5, 0.999), wd)
        a_opt = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.add_decayed_weights(cfg.arch_weight_decay),
            optax.adam(cfg.arch_lr, b1=0.5, b2=0.999),
        )

        def w_loss(params, others, alphas, bx, by, bm):
            variables = {**others, "params": params}
            logits, new_vars = bundle.apply_train(variables, alphas, bx)
            loss, aux = masked_softmax_ce(logits, by, bm)
            return loss, (new_vars, aux)

        def a_loss(alphas, variables, bx, by, bm):
            logits, _ = bundle.apply_train(variables, alphas, bx)
            loss, _ = masked_softmax_ce(logits, by, bm)
            return loss

        w_grad = jax.value_and_grad(w_loss, has_aux=True)
        a_grad = jax.grad(a_loss)

        def _state_leaf(state, typ):
            """First optimizer-state node of the given optax type (the
            momentum TraceState / schedule ScaleByScheduleState inside
            the w_opt chain), or None."""
            for leaf in jax.tree_util.tree_leaves(
                state, is_leaf=lambda n: isinstance(n, typ)
            ):
                if isinstance(leaf, typ):
                    return leaf
            return None

        def unrolled_alpha_grad(alphas, variables, w_state,
                                bx, by, bm, bvx, bvy, bvm):
            """The module-level ``darts_unrolled_alpha_grad`` wired to
            this round's batches: η is the schedule's CURRENT value and
            buf the live momentum buffer, both read from the w_opt
            state (torch reads the same two from its network optimizer,
            ``architect.py:37-42``)."""
            params = variables["params"]
            others = {k: v for k, v in variables.items() if k != "params"}
            trace = _state_leaf(w_state, optax.TraceState)
            cnt = _state_leaf(w_state, optax.ScaleByScheduleState)
            eta = (sched(cnt.count) if callable(sched) and cnt is not None
                   else (cfg.lr if callable(sched) else sched))
            return darts_unrolled_alpha_grad(
                lambda p, a: w_loss(p, others, a, bx, by, bm)[0],
                lambda p, a: a_loss(a, {**others, "params": p},
                                    bvx, bvy, bvm),
                params, alphas,
                eta=eta, momentum=cfg.momentum,
                weight_decay=cfg.weight_decay,
                buf=trace.trace if trace is not None else None,
            )

        def one_client(variables, alphas, x, y, m, vx, vy, vm):
            n_valid = vx.shape[0]
            w_state = w_opt.init(variables["params"])
            a_state = a_opt.init(alphas)

            def step(carry, batch):
                variables, alphas, w_state, a_state = carry
                bx, by, bm, bi = batch
                # a random-with-replacement valid batch per train step
                # (reference local_search: next(iter(valid_queue)))
                vi = bi % n_valid
                bvx, bvy, bvm = vx[vi], vy[vi], vm[vi]
                old_alphas = alphas
                if cfg.arch_order == 2:
                    # unrolled second-order architect (see above)
                    g = unrolled_alpha_grad(alphas, variables, w_state,
                                            bx, by, bm, bvx, bvy, bvm)
                else:
                    # architect step_v2: g_val + λ_train · g_train
                    g_train = a_grad(alphas, variables, bx, by, bm)
                    g_val = a_grad(alphas, variables, bvx, bvy, bvm)
                    g = jax.tree_util.tree_map(
                        lambda gv, gt: gv + cfg.lambda_train_regularizer * gt,
                        g_val, g_train,
                    )
                a_up, a_state = a_opt.update(g, a_state, alphas)
                alphas = optax.apply_updates(alphas, a_up)
                # weight step
                others = {k: v for k, v in variables.items() if k != "params"}
                (_, (new_vars, aux)), gw = w_grad(
                    variables["params"], others, alphas, bx, by, bm
                )
                w_up, w_state = w_opt.update(gw, w_state, variables["params"])
                params = optax.apply_updates(variables["params"], w_up)
                # pad-only batches must leave weights AND alphas untouched
                has_real = (bm.sum() > 0).astype(jnp.float32)
                params, alphas = jax.tree_util.tree_map(
                    lambda n, o: has_real * n + (1 - has_real) * o,
                    (params, alphas), (variables["params"], old_alphas),
                )
                return ({**new_vars, "params": params}, alphas, w_state,
                        a_state), aux

            def epoch(carry, _):
                return jax.lax.scan(
                    step, carry, (x, y, m, jnp.arange(x.shape[0]))
                )

            (variables, alphas, _, _), auxs = jax.lax.scan(
                epoch, (variables, alphas, w_state, a_state),
                jnp.arange(cfg.epochs),
            )
            metrics = {k: v[-1].sum() for k, v in auxs.items()}
            return variables, alphas, metrics

        def round_fn(state: SearchState, x, y, m, vx, vy, vm, num_samples):
            c_vars, c_alphas, c_metrics = jax.lax.map(
                lambda a: one_client(state.variables, state.alphas, *a),
                (x, y, m, vx, vy, vm),
            )
            w = num_samples / jnp.maximum(num_samples.sum(), 1e-12)
            avg = lambda tree: jax.tree_util.tree_map(
                lambda leaf: jnp.einsum(
                    "k,k...->...", w, leaf.astype(jnp.float32)
                ).astype(leaf.dtype),
                tree,
            )
            new_state = SearchState(
                variables=avg(c_vars),
                alphas=avg(c_alphas),
                round_idx=state.round_idx + 1,
                key=state.key,
            )
            metrics = {k: v.sum() for k, v in c_metrics.items()}
            return new_state, metrics

        return round_fn

    def _build_eval(self):
        def evaluate(variables, alphas, x, y, m):
            def body(_, batch):
                bx, by, bm = batch
                logits = self.bundle.apply_eval(variables, alphas, bx)
                _, aux = masked_softmax_ce(logits, by, bm)
                return (), aux

            _, auxs = jax.lax.scan(body, (), (x, y, m))
            return {k: v.sum() for k, v in auxs.items()}

        return evaluate

    def run_round(self) -> dict:
        cfg = self.cfg
        round_idx = int(self.state.round_idx)
        if cfg.clients_per_round < cfg.num_clients:
            rng = np.random.RandomState(cfg.seed * 100003 + round_idx)
            ids = sorted(rng.choice(cfg.num_clients, cfg.clients_per_round,
                                    replace=False).tolist())
        else:
            ids = list(range(cfg.num_clients))
        pack = pack_clients(self.ds, ids, cfg.batch_size,
                            steps_per_epoch=self.steps,
                            seed=cfg.seed + round_idx)
        # architect's valid queue = the client's local test shard
        # (reference passes test_local as valid_queue); fall back to the
        # client's train shard when no per-client test partition exists
        if self.ds.test_client_idx is not None:
            vds = FedDataset(
                train_x=self.ds.test_x, train_y=self.ds.test_y,
                test_x=self.ds.test_x, test_y=self.ds.test_y,
                train_client_idx=self.ds.test_client_idx,
                test_client_idx=None, num_classes=self.ds.num_classes,
            )
            vpack = pack_clients(vds, ids, cfg.batch_size,
                                 seed=cfg.seed + round_idx + 7)
        else:
            vpack = pack_clients(self.ds, ids, cfg.batch_size,
                                 steps_per_epoch=self.steps,
                                 seed=cfg.seed + round_idx + 7)
        self.state, metrics = self._round_fn(
            self.state,
            jnp.asarray(pack.x), jnp.asarray(pack.y), jnp.asarray(pack.mask),
            jnp.asarray(vpack.x), jnp.asarray(vpack.y), jnp.asarray(vpack.mask),
            jnp.asarray(pack.num_samples),
        )
        out = {"round": round_idx,
               **{k: float(v) for k, v in metrics.items()}}
        if out.get("count", 0) > 0:
            out["train_acc"] = out["correct"] / out["count"]
        return out

    def evaluate_global(self) -> dict:
        x, y, m = self._test_pack
        res = self._eval_fn(self.state.variables, self.state.alphas,
                            jnp.asarray(x), jnp.asarray(y), jnp.asarray(m))
        c = max(float(res["count"]), 1.0)
        return {"test_acc": float(res["correct"]) / c,
                "test_loss": float(res["loss_sum"]) / c}

    def genotype(self) -> Genotype:
        """Discrete architecture from the aggregated alphas
        (reference ``model_search.py:258-297``)."""
        return genotype_from_alphas(
            np.asarray(self.state.alphas["alphas_normal"]),
            np.asarray(self.state.alphas["alphas_reduce"]),
            steps=self.bundle.module.steps,
            multiplier=self.bundle.module.multiplier,
        )

    def run(self, rounds: Optional[int] = None) -> list:
        for _ in range(rounds if rounds is not None else self.cfg.comm_rounds):
            self.history.append(self.run_round())
        self.history[-1].update(self.evaluate_global())
        return self.history


def fednas_train_stage(
    genotype: Genotype, dataset: FedDataset, config: FedAvgConfig,
    *, C: int = 36, layers: int = 20, image_size: int = 32,
    in_channels: int = 3, lr_min: float = 0.001, metrics=None,
) -> FedAvgSimulation:
    """Stage 2 (``--stage train``): plain federated training of the fixed
    network — the FedAvg engine on the derived genotype.

    The reference's train stage uses the SAME fresh per-round
    ``CosineAnnealingLR(T_max=epochs, eta_min=learning_rate_min)`` as the
    search stage (``FedNASTrainer.py:141-155``), so the weight optimizer
    gets the per-epoch cosine schedule here too (constant lr when
    epochs == 1, where the reference scheduler never steps).
    """
    bundle = darts_network(genotype, C=C, num_classes=dataset.num_classes,
                           layers=layers, image_size=image_size,
                           in_channels=in_channels)
    schedule = cosine_epoch_schedule(
        config.lr, lr_min, config.epochs,
        cohort_steps_per_epoch(dataset, config.batch_size),
    )
    # client_lr override: every other FedAvgConfig knob (prox_mu,
    # grad_clip, compute_dtype, ...) keeps applying
    return FedAvgSimulation(bundle, dataset, config, client_lr=schedule,
                            metrics=metrics)
