"""TurboAggregate — secure aggregation over a finite field.

Reference (SURVEY.md §2 rows 18/26): clients quantize model updates
into a prime field and aggregate through secret sharing so the server
never sees an individual update — additive sharing + Lagrange-coded
(LCC) redundancy against stragglers
(``turboaggregate/mpc_function.py``, ``TA_Aggregator.py:56-87``,
``TA_decentralized_worker_manager.py:8-55``).

TPU-native split of labor (see ``fedml_tpu.core.mpc``): share
generation / recombination are int64 field kernels under jit; Lagrange
coefficient generation is exact host integer math; local training is
the same compiled client operator every other algorithm uses.  The
exactness oracle: secure aggregation must reproduce the plain FedAvg
sample-weighted average to quantization precision (< 2⁻¹⁵ per
element), tested in ``tests/test_turboaggregate.py``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core import mpc
from fedml_tpu.core import tree as treelib
from fedml_tpu.core.client import make_client_optimizer, make_evaluator, make_local_update
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.types import FedDataset, batch_eval_pack, cohort_steps_per_epoch, pack_clients
from fedml_tpu.models.base import ModelBundle


def secure_weighted_sum(
    vectors: Sequence[np.ndarray],
    weights: Sequence[float],
    key: jax.Array,
    *,
    scale: float = 2.0 ** 16,
    p: int = mpc.DEFAULT_PRIME,
) -> np.ndarray:
    """Σ wᵢ·vᵢ via additive secret sharing — the server only ever sees
    per-holder share sums, never an individual client's vector.

    Protocol (reference ``Gen_Additive_SS`` + ``TA_Aggregator``):
    client i quantizes wᵢ·vᵢ into the field and splits it into N
    additive shares, sending share j to holder j; each holder sums what
    it received (a field op on N meaningless-alone residues); the sum
    of holder sums is exactly Σ quant(wᵢ·vᵢ) mod p.
    """
    n = len(vectors)
    holder_sums = None
    for i, (v, w) in enumerate(zip(vectors, weights)):
        q = mpc.quantize(np.asarray(v, np.float64) * float(w), scale, p)
        shares = mpc.additive_shares(q, n, jax.random.fold_in(key, i), p)
        holder_sums = shares if holder_sums is None else mpc.field_sum(
            jnp.stack([jnp.asarray(holder_sums), jnp.asarray(shares)]), p
        )
    total = mpc.field_sum(holder_sums, p)
    return mpc.dequantize(np.asarray(total), scale, p)


def lcc_coded_sum(
    vectors: Sequence[np.ndarray],
    key: jax.Array,
    *,
    k: int = 2,
    t: int = 1,
    drop: Sequence[int] = (),
    scale: float = 2.0 ** 16,
    p: int = mpc.DEFAULT_PRIME,
) -> np.ndarray:
    """Straggler-resilient sum: each client LCC-encodes its quantized
    vector to N shares (K data chunks + T random); the server sums the
    surviving workers' shares in the field and decodes from any K+T
    points — dropped workers (``drop``) cost nothing
    (reference ``LCC_encoding/LCC_decoding``)."""
    n = len(vectors)
    d = vectors[0].size
    pad = (-d) % k
    enc = []
    for i, v in enumerate(vectors):
        q = mpc.quantize(np.pad(np.asarray(v, np.float64).ravel(), (0, pad)), scale, p)
        enc.append(np.asarray(mpc.lcc_encode(q, n, k, t, jax.random.fold_in(key, i), p)))
    # each worker j holds Σ_i enc_i[j] — computable without seeing any v_i
    share_sum = mpc.field_sum(np.stack(enc, axis=0), p)  # [n, d/k]
    alive = [j for j in range(n) if j not in set(drop)]
    need = k + t  # decode degree: interpolation through K+T points
    if len(alive) < need:
        raise ValueError(f"too many stragglers: {len(alive)} < {need}")
    use = alive[:need]
    # interpolating through K+T α-points recovers all K+T chunk rows of
    # the SUMMED polynomial; the first K rows are the data chunks
    decoded = np.asarray(mpc.lcc_decode(np.asarray(share_sum)[use], use, n, k + t, p))
    return mpc.dequantize(decoded[: d + pad], scale, p)[:d]


@dataclasses.dataclass
class TurboAggregateConfig:
    num_clients: int = 8
    comm_rounds: int = 5
    epochs: int = 1
    batch_size: int = 10
    lr: float = 0.03
    scale: float = 2.0 ** 16
    seed: int = 0


class TurboAggregateSimulation:
    """FedAvg with the aggregation replaced by the secure path: clients
    train with the shared compiled local-update operator; their weighted
    deltas travel as additive shares; the server reconstructs only the
    aggregate (reference ``TA_Aggregator.aggregate``, semantics of
    FedAvg's sample-weighted average)."""

    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: TurboAggregateConfig,
        *,
        loss_fn: LossFn = masked_softmax_ce,
    ):
        self.bundle = bundle
        self.dataset = dataset
        self.cfg = config
        opt = make_client_optimizer("sgd", config.lr)
        local = make_local_update(bundle, opt, config.epochs, loss_fn)
        self._local = jax.jit(
            lambda v, x, y, m, r: jax.lax.map(
                lambda a: local(v, *a), (x, y, m, r)
            )
        )
        self.evaluator = make_evaluator(bundle, loss_fn)
        key = jax.random.PRNGKey(config.seed)
        self.variables = bundle.init(key)
        self.key = key
        self.steps_per_epoch = cohort_steps_per_epoch(dataset, config.batch_size)
        self._test_pack = batch_eval_pack(dataset.test_x, dataset.test_y, 64)
        self.round_idx = 0
        self.history: List[dict] = []
        self._pack_cache = None

    def _device_pack(self):
        """Device-resident full-cohort block, packed once (round
        stochasticity is the on-device per-epoch permutation keyed per
        round — see FedAvgSimulation._device_pack)."""
        if self._pack_cache is None:
            from fedml_tpu.core.types import device_resident_pack

            args, host_ns = device_resident_pack(
                self.dataset, np.arange(self.cfg.num_clients),
                self.cfg.batch_size,
                steps_per_epoch=self.steps_per_epoch, seed=self.cfg.seed,
            )
            # host-side num_samples: the secure-aggregation weights are
            # computed on host every round — no device readback
            self._pack_cache = (args[:3], host_ns)
        return self._pack_cache

    def run_round(self) -> dict:
        cfg = self.cfg
        ids = np.arange(cfg.num_clients)
        (px, py, pm), host_ns = self._device_pack()
        k_round = jax.random.fold_in(jax.random.fold_in(self.key, self.round_idx), 0)
        rngs = jax.vmap(lambda i: jax.random.fold_in(k_round, i))(
            jnp.asarray(ids, jnp.int32)
        )
        client_vars, metrics = self._local(
            self.variables, px, py, pm, rngs,
        )
        # secure aggregation of the weighted client models (host protocol)
        weights = np.asarray(host_ns, np.float64)
        weights = weights / weights.sum()
        vecs = [
            np.asarray(treelib.tree_ravel(treelib.tree_index(client_vars, i)))
            for i in range(cfg.num_clients)
        ]
        agg_key = jax.random.fold_in(jax.random.fold_in(self.key, self.round_idx), 1)
        summed = secure_weighted_sum(vecs, weights, agg_key, scale=cfg.scale)
        self.variables = treelib.tree_unravel(
            self.variables, jnp.asarray(summed, jnp.float32)
        )
        out = {k: float(v.sum()) for k, v in metrics.items()}
        out["round"] = self.round_idx
        if out.get("count", 0) > 0:
            out["train_acc"] = out["correct"] / out["count"]
        self.round_idx += 1
        self.history.append(out)
        return out

    def evaluate_global(self) -> dict:
        x, y, m = self._test_pack
        res = self.evaluator(self.variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m))
        count = float(res["count"])
        return {
            "test_acc": float(res["correct"]) / count,
            "test_loss": float(res["loss_sum"]) / count,
        }
