"""Decentralized (serverless) gossip FL.

Reference ``fedml_api/distributed/decentralized_framework/``
(``decentralized_worker_manager.py:29-46``): each worker trains locally,
pushes its result to out-neighbors from the topology, and aggregates
when all in-neighbors' results arrived.  The message choreography
disappears on TPU: one gossip round is

    local updates on every client's OWN model (persistent, not reset to
    a global model)  →  mixing step  P ← W·P  with the row-stochastic
    topology matrix W.

Mixing is a single einsum over the stacked client axis (dense W), or —
for the ring topology on an ICI ring — two ``lax.ppermute`` shifts,
which is the sparse-neighbor-exchange design SURVEY.md §2.6 calls for.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.client import make_client_optimizer, make_evaluator, make_local_update
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.types import FedDataset, batch_eval_pack, cohort_steps_per_epoch, pack_clients
from fedml_tpu.models.base import ModelBundle

PyTree = Any


def dense_mix(stacked_vars: PyTree, w: jax.Array) -> PyTree:
    """P ← W·P for every leaf with client leading axis."""
    return jax.tree_util.tree_map(
        lambda leaf: jnp.einsum("ij,j...->i...", w, leaf.astype(jnp.float32)).astype(
            leaf.dtype
        ),
        stacked_vars,
    )


def ring_mix(local_vars: PyTree, axis_name: str, w_self=1 / 3, w_left=1 / 3, w_right=1 / 3):
    """Ring mixing via two ppermutes over a mesh axis (one client/device)."""
    # lax.psum of a constant is the static axis size on every
    # supported jax (lax.axis_size only exists on new jax)
    n = jax.lax.psum(1, axis_name)
    left = [(i, (i + 1) % n) for i in range(n)]
    right = [(i, (i - 1) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda leaf: (
            w_self * leaf.astype(jnp.float32)
            + w_left * jax.lax.ppermute(leaf.astype(jnp.float32), axis_name, left)
            + w_right * jax.lax.ppermute(leaf.astype(jnp.float32), axis_name, right)
        ).astype(leaf.dtype),
        local_vars,
    )


def make_gossip_round_fn(
    local_update,
    mixing_matrix: Optional[np.ndarray] = None,
    *,
    axis_name: Optional[str] = None,
    ring: bool = False,
):
    """Round over stacked per-client variables [K, ...].

    Simulation: dense ``mixing_matrix`` einsum.  SPMD (axis_name set,
    one client per device): ``ring=True`` uses ppermute; otherwise the
    dense matrix is applied via all_gather + einsum.
    """
    if mixing_matrix is None and not (axis_name and ring):
        raise ValueError(
            "make_gossip_round_fn: a mixing_matrix is required unless "
            "using the SPMD ring path (axis_name=..., ring=True)"
        )
    if mixing_matrix is not None:
        w_const = jnp.asarray(mixing_matrix, jnp.float32)

    def round_fn(stacked_vars, x, y, mask, rng, slot_ids):
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(slot_ids)
        new_vars, metrics = jax.lax.map(
            lambda a: local_update({k: v for k, v in a[0].items()}, *a[1:]),
            (stacked_vars, x, y, mask, rngs),
        )
        if axis_name is None:
            mixed = dense_mix(new_vars, w_const)
        elif ring:
            # one client per device: drop the singleton pack axis, mix, restore
            squeezed = jax.tree_util.tree_map(lambda l: l[0], new_vars)
            mixed = ring_mix(squeezed, axis_name)
            mixed = jax.tree_util.tree_map(lambda l: l[None], mixed)
        else:
            gathered = jax.tree_util.tree_map(
                lambda l: jax.lax.all_gather(l, axis_name, tiled=True), new_vars
            )
            row = jax.lax.axis_index(axis_name)
            mixed = jax.tree_util.tree_map(
                lambda g: jnp.einsum(
                    "j,j...->...", w_const[row], g.astype(jnp.float32)
                ).astype(g.dtype)[None],
                gathered,
            )
        metrics = {k: v.sum() for k, v in metrics.items()}
        return mixed, metrics

    return round_fn


class DecentralizedSimulation:
    """Single-process gossip driver (reference decentralized demo +
    ``standalone/decentralized`` DSGD)."""

    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        mixing_matrix: np.ndarray,
        *,
        epochs: int = 1,
        batch_size: int = 20,
        lr: float = 0.1,
        momentum: float = 0.0,
        loss_fn: LossFn = masked_softmax_ce,
        seed: int = 0,
    ):
        self.bundle = bundle
        self.dataset = dataset
        self.w = np.asarray(mixing_matrix)
        self.num_clients = self.w.shape[0]
        assert dataset.num_clients == self.num_clients
        opt = make_client_optimizer("sgd", lr, momentum=momentum)
        self.local_update = make_local_update(bundle, opt, epochs, loss_fn)
        self.round_fn = jax.jit(
            make_gossip_round_fn(self.local_update, self.w)
        )
        self.evaluator = make_evaluator(bundle, loss_fn)
        key = jax.random.PRNGKey(seed)
        init = bundle.init(key)
        # every worker starts from the same init (reference behavior)
        self.stacked_vars = jax.tree_util.tree_map(
            lambda l: jnp.stack([l] * self.num_clients), init
        )
        self.key = key
        self.seed = seed
        self.batch_size = batch_size
        self.steps_per_epoch = cohort_steps_per_epoch(dataset, batch_size)
        self._test_pack = batch_eval_pack(dataset.test_x, dataset.test_y, 64)
        self.round_idx = 0
        self.history = []
        self._pack_cache = None

    def _device_pack(self):
        """Device-resident full-cohort block (every worker trains every
        round): packed once with a round-independent seed — per-round
        stochasticity comes from the on-device per-epoch permutation
        keyed by fold_in(key, round) (FedAvgSimulation._device_pack has
        the full rationale and the measured transfer cost)."""
        if self._pack_cache is None:
            from fedml_tpu.core.types import device_resident_pack

            args, _ = device_resident_pack(
                self.dataset, np.arange(self.num_clients), self.batch_size,
                steps_per_epoch=self.steps_per_epoch, seed=self.seed,
            )
            self._pack_cache = args[:3]  # gossip weights are uniform
        return self._pack_cache

    def run_round(self) -> dict:
        ids = np.arange(self.num_clients)
        px, py, pm = self._device_pack()
        self.stacked_vars, metrics = self.round_fn(
            self.stacked_vars,
            px, py, pm,
            jax.random.fold_in(self.key, self.round_idx),
            jnp.asarray(ids, jnp.int32),
        )
        out = {k: float(v) for k, v in metrics.items()}
        out["round"] = self.round_idx
        if out.get("count", 0) > 0:
            out["train_acc"] = out["correct"] / out["count"]
            out["train_loss"] = out["loss_sum"] / out["count"]
        self.round_idx += 1
        self.history.append(out)
        return out

    def evaluate_worker(self, worker: int) -> dict:
        x, y, m = self._test_pack
        variables = jax.tree_util.tree_map(lambda l: l[worker], self.stacked_vars)
        res = self.evaluator(variables, jnp.asarray(x), jnp.asarray(y), jnp.asarray(m))
        c = float(res["count"])
        return {
            "test_acc": float(res["correct"]) / max(c, 1.0),
            "test_loss": float(res["loss_sum"]) / max(c, 1.0),
        }

    def consensus_distance(self) -> float:
        """Mean squared distance of workers' params from their average —
        the convergence diagnostic for gossip."""
        mean = jax.tree_util.tree_map(
            lambda l: l.mean(axis=0, keepdims=True), self.stacked_vars
        )
        d = jax.tree_util.tree_map(
            lambda l, m: jnp.sum(jnp.square(l - m)), self.stacked_vars, mean
        )
        return float(sum(jax.tree_util.tree_leaves(d))) / self.num_clients

    def run(self, rounds: int, log_fn=None) -> list:
        for _ in range(rounds):
            m = self.run_round()
            if log_fn:
                log_fn(m)
        return self.history
