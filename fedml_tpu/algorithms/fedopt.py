"""FedOpt — FedAvg with a server-side optimizer.

Reference mechanism (``fedml_api/distributed/fedopt/FedOptAggregator.py:14-118``):
the aggregated model is turned into a pseudo-gradient
``grad = w_global − w_avg`` injected into ``parameter.grad``
(``set_model_global_grads``, ``:110-118``) and an arbitrary torch
optimizer steps on it.  TPU-natively the server update is an optax
transform applied to the psum'd delta inside the same compiled round —
no host round-trip (SURVEY.md §7 "hard parts": server opt state stays
replicated on device).

BatchNorm statistics are not optimizer state: the server optimizer steps
on ``params`` only; ``batch_stats`` (if any) take the plain weighted
average, matching the reference where ``optimizer.step`` only touches
parameters while the averaged state_dict supplies buffers.
"""

from __future__ import annotations

from typing import Any

import optax

from fedml_tpu.algorithms.fedavg import (
    FedAvgConfig,
    FedAvgSimulation,
    ServerUpdateFn,
)
from fedml_tpu.core import tree as treelib
from fedml_tpu.core.losses import LossFn, masked_softmax_ce
from fedml_tpu.core.optrepo import get_server_optimizer
from fedml_tpu.core.types import FedDataset
from fedml_tpu.models.base import ModelBundle

PyTree = Any


def make_fedopt_server_update(
    server_opt: optax.GradientTransformation,
) -> ServerUpdateFn:
    def server_update(old, agg, opt_state):
        # pseudo-gradient: Δ = w_global − w_avg (reference sign convention)
        pseudo_grad = treelib.tree_sub(old["params"], agg["params"])
        updates, new_opt_state = server_opt.update(
            pseudo_grad, opt_state, old["params"]
        )
        new_params = optax.apply_updates(old["params"], updates)
        new_vars = {**agg, "params": new_params}  # buffers from plain average
        return new_vars, new_opt_state

    return server_update


class FedOptSimulation(FedAvgSimulation):
    """FedAvg driver + server optimizer (``--server_optimizer/--server_lr``,
    reference ``main_fedopt.py:54-60``)."""

    def __init__(
        self,
        bundle: ModelBundle,
        dataset: FedDataset,
        config: FedAvgConfig,
        *,
        server_optimizer: str = "adam",
        server_lr: float = 1e-2,
        server_momentum: float = 0.9,
        loss_fn: LossFn = masked_softmax_ce,
        **kwargs,
    ):
        server_opt = get_server_optimizer(
            server_optimizer, lr=server_lr, momentum=server_momentum
        )
        super().__init__(
            bundle,
            dataset,
            config,
            loss_fn=loss_fn,
            server_update=make_fedopt_server_update(server_opt),
            server_opt_init=lambda variables: server_opt.init(variables["params"]),
            **kwargs,
        )
