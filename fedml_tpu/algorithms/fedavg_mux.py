"""Muxer-side FedAvg: N virtual clients, ONE process, ONE jit step.

The wire half of virtual-client multiplexing lives in ``comm/mux.py``
(hello v2, per-connection broadcast dedup, local demux).  This module
is the compute half: a cohort manager that turns the co-located sync
deliveries of one broadcast into ONE vmapped local update —
``jax.vmap`` over the SAME explicitly-vmappable ``make_local_update``
scan the simulation engine jits (``algorithms/fedavg.py``) — and then
uploads K codec-encoded deltas whose bytes are bit-identical to the
one-process-per-client path:

- each virtual client's ``client_idx``/``slot``/rng stream is derived
  exactly as ``FedAvgClientManager._on_sync`` derives them (client =
  node - 1, slot defaults to client_idx, rng =
  ``fold_in(fold_in(fold_in(seed_key, round), 0), slot)``);
- per-client packs are id-keyed (``pack_clients``), so a cohort pack's
  row k IS the single-client pack for that id;
- uploads go through the SHARED ``encode_client_upload``
  (``fedavg_cross_device``) with a per-virtual-client error-feedback
  store — encoded bytes are a pure function of (seed, round, slot).

Chaos/trace/obs parity: every virtual client sits behind its own
``VirtualNodeBackend`` (optionally chaos-wrapped per node), so
FaultRule decisions, trace hop chains, and telemetry identities match
the per-process topology — a drop rule for virtual node 3 drops only
node 3's sync copy, and node 3 simply isn't in that round's vmapped
cohort.

The same wrap is the MALICIOUS-MUXER attack surface
(``fedml_tpu/robust`` threat model): Byzantine FaultRules (``sign_flip``
/ ``scale_grad``) covering this muxer's virtual ids mutate every
co-located upload on its way out — one compromised process speaking for
a whole cohort through one connection, which is exactly what the
server's per-connection contribution caps exist to bound (the hub's
``conn_map`` introspection attributes all these uploads to THIS conn,
however many virtual identities ride it).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.comm.backend import CommBackend, NodeManager
from fedml_tpu.comm.message import (
    MSG_ARG_KEY_CLIENT_INDEX,
    MSG_ARG_KEY_LOCAL_METRICS,
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_ARG_KEY_ROUND_INDEX,
    MSG_TYPE_C2S_SEND_MODEL,
    MSG_TYPE_S2C_FINISH,
    MSG_TYPE_S2C_INIT_CONFIG,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
)
from fedml_tpu.algorithms.fedavg_cross_device import (
    MSG_ARG_KEY_CODEC,
    SERVER,
    encode_client_upload,
    ef_for,
    reconstruct_sync_model,
    request_resync,
)
from fedml_tpu.comm.mux import TcpMuxBackend
from fedml_tpu.core.client import LocalUpdateFn
from fedml_tpu.core.types import FedDataset, pack_clients
from fedml_tpu.obs import flight
from fedml_tpu.obs.telemetry import get_telemetry


class _VirtualEndpoint(NodeManager):
    """One virtual client's protocol endpoint: registers the standard
    client-side handlers on its (possibly chaos-wrapped) virtual
    backend and forwards into the shared cohort collector.  Keeping a
    real ``NodeManager`` per virtual node preserves the per-node
    handler-latency telemetry and the trace 'done' stamps the
    per-process topology emits."""

    def __init__(self, backend: CommBackend,
                 cohort: "FedAvgMuxClientManager"):
        self.cohort = cohort
        super().__init__(backend)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_FINISH, self._on_finish)

    def _on_sync(self, msg: Message) -> None:
        self.cohort._enqueue_sync(self.backend.node_id, msg)

    def _on_finish(self, msg: Message) -> None:
        self.cohort._on_finish(self.backend.node_id, msg)


class FedAvgMuxClientManager:
    """Drives every virtual client of one muxed connection.

    Collection contract: sync handlers only ENQUEUE (cheap — the trace
    'done' stamp marks delivery, not training); the mux backend's
    post-dispatch flush hook then trains everyone the physical frame
    reached in one vmapped step.  A sync arriving OUTSIDE a dispatch
    (a chaos-delayed copy re-injected from a timer thread) trains
    immediately as its own — possibly singleton — cohort: late copies
    behave like the late stragglers they are.

    Syncs carrying DIFFERENT payloads (a chaos-corrupted copy, mixed
    rounds after a delay) group by payload identity and train as
    separate cohorts: a NaN-corrupted sync NaN-poisons exactly its own
    virtual client's upload, which the server's corrupt-upload firewall
    then rejects — the same blast radius as the per-process path.
    """

    # reader-thread dispatch flushes vs chaos-timer-thread flushes:
    # the enqueue list rides its own lock; everything a flush TOUCHES
    # (pack cache, EF stores, digests) is serialized by _train_lock
    _GUARDED_BY = {
        "_pending": "_plock",
        "_bases": "_train_lock",
        "_pack_key": "_train_lock",
        "_pack_ids": "_train_lock",
        "_pack_index": "_train_lock",
        "_pack_host": "_train_lock",
        "_pack_dev": "_train_lock",
    }

    def __init__(
        self,
        mux: TcpMuxBackend,
        local_update: LocalUpdateFn,
        dataset: FedDataset,
        *,
        batch_size: int,
        template_variables,
        seed: int = 0,
        error_feedback: bool = True,
        train_delay: float = 0.0,
        crash_at_round: Optional[int] = None,
        wrap_backend: Optional[Callable[[CommBackend], CommBackend]] = None,
        rejoin_every_round: bool = False,
        traffic=None,
        mesh=None,
        partition_rules=None,
    ):
        self.mux = mux
        # open-loop traffic model (faults/traffic.TrafficModel): every
        # virtual client gets its own seeded per-round arrival decision
        # — offline (churn), per-upload delay (speed class + jitter +
        # heavy-tailed straggler), and a connection-level flap draw.
        # None = the closed-loop behavior, byte-identical to before.
        self.traffic = traffic
        self._last_traffic_round = -1
        # connection-churn soak knob: after every trained round this
        # muxer drops its hub connection (auto_reconnect re-dials and
        # re-helloes — the hub's rebind counters grow) AND forgets its
        # delta base cache, so the next delta broadcast finds a
        # rejoiner that must be walked back to a full model (resync).
        # Production muxers never set this; tools/fed_scale_run.py's
        # churn mode is its only caller.
        self.rejoin_every_round = bool(rejoin_every_round)
        # once-per-round guard for the churn rebind: a round's resync
        # walkback delivers per-node unicast fulls (one flush each),
        # and rebinding on every one of those would orphan the rest of
        # the walkback in the displaced connection's queues
        self._last_rebind_round = -1
        self.dataset = dataset
        self.batch_size = batch_size
        self.template = template_variables
        self.seed = seed
        self.error_feedback = error_feedback
        self.train_delay = train_delay
        self.crash_at_round = crash_at_round
        # ONE jit step for the whole cohort: vmap over the packed
        # client axis of the same local-update operator the sim engine
        # jits; the synced variables broadcast (in_axes=None), exactly
        # the sim's run_one closure.  jit re-specializes per cohort
        # size — constant in the steady state (every round's broadcast
        # reaches the same co-located subset), and chaos-induced
        # stragglers just compile their own (smaller) shape once.
        self._cohort_update = jax.jit(
            jax.vmap(local_update.fn, in_axes=(None, 0, 0, 0, 0))
        )
        # dp×mp mesh path (parallel/partition.py): the SAME vmapped
        # operator jitted with sharding annotations — cohort rows over
        # `dp`, the broadcast model laid out by the partition-rule
        # table over `mp`.  Per-row math is row-independent, so with
        # mp=1 the sharded step is BIT-identical to the plain one
        # (pinned by tests/test_shard_rules.py); mp>1 reassociates the
        # tensor-parallel matmul reductions, which changes bits but
        # not the model (tests/test_gspmd.py tolerance applies).
        self._mesh = mesh
        self._cohort_update_sharded = None
        if mesh is not None:
            from fedml_tpu.parallel.partition import (
                FEDLLM_RULES, cohort_shardings, jit_sharded, resolve_rules,
            )

            table = (resolve_rules(partition_rules)
                     if isinstance(partition_rules, str)
                     else (partition_rules or FEDLLM_RULES))
            var_in, data_sh, var_out, metrics_sh = cohort_shardings(
                mesh, template_variables, table
            )
            self._cohort_update_sharded = jit_sharded(
                jax.vmap(local_update.fn, in_axes=(None, 0, 0, 0, 0)),
                in_shardings=(var_in, data_sh, data_sh, data_sh, data_sh),
                out_shardings=(var_out, metrics_sh),
            )
            tel = get_telemetry()
            tel.gauge_set("shard.mesh_dp", int(mesh.shape["dp"]))
            tel.gauge_set("shard.mesh_mp", int(mesh.shape["mp"]))
        from fedml_tpu.analysis.locks import make_lock

        self._pending: List[tuple] = []
        self._plock = make_lock("FedAvgMuxClientManager._plock")
        # serializes whole flushes: the reader thread's dispatch flush
        # and a chaos-delayed copy's timer-thread flush share the pack
        # cache, the per-node EF stores, and the digest hashes — an
        # interleaved train would swap the pack out from under the
        # bigger cohort (or tear an EF residual) mid-step
        self._train_lock = make_lock("FedAvgMuxClientManager._train_lock")
        self._finished = threading.Event()
        # cohort pack cache: packs are round-independent (the local
        # update re-permutes per epoch from the (seed, round, slot)
        # stream) and per-client id-keyed, so row k of any cohort pack
        # is bit-identical to client k's single-client pack.  At 10k
        # virtual clients a per-round repack (10k seeded permutations
        # + a multi-MB gather) would dominate the round — so the cache
        # holds ONE pack covering the superset of ids seen (seeded
        # with this muxer's default client range) and every cohort —
        # including a per-round SAMPLED subset — row-slices it.  The
        # full-cohort steady state reuses the cached device arrays
        # with no per-round copy at all.
        self._pack_key = None        # (steps_per_epoch, batch_size)
        self._pack_ids = None        # ids the cached pack covers, in order
        self._pack_index = None     # client id -> row
        self._pack_host = None      # (x, y, mask, num_samples) numpy
        self._pack_dev = None       # full-cohort jnp arrays (fast path)
        # delta-broadcast base cache, shared by the whole co-located
        # cohort (chain models are globally identical): round -> OWNED
        # copy of the reconstructed model — same two contracts as the
        # per-process client's cache (in-window bases on hand; nothing
        # cached aliases a transport buffer)
        from collections import OrderedDict

        self._bases: "OrderedDict[int, object]" = OrderedDict()
        self._base_window = 4
        self._ef: Dict[int, object] = {}
        self._hash = {n: hashlib.sha256() for n in mux.node_ids}
        self.rounds_trained = {n: 0 for n in mux.node_ids}
        # in-band stats plane: ONE reporter per muxer process IS the
        # pre-merge — every virtual client shares this process registry,
        # so one digest frame per interval covers the whole co-located
        # cohort and the hub ingests one stream per CONNECTION (10k
        # virtual clients on 4 muxers = 4 digest streams, not 10k).
        # The entry point attaches it; FINISH stops it with a final
        # flush before the shared connection closes.
        self.stats_reporter = None
        self._endpoints: Dict[int, _VirtualEndpoint] = {}
        for n in mux.node_ids:
            vb = mux.virtual(n)
            backend = wrap_backend(vb) if wrap_backend is not None else vb
            self._endpoints[n] = _VirtualEndpoint(backend, self)
        mux.add_flush_hook(self._flush)

    # -- collection ---------------------------------------------------------
    def _enqueue_sync(self, node: int, msg: Message) -> None:
        with self._plock:
            self._pending.append((node, msg))
        if not self.mux.in_dispatch():
            # delayed/re-injected copy on a timer thread: no dispatch
            # flush is coming — train it now as its own cohort
            self._flush()

    def _on_finish(self, node: int, msg: Message) -> None:
        if not self._finished.is_set():
            self._finished.set()
            if self.stats_reporter is not None:
                self.stats_reporter.stop()  # final flush, conn still open
            self.mux.stop()

    def reporter_backend(self):
        """The backend a DigestReporter should send through: the PRIMARY
        virtual node's (possibly chaos-wrapped) endpoint, so a fault
        plan targeting that node's telemetry frames applies exactly as
        it would on a dedicated process."""
        return self._endpoints[self.mux.node_ids[0]].backend

    # -- cohort training ----------------------------------------------------
    def _flush(self) -> None:
        with self._train_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:  # fedlint: holds=_train_lock
        with self._plock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        batch_round = max(
            (m.get(MSG_ARG_KEY_ROUND_INDEX) for _, m in pending
             if m.get(MSG_ARG_KEY_ROUND_INDEX) is not None),
            default=None,
        )
        if (self.rejoin_every_round and not self._finished.is_set()
                and batch_round is not None
                and batch_round > self._last_rebind_round):
            # churn soak, step 1: ONCE per round, re-hello on a FRESH
            # connection while the old one is still registered — the
            # hub counts one rebind per virtual id, and doing it BEFORE
            # training means the connection is stable again by the time
            # this round's uploads (and the server's next broadcast or
            # resync walkback) happen, so the soak churns identities,
            # not round deadlines
            self._last_rebind_round = batch_round
            try:
                self.mux.rebind_connection()
            except (OSError, ConnectionError):
                logging.exception(
                    "muxer %d: churn re-dial failed; falling back to "
                    "drop_connection", self.mux.node_id,
                )
                self.mux.drop_connection()
        if (self.traffic is not None and not self._finished.is_set()
                and batch_round is not None
                and batch_round > self._last_traffic_round):
            # traffic-model flap: connection-granularity (one physical
            # socket per muxer), keyed by the PRIMARY virtual node so
            # the draw is independent of cohort composition.  Same
            # once-per-round guard as the churn soak — a resync
            # walkback's per-node flushes must not flap repeatedly.
            self._last_traffic_round = batch_round
            if self.traffic.decide(
                    self.mux.node_ids[0], batch_round)["rebind"]:
                get_telemetry().inc("traffic.rebinds")
                try:
                    self.mux.rebind_connection()
                except (OSError, ConnectionError):
                    logging.exception(
                        "muxer %d: traffic flap re-dial failed; falling "
                        "back to drop_connection", self.mux.node_id,
                    )
                    self.mux.drop_connection()
        if self.crash_at_round is not None and any(
            m.get(MSG_ARG_KEY_ROUND_INDEX) == self.crash_at_round
            for _, m in pending
        ):
            import os

            # black-box flush on the way down (see the per-process
            # client manager's twin of this path)
            flight.trigger("crash", reason="crash_at_round",
                           round_idx=self.crash_at_round, force=True)
            # the muxer-process twin of the client --crash-at-round
            # knob: os._exit skips cleanup, so HUNDREDS of virtual
            # clients vanish mid-protocol at once — the blast radius
            # tools/chaos_run.py's muxer_crash scenario exercises
            os._exit(137)
        if self.train_delay:
            time.sleep(self.train_delay)
        # group by payload identity: clones of one broadcast share the
        # wiretree OBJECT (decode once per group); a chaos-corrupted
        # copy is a fresh object and trains — and fails — alone
        groups: Dict[int, tuple] = {}
        order: List[int] = []
        for node, msg in pending:
            key = id(msg.get(MSG_ARG_KEY_MODEL_PARAMS))
            if key not in groups:
                groups[key] = (msg, [])
                order.append(key)
            groups[key][1].append((node, msg))
        trained = False
        for key in order:
            ref_msg, entries = groups[key]
            try:
                trained = self._train_cohort(ref_msg, entries) or trained
            except Exception:
                # one cohort's failure (undecodable sync, engine bug)
                # must not take down the other groups or the reader
                # thread: those virtual clients become stragglers this
                # round, the deadline covers them
                logging.exception(
                    "muxer %d: cohort train failed for nodes %s",
                    self.mux.node_id, [n for n, _ in entries],
                )
        if trained and self.rejoin_every_round \
                and not self._finished.is_set():
            # churn soak, step 2: this round's uploads are out — forget
            # the delta bases (fresh-process amnesia), so the next
            # delta broadcast finds a cold rejoiner and must walk it
            # back through the resync/full-model path
            self._bases.clear()

    def _reconstruct_sync(self, ref_msg: Message):  # fedlint: holds=_train_lock
        """The muxer side of the SHARED ``reconstruct_sync_model``
        (``fedavg_cross_device``): one cache for the whole co-located
        cohort (chain models are globally identical).  Returns None on
        a missing base — the caller then requests a resync for every
        virtual node in the cohort, since a rejoined muxer's whole
        cohort is behind at once."""
        variables, self._base_window = reconstruct_sync_model(
            ref_msg, self.template, self._bases, self._base_window
        )
        return variables

    def _train_cohort(self, ref_msg: Message, entries: List[tuple]) -> bool:  # fedlint: holds=_train_lock
        entries = sorted(entries, key=lambda e: e[0])
        round_idx = ref_msg.get(MSG_ARG_KEY_ROUND_INDEX)
        # open-loop arrivals: each virtual client draws its own seeded
        # traffic decision for this round — offline nodes drop out of
        # the cohort (join/leave churn; the server's deadline/async cut
        # covers them), the rest carry a per-upload delay (speed class
        # x jitter x straggler tail) applied at send time so a slow
        # device's upload ARRIVES late without stalling the cohort.
        decisions: Dict[int, dict] = {}
        if self.traffic is not None and round_idx is not None:
            tel = get_telemetry()
            kept = []
            for node, msg in entries:
                d = self.traffic.decide(node, round_idx)
                if d["offline"]:
                    tel.inc("traffic.offline_rounds")
                    continue
                if d["straggler"]:
                    tel.inc("traffic.straggler_draws")
                decisions[node] = d
                kept.append((node, msg))
            entries = kept
            if not entries:
                return False
        variables = self._reconstruct_sync(ref_msg)
        if variables is None:
            get_telemetry().inc("comm.delta_resyncs",
                                len(entries))
            logging.warning(
                "muxer %d: delta sync for round %s against unknown base "
                "— requesting full resync for %d virtual nodes",
                self.mux.node_id, round_idx, len(entries),
            )
            for node, _msg in entries:
                try:
                    request_resync(self._endpoints[node].send_message,
                                   node, round_idx)
                except OSError:
                    logging.warning(
                        "muxer %d: resync for virtual node %d lost",
                        self.mux.node_id, node,
                    )
            return False
        codec_name = ref_msg.get(MSG_ARG_KEY_CODEC) or "none"
        steps = ref_msg.get("steps_per_epoch")
        # exactly the single-client identity derivation
        # (FedAvgClientManager._on_sync): client = node - 1 on the
        # shared multicast envelope, slot defaults to client_idx
        client_ids: List[int] = []
        slots: List[int] = []
        for node, msg in entries:
            ci = msg.get(MSG_ARG_KEY_CLIENT_INDEX)
            if ci is None:
                ci = node - 1
            client_ids.append(int(ci))
            slots.append(int(msg.get("slot", ci)))
        # id-keyed per-client pack seeding: row k of this cohort pack
        # is bit-identical to client k's single-client pack
        pack_key = (steps, self.batch_size)
        if (self._pack_key != pack_key
                or any(c not in self._pack_index for c in client_ids)):
            base_ids = sorted(
                set(client_ids) | {n - 1 for n in self.mux.node_ids}
            )
            pack = pack_clients(
                self.dataset, base_ids, self.batch_size,
                steps_per_epoch=steps, seed=self.seed,
            )
            self._pack_key = pack_key
            self._pack_ids = base_ids
            self._pack_index = {c: i for i, c in enumerate(base_ids)}
            self._pack_host = (
                np.asarray(pack.x), np.asarray(pack.y),
                np.asarray(pack.mask),
                np.asarray(pack.num_samples).copy(),
            )
            self._pack_dev = (
                jnp.asarray(pack.x), jnp.asarray(pack.y),
                jnp.asarray(pack.mask),
            )
        if client_ids == self._pack_ids:
            # full-cohort steady state: the cached device arrays,
            # no per-round copy
            x, y, mask = self._pack_dev
            num_samples = self._pack_host[3]
        else:
            rows = np.asarray(
                [self._pack_index[c] for c in client_ids], np.int64
            )
            hx, hy, hm, hn = self._pack_host
            x, y, mask = (jnp.asarray(hx[rows]), jnp.asarray(hy[rows]),
                          jnp.asarray(hm[rows]))
            num_samples = hn[rows]
        # identical stream to the compiled round engine and the
        # single-client manager: key→round→train→slot.  vmapped
        # fold_in == the sequential per-client fold_in bit-for-bit
        # (threefry is exact integer math — the sim engine leans on the
        # same equivalence, algorithms/fedavg.py client_rngs).
        k_round = jax.random.fold_in(
            jax.random.PRNGKey(self.seed), round_idx
        )
        k_train = jax.random.fold_in(k_round, 0)
        rngs = jax.vmap(
            lambda s: jax.random.fold_in(k_train, s)
        )(jnp.asarray(slots, jnp.int32))
        cohort_fn = self._cohort_update
        if self._cohort_update_sharded is not None:
            dp = int(self._mesh.shape["dp"])
            if len(entries) % dp == 0:
                cohort_fn = self._cohort_update_sharded
            else:
                # a cohort the dp axis can't split evenly (chaos
                # stragglers, churn remainders) takes the replicated
                # path — correctness identical, just unsharded
                get_telemetry().inc("shard.cohort_fallbacks",
                                    reason="indivisible")
        new_stacked, metrics = cohort_fn(
            variables, x, y, mask, rngs,
        )
        # host-side views once per leaf; per-client rows slice from them
        host_leaves = [np.asarray(l)
                       for l in jax.tree_util.tree_leaves(new_stacked)]
        treedef = jax.tree_util.tree_structure(new_stacked)
        host_metrics = {k: np.asarray(v) for k, v in metrics.items()}
        for k, (node, msg) in enumerate(entries):
            new_vars = jax.tree_util.tree_unflatten(
                treedef, [l[k] for l in host_leaves]
            )
            self._upload(node, msg, new_vars, variables, round_idx,
                         codec_name, slots[k],
                         float(num_samples[k]),
                         {m: float(v[k]) for m, v in host_metrics.items()},
                         delay_s=decisions.get(node, {}).get("delay_s",
                                                             0.0))
            self.rounds_trained[node] += 1
        return True

    def _upload(self, node: int, msg: Message, new_vars, synced_vars,
                round_idx, codec_name: str, slot: int, n_samples: float,
                metrics: dict, delay_s: float = 0.0) -> None:
        from fedml_tpu.compress import wire_tree_digest
        from fedml_tpu.obs import comm_obs

        wire, raw, comp = encode_client_upload(
            codec_name, new_vars, synced_vars, self.template,
            seed=self.seed, round_idx=round_idx, slot=slot,
            ef=ef_for(self._ef, node, codec_name, self.error_feedback),
        )
        if raw is not None:
            comm_obs.record_compression(MSG_TYPE_C2S_SEND_MODEL, raw, comp)
        self._hash[node].update(wire_tree_digest(wire).encode())
        reply = Message(MSG_TYPE_C2S_SEND_MODEL, node, SERVER)
        reply.add_params(MSG_ARG_KEY_ROUND_INDEX, round_idx)
        reply.add_params(MSG_ARG_KEY_MODEL_PARAMS, wire)
        reply.add_params(MSG_ARG_KEY_NUM_SAMPLES, n_samples)
        reply.add_params(MSG_ARG_KEY_LOCAL_METRICS, metrics)
        if delay_s and delay_s > 0.0:
            # traffic-model arrival delay: the upload leaves on a timer
            # thread so a slow device's lateness never stalls the rest
            # of the vmapped cohort — the server sees an open-loop
            # arrival trickle (and, past a close, a straggler the async
            # cut discounts or the sync barrier stale-rejects)
            tel = get_telemetry()
            tel.inc("traffic.delayed_uploads")
            tel.observe("traffic.upload_delay_s", float(delay_s))
            t = threading.Timer(
                float(delay_s), self._send_upload, args=(node, reply)
            )
            t.daemon = True
            t.start()
            return
        self._send_upload(node, reply)

    def _send_upload(self, node: int, reply: Message) -> None:
        # through the per-virtual (possibly chaos-wrapped) backend:
        # per-virtual-node send fault decisions + trace origin
        try:
            self._endpoints[node].send_message(reply)
        except OSError:
            # the shared conn died mid-cohort (after the backend's own
            # bounded retries): this virtual client is a straggler this
            # round; the remaining uploads still get their attempts —
            # the reconnect path may revive the socket between them
            logging.warning(
                "muxer %d: upload for virtual node %d lost (connection "
                "error) — deadline straggler", self.mux.node_id, node,
            )

    # -- lifecycle / evidence ----------------------------------------------
    def run(self) -> None:
        """Drive the shared reader loop (returns on FINISH/stop)."""
        self.mux.run()

    @property
    def upload_digests(self) -> Dict[int, str]:
        """node id -> accumulated sha256 of every upload it sent — the
        same reproducibility probe ``FedAvgClientManager.upload_digest``
        prints, one per virtual client."""
        return {n: h.hexdigest() for n, h in self._hash.items()}
